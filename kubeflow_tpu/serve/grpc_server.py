"""gRPC data plane: the Open Inference Protocol v2 over grpcio.

The reference's model server answers REST *and* gRPC (⟨kserve:
python/kserve — ModelServer grpc servicer⟩, SURVEY.md §2.2); this is the
gRPC half, sharing the same ModelRepository/Batcher as the HTTP server so
both protocols hit one compiled model. Service stubs are hand-rolled with
`grpc.method_handlers_generic_handler` (messages come from the checked-in
protoc gencode; the grpc python codegen plugin is not in this toolchain —
the wire format is identical either way).

Tensor encoding: typed `contents` fields or packed little-endian
`raw_input_contents` (both directions), matching the public protocol.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import TYPE_CHECKING

import grpc
import ml_dtypes  # noqa: F401 — registers bfloat16 with numpy
import numpy as np

from kubeflow_tpu.serve import open_inference_pb2 as pb
from kubeflow_tpu.serve.model import Model, _v2_dtype, v2_to_numpy_dtype
from kubeflow_tpu.utils import obs
from kubeflow_tpu.utils.resilience import (Deadline, DeadlineExceeded,
                                           metrics as res_metrics)

if TYPE_CHECKING:  # avoid a cycle; server.py imports us lazily
    from kubeflow_tpu.serve.server import ModelServer

SERVICE = "inference.GRPCInferenceService"

# v2 datatype -> InferTensorContents field; the numpy<->v2 dtype mapping
# itself lives in serve/model.py so REST and gRPC can't drift. FP16/BF16
# have no typed contents field in the protocol: raw encoding only.
_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents", "INT16": "int_contents",
    "INT32": "int_contents", "INT64": "int64_contents",
    "UINT8": "uint_contents", "UINT16": "uint_contents",
    "UINT32": "uint_contents", "UINT64": "uint64_contents",
    "FP16": None, "BF16": None,
    "FP32": "fp32_contents", "FP64": "fp64_contents",
}


def tensor_to_numpy(tensor, raw: bytes | None) -> np.ndarray:
    dt = tensor.datatype.upper()
    if dt not in _CONTENTS_FIELD:
        raise ValueError(f"unsupported datatype {tensor.datatype!r}")
    np_dtype = np.dtype(v2_to_numpy_dtype(dt))
    shape = tuple(tensor.shape)
    if raw is not None and len(raw):
        return np.frombuffer(raw, dtype=np_dtype).reshape(shape)
    field = _CONTENTS_FIELD[dt]
    if field is None:
        raise ValueError(f"{dt} tensors must use raw_input_contents")
    vals = getattr(tensor.contents, field)
    return np.asarray(list(vals), dtype=np_dtype).reshape(shape)


def numpy_to_tensor(name: str, arr: np.ndarray):
    """(InferOutputTensor, raw bytes). Outputs use raw_output_contents —
    one memcpy instead of per-element typed-field churn on the hot path,
    and BF16/FP16 keep their dtype instead of upcasting."""
    arr = np.asarray(arr)
    dt = _v2_dtype(str(arr.dtype))
    if v2_to_numpy_dtype(dt) != str(arr.dtype):
        # dtype outside the protocol (e.g. complex): ship as FP32 rather
        # than mislabeling raw bytes via _v2_dtype's FP32 fallback.
        arr = arr.astype(np.float32)
        dt = "FP32"
    out = pb.ModelInferResponse.InferOutputTensor(
        name=name, datatype=dt, shape=list(arr.shape))
    return out, np.ascontiguousarray(arr).tobytes()


class InferenceServicer:
    """The five open-inference RPCs over a ModelServer's repository."""

    def __init__(self, server: "ModelServer"):
        self.server = server
        self.repo = server.repo

    def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=True)

    def ServerReady(self, request, context):
        # Shares ModelServer.readiness() with the HTTP probe — ONE
        # readiness rule, so the two surfaces cannot drift.
        return pb.ServerReadyResponse(ready=self.server.readiness()[0])

    def _model(self, name, context):
        try:
            return self.repo.get(name)
        except Exception:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {name!r} not found")

    def ModelReady(self, request, context):
        model = self._model(request.name, context)
        return pb.ModelReadyResponse(ready=bool(model.ready))

    def ModelMetadata(self, request, context):
        model = self._model(request.name, context)
        md = model.metadata()
        resp = pb.ModelMetadataResponse(
            name=md.get("name", request.name), versions=["1"],
            platform=md.get("platform", "kubeflow-tpu"))
        for t in md.get("inputs", []):
            resp.inputs.add(name=t["name"], datatype=t["datatype"],
                            shape=[int(s) for s in t["shape"]])
        for t in md.get("outputs", []):
            resp.outputs.add(name=t["name"], datatype=t["datatype"],
                             shape=[int(s) for s in t["shape"]])
        return resp

    def Metrics(self, request: bytes, context) -> bytes:
        """Prometheus text over gRPC (`/tpk.Metrics/Prometheus`): the
        SAME rendering the HTTP /metrics endpoint serves — engine
        counters (tpk_decode_dispatch_total, host-stall, admit-overlap,
        prefix hits, paged-KV zero-copy/CoW counters and the
        tpk_kv_blocks_free/used pool gauges admission decides by),
        batcher/admission gauges, resilience counters —
        so a gRPC-only deployment still gets the full scrape. Raw-bytes
        payload via identity (de)serializers: the message needs no
        schema and the checked-in protoc gencode stays untouched."""
        return self.server.prometheus_text().encode()

    def ModelInfer(self, request, context):
        # Trace identity, shared with the HTTP plane: honor the caller's
        # x-request-id metadata, assign one otherwise, echo it back in
        # the trailing metadata — gRPC and HTTP requests land in the
        # SAME span ring with the same span names.
        rid = next((v for k, v in (context.invocation_metadata() or ())
                    if k.lower() == "x-request-id"), None)
        trace_id = obs.sanitize_trace_id(rid)
        context.set_trailing_metadata((("x-request-id", trace_id),))
        if getattr(self.server, "draining", False):
            # Scale-in drain: same contract as the HTTP plane's 503 +
            # DRAINING_HEADER — UNAVAILABLE (not RESOURCE_EXHAUSTED,
            # which means overload backpressure) with "draining" in the
            # details so the router retries on a surviving replica.
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          "replica draining")
        # The gRPC data plane sits behind the SAME admission gate as the
        # HTTP handlers — it must not be an unbounded side door around
        # --max-inflight. RESOURCE_EXHAUSTED is the canonical overload
        # status (the HTTP 503 + Retry-After equivalent).
        adm = self.server.admission
        with obs.span("serve.admit", trace_id=trace_id,
                      path="grpc.ModelInfer") as sp:
            shed = adm is not None and not adm.try_acquire(
                component="serve_grpc")
            sp.set(admitted=not shed)
        if shed:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          "server overloaded: admission queue full")
        # An expired request's work may still be computing when the
        # abort unwinds: _infer parks the claimed future here so the
        # admission slot rides it to true completion (same rule as the
        # HTTP path's _slot_rides_with) — max_inflight bounds concurrent
        # WORK, not just concurrent waiting callers.
        ride = []
        try:
            return self._infer(request, context, ride, trace_id)
        finally:
            if adm is not None:
                if ride:
                    ride[0].add_done_callback(lambda _f: adm.release())
                else:
                    adm.release()

    def _infer(self, request, context, ride, trace_id=""):
        name = request.model_name
        model = self._model(name, context)
        if not model.ready:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"model {name!r} not ready")
        nraw = len(request.raw_input_contents)
        if nraw and nraw != len(request.inputs):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "raw_input_contents is all-or-nothing: one entry per input")
        try:
            inputs = []
            for i, tensor in enumerate(request.inputs):
                raw = request.raw_input_contents[i] if nraw else None
                inputs.append(tensor_to_numpy(tensor, raw))
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        params = {k: _param_value(v)
                  for k, v in request.parameters.items()}
        # Protocol parity with the HTTP V2 handler: a custom preprocess
        # sees the same v2-shaped body either way.
        if type(model).preprocess is not Model.preprocess:
            body = model.preprocess({
                "id": request.id, "parameters": params,
                "inputs": [{
                    "name": t.name, "datatype": t.datatype,
                    "shape": list(t.shape), "data": arr,
                } for t, arr in zip(request.inputs, inputs)]})
            inputs = [np.asarray(
                t["data"],
                dtype=v2_to_numpy_dtype(t.get("datatype", "FP32"))
            ).reshape(t["shape"]) for t in body.get("inputs", [])]
            if not inputs:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "preprocess returned no inputs")
        # gRPC's native deadline (client-set, in context) maps onto the
        # shared Deadline clock, same as the HTTP timeout header: an
        # expired request frees its batch row instead of computing a
        # result nobody will read.
        rem = context.time_remaining()
        deadline = Deadline(rem) if rem is not None else None
        fut = None
        t0 = time.monotonic()
        try:
            if getattr(model, "wants_raw_payload", False):
                # Graph/raw-payload models take the whole payload dict
                # and bypass the batcher, but still run bounded on the
                # server's worker pool (same as the HTTP handlers).
                payload = dict(params)
                payload["instances"] = inputs[0]
                fut = self.server.executor.submit(model.predict, payload)
                out = fut.result(
                    timeout=deadline.bound(120.0) if deadline else 120)
                outs = [out.get("instances")
                        if isinstance(out, dict) else out]
            else:
                fut = self.server.repo.batcher(name).submit(
                    inputs, deadline=deadline, trace_id=trace_id)
                outs = fut.result(
                    timeout=deadline.bound(120.0) if deadline else 120)
            outs = model.postprocess(outs)
        except (DeadlineExceeded, futures.TimeoutError) as e:
            # The caller is getting an error either way: try to abandon
            # the queued work (cancel only lands pre-claim); if it is
            # already computing, park it so the admission slot rides it
            # to completion.
            if fut is not None and not fut.cancel():
                ride.append(fut)
            if (isinstance(e, DeadlineExceeded)
                    or (deadline is not None and deadline.expired())):
                # This surface aborts at most once per request and the
                # inner layers never count — exactly one increment.
                res_metrics.inc("tpk_deadline_expired_total",
                                component="serve_grpc")
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                              f"request deadline exceeded "
                              f"({type(e).__name__})")
            # A work-raised timeout with budget left (or no deadline at
            # all — on py3.11+ futures.TimeoutError IS builtin
            # TimeoutError) is a server fault, not an expired deadline.
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")
        except Exception as e:  # surfaced as a proper gRPC status
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        self.server.observe(name, int(np.asarray(inputs[0]).shape[0]),
                            time.monotonic() - t0)
        resp = pb.ModelInferResponse(model_name=name, id=request.id)
        # All outputs raw (positional, one entry per tensor) — the
        # protocol's all-or-nothing rule holds by construction.
        for j, arr in enumerate(outs):
            tensor, raw_bytes = numpy_to_tensor(f"output_{j}",
                                                np.asarray(arr))
            resp.outputs.append(tensor)
            resp.raw_output_contents.append(raw_bytes)
        return resp


def _param_value(p):
    """InferParameter oneof -> python value."""
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else None


def _unary(fn, req_cls, resp_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString)


def build_grpc_server(server: "ModelServer", port: int = 0,
                      max_workers: int = 8):
    """Returns (grpc.Server, bound_port). Serves on 127.0.0.1."""
    servicer = InferenceServicer(server)
    handlers = grpc.method_handlers_generic_handler(SERVICE, {
        "ServerLive": _unary(servicer.ServerLive, pb.ServerLiveRequest,
                             pb.ServerLiveResponse),
        "ServerReady": _unary(servicer.ServerReady, pb.ServerReadyRequest,
                              pb.ServerReadyResponse),
        "ModelReady": _unary(servicer.ModelReady, pb.ModelReadyRequest,
                             pb.ModelReadyResponse),
        "ModelMetadata": _unary(servicer.ModelMetadata,
                                pb.ModelMetadataRequest,
                                pb.ModelMetadataResponse),
        "ModelInfer": _unary(servicer.ModelInfer, pb.ModelInferRequest,
                             pb.ModelInferResponse),
    })
    metrics_handlers = grpc.method_handlers_generic_handler(
        "tpk.Metrics", {
            "Prometheus": grpc.unary_unary_rpc_method_handler(
                servicer.Metrics,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b),
        })
    gserver = grpc.server(futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix="tpk-grpc"))
    gserver.add_generic_rpc_handlers((handlers, metrics_handlers))
    bound = gserver.add_insecure_port(f"127.0.0.1:{port}")
    if bound == 0:
        # Fail loudly: advertising a dead port would leave the replica
        # Ready (HTTP probe passes) while gRPC clients get refused forever;
        # a crash here routes through the controller's relaunch instead.
        raise RuntimeError(f"cannot bind gRPC port {port}")
    return gserver, bound


class InferenceClient:
    """Minimal typed client over the same generic-handler trick — what the
    reference's InferenceGRPCClient provides (tests + SDK use)."""

    def __init__(self, target: str):
        self._channel = grpc.insecure_channel(target)

    def _call(self, method, req, resp_cls, metadata=None):
        rpc = self._channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return rpc(req, metadata=metadata)

    def server_live(self) -> bool:
        return self._call("ServerLive", pb.ServerLiveRequest(),
                          pb.ServerLiveResponse).live

    def server_ready(self) -> bool:
        """The gRPC readiness probe — same shared rule as HTTP
        /v2/health/ready (degrades while shedding OR draining)."""
        return self._call("ServerReady", pb.ServerReadyRequest(),
                          pb.ServerReadyResponse).ready

    def model_ready(self, name: str) -> bool:
        return self._call("ModelReady", pb.ModelReadyRequest(name=name),
                          pb.ModelReadyResponse).ready

    def model_metadata(self, name: str):
        return self._call("ModelMetadata",
                          pb.ModelMetadataRequest(name=name),
                          pb.ModelMetadataResponse)

    def metrics(self, timeout: float | None = None) -> str:
        """The server's Prometheus text over the gRPC plane (same
        rendering as HTTP /metrics — engine pipelining counters
        included). `timeout` bounds the RPC — the fleet poller's scrape
        must never hang on an unreachable replica."""
        rpc = self._channel.unary_unary(
            "/tpk.Metrics/Prometheus",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        return rpc(b"", timeout=timeout).decode()

    def infer(self, name: str, arrays: list[np.ndarray], *,
              raw: bool = False,
              request_id: str | None = None) -> list[np.ndarray]:
        """`request_id` rides as x-request-id metadata — the gRPC half
        of the trace-id contract (the server echoes it in the trailing
        metadata and stamps it on the request's spans)."""
        arrays = [np.asarray(a) for a in arrays]
        # raw_input_contents is all-or-nothing; FP16/BF16 force raw.
        use_raw = raw or any(
            _CONTENTS_FIELD.get(_v2_dtype(str(a.dtype))) is None
            for a in arrays)
        req = pb.ModelInferRequest(model_name=name)
        for i, arr in enumerate(arrays):
            dt = _v2_dtype(str(arr.dtype))
            t = req.inputs.add(name=f"input_{i}", datatype=dt,
                               shape=list(arr.shape))
            if use_raw:
                req.raw_input_contents.append(
                    np.ascontiguousarray(arr).tobytes())
            else:
                getattr(t.contents, _CONTENTS_FIELD[dt]).extend(
                    arr.reshape(-1).tolist())
        resp = self._call("ModelInfer", req, pb.ModelInferResponse,
                          metadata=(("x-request-id", request_id),)
                          if request_id else None)
        outs = []
        for j, t in enumerate(resp.outputs):
            raw_out = (resp.raw_output_contents[j]
                       if j < len(resp.raw_output_contents) else None)
            outs.append(tensor_to_numpy(t, raw_out))
        return outs

    def close(self):
        self._channel.close()
