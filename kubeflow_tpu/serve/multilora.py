"""Multi-LoRA serving: N PEFT adapters stacked over one base model,
selected per request — vLLM's multi-LoRA capability, XLA-shaped.

Instead of swapping adapter weights per request (a host round-trip and a
recompile hazard), all adapters live on device as STACKED tensors
[N+1, ...] with entry 0 all-zeros ("base", no adapter); every batch row
gathers its own adapter by index inside the same compiled program
(models/llama.py `_multi_lora_delta`), so one decode dispatch serves a
mixed batch of adapters. Ranks may differ per adapter — narrower ones
zero-pad to the widest rank (zero rows contribute nothing); alpha/r is
folded into the stacked B so the model applies no further scaling. An
adapter that doesn't target some module contributes zeros there.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

_GROUPS = {"q_proj": "attn", "v_proj": "attn", "gate_proj": "mlp",
           "up_proj": "mlp", "down_proj": "mlp"}


def build_adapter_stacks(adapter_dirs: dict[str, str], cfg
                         ) -> tuple[dict, dict]:
    """{name: PEFT adapter dir} + base LlamaConfig -> (stacks, ids).

    stacks: {module: {"a": [L, N+1, in, rmax], "b": [L, N+1, rmax, *out]}}
    ready for `Llama(..., adapter=stacks, adapter_ids=...)`;
    ids: {name: index >= 1} (0 is the implicit no-adapter base)."""
    from kubeflow_tpu.models.peft_import import load_peft_adapter

    if not adapter_dirs:
        raise ValueError("adapter_dirs must name at least one adapter")
    names = sorted(adapter_dirs)
    loaded = []
    for n in names:
        acfg, leaves = load_peft_adapter(adapter_dirs[n], cfg)
        loaded.append((n, acfg, leaves))
    rmax = max(acfg.lora_rank for _, acfg, _ in loaded)
    L = cfg.num_layers
    pd = np.dtype(jnp.dtype(cfg.param_dtype).name)

    out_shapes = {
        "q_proj": (cfg.num_heads, cfg.head_dim),
        "v_proj": (cfg.num_kv_heads, cfg.head_dim),
        "gate_proj": (cfg.intermediate_size,),
        "up_proj": (cfg.intermediate_size,),
        "down_proj": (cfg.hidden_size,),
    }
    in_dims = {
        "q_proj": cfg.hidden_size, "v_proj": cfg.hidden_size,
        "gate_proj": cfg.hidden_size, "up_proj": cfg.hidden_size,
        "down_proj": cfg.intermediate_size,
    }
    modules = sorted({
        m for _, _, leaves in loaded
        for (_, _, leaf) in leaves
        for m in [leaf[: -len("_lora_a")]]
        if leaf.endswith("_lora_a")})

    stacks: dict[str, Any] = {}
    for m in modules:
        group = _GROUPS[m]
        akey = ("layers", group, f"{m}_lora_a")
        bkey = ("layers", group, f"{m}_lora_b")
        out = out_shapes[m]
        a_entries = [np.zeros((L, in_dims[m], rmax), pd)]
        b_entries = [np.zeros((L, rmax, *out), pd)]
        for _, acfg, leaves in loaded:
            r = acfg.lora_rank
            a = np.zeros((L, in_dims[m], rmax), pd)
            b = np.zeros((L, rmax, *out), pd)
            if akey in leaves:
                a[:, :, :r] = np.asarray(leaves[akey], pd)
                # Fold alpha/r into B: the per-row delta is then just
                # (x @ a) @ b, uniform across mixed-alpha adapters.
                b[:, :r] = (np.asarray(leaves[bkey], pd)
                            * (acfg.lora_alpha / r))
            a_entries.append(a)
            b_entries.append(b)
        stacks[m] = {
            # Stack on axis 1: the layer scan consumes axis 0.
            "a": jnp.asarray(np.stack(a_entries, axis=1)),
            "b": jnp.asarray(np.stack(b_entries, axis=1)),
        }
    ids = {n: i + 1 for i, n in enumerate(names)}
    return stacks, ids
