"""Request coalescing batcher — the TPU-side answer to KServe's agent batcher.

The reference batches in a Go sidecar in front of the model container
(⟨kserve: pkg/agent — batcher⟩, SURVEY.md §2.2). On TPU the batcher must sit
*inside* the server, because its whole point is MXU utilization: many small
concurrent requests become one padded device call on an AOT executable
(see model.JAXModel). Policy matches the reference's: flush at
`max_batch_size` or after `max_latency_ms`, whichever first.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Sequence

import numpy as np

from kubeflow_tpu.utils import faults, obs
from kubeflow_tpu.utils.resilience import Deadline, DeadlineExceeded

_FP_PREDICT = faults.register_point(
    "serve.predict", "batcher worker, before the coalesced model call; "
                     "ctx: batch (total examples)")


class _Item:
    __slots__ = ("inputs", "future", "n", "deadline", "t_enq", "t_perf",
                 "trace")

    def __init__(self, inputs: Sequence[np.ndarray],
                 deadline: Deadline | None = None, trace_id: str = ""):
        self.inputs = [np.asarray(x) for x in inputs]
        self.n = self.inputs[0].shape[0]
        self.deadline = deadline
        self.t_enq = time.monotonic()
        self.t_perf = time.perf_counter()  # span clock (obs epoch)
        self.trace = trace_id
        self.future: Future = Future()

    def deliver(self, result=None, exc: BaseException | None = None) -> None:
        """Complete the future, tolerating a caller that already gave up:
        an expired server-side await (asyncio.wait_for) CANCELS the
        wrapped future, and a plain set_result after that would raise
        InvalidStateError out of the worker thread — killing the batcher
        for every other request."""
        try:
            if exc is not None:
                self.future.set_exception(exc)
            else:
                self.future.set_result(result)
        except InvalidStateError:
            pass  # caller abandoned (deadline/cancel): result is moot

    def expire_if_due(self) -> bool:
        """Resolve the future with DeadlineExceeded when the request's
        budget is gone — an expired item must not occupy device batch
        rows its caller will never read (the 504 already went out).
        A caller-side cancel counts as expiry too. No metrics here: the
        serving surface that returns the error (HTTP/gRPC) counts each
        expired request exactly once."""
        if self.future.cancelled():
            return True
        if self.deadline is not None and self.deadline.expired():
            self.deliver(exc=DeadlineExceeded(
                "request deadline expired in the admission queue"))
            return True
        return False

    def signature(self) -> tuple:
        """Items only coalesce when per-example shapes and dtypes agree —
        one malformed request must not poison a batch of valid ones."""
        return tuple((a.shape[1:], str(a.dtype)) for a in self.inputs)


class Batcher:
    """Coalesces concurrent predict calls into single model calls.

    `predict_fn` takes a list of stacked input arrays and returns a list of
    output arrays whose leading dim equals the total batch.
    """

    def __init__(self, predict_fn: Callable[[list[np.ndarray]], list],
                 max_batch_size: int = 32, max_latency_ms: float = 5.0):
        self._predict = predict_fn
        self.max_batch_size = max_batch_size
        self.max_latency_s = max_latency_ms / 1e3
        self._q: queue.Queue[_Item | None] = queue.Queue()
        self._pending: _Item | None = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpk-batcher")
        self._closed = False
        self._lock = threading.Lock()
        # guarded-by: _lock
        self.stats = {"batches": 0, "items": 0, "examples": 0}
        self._thread.start()

    def submit(self, inputs: Sequence[np.ndarray],
               deadline: Deadline | None = None,
               trace_id: str = "") -> Future:
        if self._closed:
            raise RuntimeError("batcher is closed")
        item = _Item(inputs, deadline, trace_id)
        if item.expire_if_due():
            return item.future
        if item.n > self.max_batch_size:
            # Oversized requests bypass coalescing; JAXModel chunks them.
            if item.future.set_running_or_notify_cancel():
                try:
                    faults.fire(_FP_PREDICT, batch=item.n)
                    item.deliver(self._predict(item.inputs))
                except BaseException as e:  # noqa: BLE001 - to caller
                    item.deliver(exc=e)
            return item.future
        self._q.put(item)
        return item.future

    def predict(self, inputs: Sequence[np.ndarray],
                timeout: float | None = 30.0) -> list:
        return self.submit(inputs).result(timeout)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._q.put(None)
            self._thread.join(timeout=5.0)

    # -- worker -------------------------------------------------------------

    # tpk-hot: batcher-worker
    def _gather(self) -> list[_Item] | None:
        """Blocks for the first item, then drains until size limit or until
        max_latency has elapsed since the FIRST item was ENQUEUED (not
        since this gather started — a fixed deadline, not a per-item idle
        timeout: trickling arrivals must not extend it, and time the head
        already spent queued behind an in-flight batch counts).

        The enqueue-anchored deadline is the p99 fix (ISSUE 3 satellite,
        PROFILE.md §5): waiters that arrived while the previous batch was
        executing have typically burned their whole window already — the
        old gather made them wait a FRESH window (a full extra batch
        generation) before flushing. Now an expired window flushes
        immediately, after sweeping every already-queued compatible
        waiter into the same device call."""
        first = self._pending or self._q.get()
        self._pending = None
        while first is not None and first.expire_if_due():
            # Expired while queued: its caller already got the 504 —
            # don't spend device batch rows on it.
            first = self._q.get()
        if first is None:
            return None
        batch, total = [first], first.n
        sig = first.signature()
        deadline = first.t_enq + self.max_latency_s
        while total < self.max_batch_size:
            remaining = deadline - time.monotonic()
            try:
                # Window expired: no fresh wait, but DO sweep compatible
                # waiters already in the queue into this flush.
                nxt = (self._q.get_nowait() if remaining <= 0
                       else self._q.get(timeout=remaining))
            except queue.Empty:
                break
            if nxt is None:
                self._q.put(None)  # re-post sentinel for the outer loop
                break
            if nxt.expire_if_due():
                continue
            if nxt.signature() != sig or total + nxt.n > self.max_batch_size:
                self._pending = nxt  # incompatible/overflow: next batch's head
                break
            batch.append(nxt)
            total += nxt.n
        return batch

    # tpk-hot: batcher-worker
    def _loop(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return
            # Claim each item (PENDING -> RUNNING, the concurrent.futures
            # protocol): a caller-side cancel can no longer race the
            # dispatch, so "cancelled" reliably means "never computed" and
            # a cancelled-after-claim slot rides the batch to completion.
            batch = [i for i in batch
                     if i.future.set_running_or_notify_cancel()]
            if not batch:
                continue
            # One batch-gather span per item (enqueue → dispatch: the
            # time this request spent waiting to coalesce), each
            # carrying ITS request id, so a slow request's queue share
            # is separable from its compute share in /debug/trace.
            t_flush = time.perf_counter()
            tracer = obs.get_tracer()
            if tracer.enabled:
                for item in batch:
                    tracer.record("serve.batch_gather", item.t_perf,
                                  t_flush, item.trace,
                                  batch=len(batch), examples=item.n)
            try:
                faults.fire(_FP_PREDICT, batch=sum(i.n for i in batch))
                stacked = [np.concatenate(parts)
                           for parts in zip(*(i.inputs for i in batch))]
                outs = self._predict(stacked)
            except BaseException as e:  # noqa: BLE001 - deliver to callers
                for item in batch:
                    item.deliver(exc=e)
                continue
            if tracer.enabled:
                # The shared model call, one span PER rider (same
                # interval, each request's own trace id): a request's
                # timeline stays complete even when it shared the batch.
                t1 = time.perf_counter()
                for item in batch:
                    tracer.record("serve.predict", t_flush, t1, item.trace,
                                  items=len(batch),
                                  examples=sum(i.n for i in batch))
            with self._lock:
                self.stats["batches"] += 1
                self.stats["items"] += len(batch)
                self.stats["examples"] += sum(i.n for i in batch)
            off = 0
            for item in batch:
                item.deliver([o[off:off + item.n] for o in outs])
                off += item.n
