"""Block-paged KV memory management for the generation engine.

The flat engine (ISSUE 3 lineage) holds one slot-contiguous cache
`[L, B_slots, max_len, KH, D]`: every request owns a `max_len`-sized row
for its whole lifetime regardless of actual length. Slots are the proven
throughput lever (1→4 slots scaled decode 78→296 tok/s, SERVEBENCH.json)
but each slot charges worst-case HBM, so mixed-length traffic caps out
long before the arithmetic does.

This module is the host half of the PagedAttention-style answer (the
vLLM design the serve module header cites): the KV tensor becomes a pool
of fixed-size blocks `[L, n_blocks, block_size, KH, D]`, each request
owns a *block table* (a host-side list of block ids), and the jitted
step gathers the table into a contiguous view / scatters it back
(serve/generation.py `build_engine_fns` paged fns). Everything here is
plain-Python bookkeeping mutated only by the engine worker thread —
block allocation sits at admit/retire, off the decode critical path, so
pipelined dispatch (`pipeline_depth > 1`) needs no new host syncs.

Sharing model (copy-on-write prefix reuse):

  * block id 0 is the reserved NULL block — the pad target for table
    entries past a request's allocation. It is written with garbage by
    padded scatters and never read as meaningful data (absolute-position
    masking hides every row past a request's write index).
  * a block referenced by more than one table (or by the prefix cache)
    is IMMUTABLE in value: only fully-committed, block-aligned prefix
    blocks are ever shared. A prefix-cache hit maps those ids into the
    new request's table with a refcount bump — zero-copy.
  * the partially-filled tail block of a stored prefix is never shared
    into a new table: the hit forks it (fresh block, committed rows
    copied via the admission fragment) because the new request will
    append into that block — the one copy CoW pays.

Quantized pools (`kv_quant=int8|fp8`, ISSUE 19) change NOTHING here:
the pool grows parallel per-row-per-head scale planes (`ks`/`vs`,
`[L, n_blocks, block_size, KH]`) addressed by the SAME block ids, so
one table entry names a value block and its scale block together.
Allocation, refcounts, CoW forks, and the NULL block are identical —
a shared quantized prefix shares its scales by construction, and a
tail fork copies them through the same admission-fragment scatter.
Blocks stay opaque above the engine; this module never sees a dtype.
"""

from __future__ import annotations


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold `tokens` cache rows (ceil division)."""
    if tokens <= 0:
        return 0
    return -(-int(tokens) // int(block_size))


class BlockAllocator:
    """Fixed-pool block allocator with refcounted sharing.

    Ids are indices into the device pool's block axis; id 0 is reserved
    (NULL). `alloc` is all-or-nothing — a request either gets its whole
    allocation or nothing, so admission can never strand a half-admitted
    request holding blocks it cannot use. Free ids are handed out in
    LIFO order: recently freed blocks are re-written first, keeping the
    pool's cold tail untouched (and making use-after-free bugs loud in
    tests, since stale readers see fresh writes immediately)."""

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        #: usable ids are 1..n_blocks (0 is NULL); the device pool is
        #: therefore n_blocks + 1 blocks long.
        self._free: list[int] = list(range(self.n_blocks, 0, -1))
        self._ref: dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Take `n` blocks (refcount 1 each), or None if the pool can't
        cover the whole request right now (caller queues/sheds)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._ref[b] = 1
        return ids

    def incref(self, ids) -> None:
        """Add one reference to each id (prefix-cache store / zero-copy
        hit). Double-incref of the same id in one call is legal — each
        occurrence counts."""
        for b in ids:
            if b not in self._ref:
                raise ValueError(f"incref of unallocated block {b}")
            self._ref[b] += 1

    def decref(self, ids) -> int:
        """Drop one reference per id; blocks reaching zero return to the
        free list. Returns how many blocks were actually freed."""
        freed = 0
        for b in ids:
            c = self._ref.get(b)
            if c is None:
                raise ValueError(f"decref of unallocated block {b}")
            if c == 1:
                del self._ref[b]
                self._free.append(b)
                freed += 1
            else:
                self._ref[b] = c - 1
        return freed

    def refcount(self, block_id: int) -> int:
        return self._ref.get(block_id, 0)
