"""Serving model abstraction — KServe's `kserve.Model` contract, TPU-first.

The reference model server (⟨kserve: python/kserve — Model, ModelServer⟩,
SURVEY.md §2.2/§3.3) defines load/preprocess/predict/postprocess with the
GPU framework hidden behind `predict`. Here the TPU path is explicit:
`JAXModel` AOT-compiles the forward for a fixed set of batch buckets at
load time (`jit(...).lower(...).compile()`), so the serving hot path never
hits a trace/compile and every request lands on a static-shaped MXU-friendly
executable. Requests are padded up to the nearest bucket.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class Model:
    """Lifecycle + request hooks. Subclasses override load()/predict().

    Mirrors the reference's kserve.Model surface: `ready` gates the
    readiness probes, preprocess/postprocess wrap the hot predict call.
    """

    def __init__(self, name: str):
        self.name = name
        self.ready = False
        self.load_time_s: float | None = None

    def load(self) -> bool:
        self.ready = True
        return self.ready

    def unload(self) -> None:
        self.ready = False

    def preprocess(self, payload: Any) -> Any:
        return payload

    def predict(self, inputs: Any) -> Any:
        raise NotImplementedError

    def postprocess(self, outputs: Any) -> Any:
        return outputs

    def explain(self, instances: Any) -> Any:
        """v1 `:explain` hook (serve/explain.py attaches implementations)."""
        raise NotImplementedError(
            f"model {self.name!r} has no explainer configured")

    def __call__(self, payload: Any) -> Any:
        return self.postprocess(self.predict(self.preprocess(payload)))

    # Metadata for the v2 protocol's GET /v2/models/{name}.
    def metadata(self) -> dict:
        return {"name": self.name, "platform": "jax-tpu",
                "inputs": [], "outputs": []}


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class JAXModel(Model):
    """A jitted forward over fixed params, AOT-compiled per batch bucket.

    `apply_fn(params, *inputs)` must be shape-polymorphic over the leading
    batch dim only; everything else is static. `input_spec` gives the
    per-example shape/dtype of each positional input.
    """

    def __init__(self, name: str, apply_fn, params: Any,
                 input_spec: Sequence[tuple[tuple[int, ...], str]],
                 batch_buckets: Sequence[int] = (1, 2, 4, 8, 16, 32),
                 warm_buckets: Sequence[int] = (1, 8)):
        super().__init__(name)
        self._apply = apply_fn
        self._params = params
        self.input_spec = [(tuple(s), str(d)) for s, d in input_spec]
        self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
        self.warm_buckets = [b for b in warm_buckets
                             if b in self.batch_buckets]
        self._compiled: dict[int, Any] = {}
        self._lock = threading.Lock()
        self.explainer = None  # serve/explain.py; set via attach_explainer
        self.stats = {"requests": 0, "examples": 0, "padded_examples": 0,
                      "compiles": 0, "predict_s": 0.0}

    def attach_explainer(self, explainer) -> None:
        self.explainer = explainer

    def apply_and_params(self):
        """(apply_fn, params) for explainers that differentiate through
        the model (integrated gradients) rather than calling predict."""
        return self._apply, self._params

    def explain(self, instances) -> Any:
        if self.explainer is None:
            raise NotImplementedError(
                f"model {self.name!r} has no explainer configured")
        if not self.ready:
            raise RuntimeError(f"model {self.name} is not loaded")
        return self.explainer.explain(self, instances)

    # -- compilation --------------------------------------------------------

    def _abstract_inputs(self, batch: int):
        return [jax.ShapeDtypeStruct((batch, *shape), jnp.dtype(dtype))
                for shape, dtype in self.input_spec]

    def _executable(self, batch: int):
        """AOT executable for one bucket; compiled once, cached forever."""
        exe = self._compiled.get(batch)
        if exe is not None:
            return exe
        with self._lock:
            exe = self._compiled.get(batch)
            if exe is None:
                args = self._abstract_inputs(batch)
                exe = (jax.jit(self._apply)
                       .lower(self._params, *args).compile())
                self._compiled[batch] = exe
                self.stats["compiles"] += 1
        return exe

    def load(self) -> bool:
        t0 = time.monotonic()
        self._params = jax.device_put(self._params)
        for b in self.warm_buckets:
            self._executable(b)
        self.load_time_s = time.monotonic() - t0
        self.ready = True
        return True

    def unload(self) -> None:
        # Keep params: unload/load through the repository API must be able
        # to round-trip for models registered without a model_dir. Only the
        # compiled executables (the large device allocations) are dropped.
        self.ready = False
        self._compiled.clear()

    # -- hot path -----------------------------------------------------------

    def predict(self, inputs: Sequence[np.ndarray]) -> list[np.ndarray]:
        """Pads the batch up to the nearest bucket, runs the AOT executable,
        and strips the padding. Returns a list of output arrays."""
        if not self.ready:
            raise RuntimeError(f"model {self.name} is not loaded")
        arrays = [np.asarray(x, dtype=np.dtype(d))
                  for x, (_, d) in zip(inputs, self.input_spec)]
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("inputs disagree on batch size")
        bucket = _next_bucket(n, self.batch_buckets)
        t0 = time.monotonic()
        if n > bucket:  # above the largest bucket: split into max-size chunks
            outs = [self.predict([a[i:i + bucket] for a in arrays])
                    for i in range(0, n, bucket)]
            return [np.concatenate(parts) for parts in zip(*outs)]
        if n < bucket:
            arrays = [np.concatenate(
                [a, np.repeat(a[-1:], bucket - n, axis=0)]) for a in arrays]
        exe = self._executable(bucket)
        out = exe(self._params, *arrays)
        leaves = [np.asarray(x)[:n] for x in jax.tree.leaves(out)]
        self.stats["requests"] += 1
        self.stats["examples"] += n
        self.stats["padded_examples"] += bucket - n
        self.stats["predict_s"] += time.monotonic() - t0
        return leaves

    def metadata(self) -> dict:
        return {
            "name": self.name, "platform": "jax-tpu",
            "inputs": [{"name": f"input_{i}", "shape": [-1, *shape],
                        "datatype": _v2_dtype(dtype)}
                       for i, (shape, dtype) in enumerate(self.input_spec)],
            "outputs": [{"name": "output_0", "shape": [-1],
                         "datatype": "FP32"}],
            "batch_buckets": self.batch_buckets,
        }


_V2_DTYPES = {
    "float32": "FP32", "float16": "FP16", "bfloat16": "BF16",
    "float64": "FP64", "int32": "INT32", "int64": "INT64",
    "int8": "INT8", "int16": "INT16", "uint8": "UINT8",
    "uint16": "UINT16", "uint32": "UINT32", "uint64": "UINT64",
    "bool": "BOOL",
}
_NP_DTYPES = {v: k for k, v in _V2_DTYPES.items()}


def _v2_dtype(np_dtype: str) -> str:
    return _V2_DTYPES.get(str(np_dtype), "FP32")


def v2_to_numpy_dtype(v2: str) -> str:
    try:
        return _NP_DTYPES[v2.upper()]
    except KeyError:
        raise ValueError(f"unsupported v2 datatype {v2!r}") from None
