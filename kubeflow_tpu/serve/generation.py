"""Generative LLM serving: KV-cache decode, sampling, continuous batching.

The reference's flagship LLM runtime is the vLLM-backed huggingfaceserver
(⟨kserve: python/huggingfaceserver⟩, SURVEY.md §2.2/§3.3 rebuild note).
Its design — paged KV blocks, per-step GPU kernel launches, token-level
continuous batching — does not map to XLA. The TPU-native shape:

  * **Functional cache**: one global slot-batched cache [L, B_slots, T, KH,
    D] carried through pure jitted fns (models/llama.py `init_cache`);
    stale slot content needs no eviction — absolute-position masking hides
    anything past a slot's write index.
  * **AOT everything**: prefill compiled per prompt-length bucket,
    decode compiled once — the hot path never traces.
  * **Chunked decode**: one dispatch runs `lax.scan` over K decode steps
    with on-device sampling, returning K tokens/slot. On the axon tunnel a
    host sync costs ~66 ms (PROFILE.md §1), so per-token sync decoding
    would cap at ~15 tok/s; chunking amortizes the latency K×.
  * **Continuous batching at chunk boundaries**: finished slots are
    re-admitted (prefill → cache insert at the slot index) between decode
    dispatches — the scheduling granularity is the chunk, not the token,
    which is the right trade under compiled static shapes.

Sampling: greedy (temperature 0), temperature, top-k, and nucleus
(top-p) sampling — all per-slot and on device.

Speculative decoding (`draft=`): a small draft model proposes gamma
tokens per step and the target verifies them in ONE forward — greedy
output stays token-identical to vanilla decode (the first mismatch emits
the target's own argmax), and plain-temperature requests use the
standard rejection scheme whose emitted marginal IS the tempered target
distribution (`spec_acceptance`) — the speedup is free of quality loss
either way; see `build_spec_decode`. Top-k/top-p requests fall back to
plain chunked decode.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.serve.kv_transfer import (HostKVTier, ShipmentError,
                                            pack_shipment,
                                            unpack_shipment)
from kubeflow_tpu.serve.model import Model
from kubeflow_tpu.serve.paging import BlockAllocator, blocks_for
from kubeflow_tpu.serve.quant import (KV_QUANT_MODES, kv_dequantize_rows,
                                      kv_qdtype, kv_quantize_rows)
from kubeflow_tpu.utils import obs
from kubeflow_tpu.utils.resilience import (Deadline, DeadlineExceeded,
                                           metrics as res_metrics)

#: tpk_kv_shipment_bytes buckets — wire-payload-shaped (1 KiB tiny-model
#: handoffs to multi-MiB production blocks), NOT the latency-shaped
#: default. Quantified wire savings: fmt-3 shipments of the same blocks
#: land ≈2 buckets lower than fmt-1 (DISAGGBENCH reports only wall).
_SHIPMENT_BUCKETS = (1024.0, 4096.0, 16384.0, 65536.0, 262144.0,
                     1048576.0, 4194304.0, 16777216.0, 67108864.0)

#: Engine roles (disaggregated prefill/decode, ISSUE 13). "unified" is
#: the escape hatch — today's engine bit-for-bit, serving both phases
#: from one loop. A "prefill" engine only chunk-prefills and SHIPS
#: committed KV blocks (prefill_ship); a "decode" engine only admits
#: shipped blocks (submit_remote) and never runs a prefill chunk, so
#: long-prompt admission cannot steal decode dispatches from in-flight
#: streams.
ENGINE_ROLES = ("unified", "prefill", "decode")

NEG_INF = -1e30


class KVCapacityExceeded(RuntimeError):
    """The request's worst-case KV footprint exceeds the whole paged
    pool — it can NEVER be admitted, no matter how long it waits. The
    HTTP serving surfaces (native :generate, OpenAI facade, both
    streaming and not) map this to a shed — 503 + Retry-After, counted
    in tpk_shed_total — instead of a 400: the spec is valid, this
    replica's pool is just too small."""


class _NeedKVBlocks(Exception):
    """Internal admission signal: the pool cannot cover the request's
    worst-case block need RIGHT NOW (it fits the pool in principle).
    The scheduler keeps the request queued — head-of-line, so a large
    request cannot be starved by a stream of small ones — and retries
    as retirements free blocks."""


def _chosen_logprob(logits: jax.Array, tok: jax.Array) -> jax.Array:
    """log P(tok) under the UNTEMPERED distribution — the logprob surface
    OpenAI reports. logits [..., V], tok [...] -> [...] f32."""
    l32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(l32, axis=-1)
    gold = jnp.take_along_axis(l32, tok[..., None], axis=-1)[..., 0]
    return gold - lse


def sample_tokens(logits: jax.Array, temperature: jax.Array,
                  key: jax.Array, top_k: jax.Array | None = None,
                  top_p: jax.Array | None = None) -> jax.Array:
    """Per-row sampling: argmax where temperature<=0, else categorical at
    that temperature with optional per-row nucleus/top-k truncation.
    logits [B, V]; temperature/top_p [B] f32; top_k [B] int32 (<=0 means
    disabled) -> [B] int32. All on device — one fused dispatch per step."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(temperature, 1e-4)[:, None]
    scaled = logits.astype(jnp.float32) / safe_t
    if top_k is not None or top_p is not None:
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
        keep = jnp.ones_like(scaled, bool)
        if top_k is not None:
            idx = jnp.clip(top_k - 1, 0, v - 1)[:, None]
            kth = jnp.take_along_axis(sorted_desc, idx, axis=-1)  # [B,1]
            keep &= jnp.where(top_k[:, None] > 0, scaled >= kth, True)
        if top_p is not None:
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            # Keep the smallest prefix whose mass reaches p (top token
            # always survives): a token is kept iff the mass STRICTLY
            # before it is < p.
            cum_before = jnp.cumsum(probs, axis=-1) - probs
            # Cutoff value: the smallest sorted logit still kept.
            kept_sorted = cum_before < top_p[:, None]
            cutoff_idx = jnp.maximum(
                jnp.sum(kept_sorted, axis=-1, keepdims=True) - 1, 0)
            cutoff = jnp.take_along_axis(sorted_desc, cutoff_idx, axis=-1)
            keep &= jnp.where(top_p[:, None] < 1.0, scaled >= cutoff, True)
        scaled = jnp.where(keep, scaled, NEG_INF)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def build_engine_fns(model, cfg, *, max_len: int, chunk: int,
                     prefill_buckets: Sequence[int],
                     offset_writes: bool,
                     cache_sharding=None, adapters=None,
                     rolling_window: int = 0,
                     kv_block_size: int = 0,
                     kv_quant: str = "none") -> dict:
    """The engine's pure device functions, as unjitted closures.

    Single source of truth shared by the live `GenerationEngine` (which
    jits them) and the 8B serving scale proof (which AOT-lowers THESE
    functions with tensor-parallel shardings — proving the memory envelope
    of the actual product, not a hand-written stand-in;
    `utils/scaleproof.py` serve_8b_tp8). `cache_sharding` (a NamedSharding
    or None) pins fragment caches created inside prefill so GSPMD shards
    KV heads over `tensor` instead of guessing from use.

    `adapters` (serve/multilora.py stacks): every fn gains an optional
    trailing `aid` (adapter index per row, 0 = base) and the model call
    gathers per-row adapter deltas — multi-LoRA inside one compiled
    program. Callers that never pass `aid` keep base behavior exactly.

    `rolling_window` > 0 switches the cache to the rolling sliding-window
    layout (models/llama.py init_cache): caches hold `window` rows, every
    admission fn passes EXPLICIT positions whose padded tail is the
    sentinel (so modular writes skip pad rows), and decode passes the raw
    absolute index (the model wraps it; clamping would corrupt positions).

    `kv_block_size` > 0 additionally builds the PAGED variants (serve/
    paging.py design note): the persistent cache is a pool of fixed-size
    blocks `[L, n_blocks, block_size, KH, D]` and each decode row's
    history lives wherever its block table points. The jitted step
    gathers the table into a contiguous `[L, B, bucket, ...]` view, runs
    the EXACT flat decode computation on it (view row t IS logical
    position t, so masking/positions/sampling are untouched — paged
    greedy/seeded decode is token-identical to flat), then scatters the
    view back block-by-block. Scatter-back rewrites shared (immutable)
    blocks with their own values and pads through the reserved NULL
    block 0, so duplicate scatter indices can only ever disagree on
    garbage nobody reads (absolute-position masking hides every row past
    a request's write index, exactly as it hides stale flat slots).

    `kv_quant` != "none" (ISSUE 19, paged only) stores the pool as
    int8/fp8 payloads with per-row f32 scale planes "ks"/"vs" addressed
    by the same block ids. The decode path is UNCHANGED TEXT: gather/
    scatter and the scan carry are tree-generic, so the quantized view
    (values + scales) flows through `make_decode_paged` verbatim and
    the model applies scales output-side (models/llama.py decode
    branch) — no full-width dequantized cache ever exists in the scan.
    Only the admission boundary changes: `insert_paged` quantizes the
    fragment's rows (the identical encode as the scan's row writes —
    tpk-sync pins it) and `frag_from_pool` dequantizes into the full-
    precision fragment (admission-side, outside any scan).
    """
    from kubeflow_tpu.models.llama import init_cache

    prefill_buckets = sorted(prefill_buckets)
    big = prefill_buckets[-1]
    frag_len = max_len + (big if offset_writes else 0)
    rolling = int(rolling_window) > 0
    cache_len = rolling_window if rolling else max_len
    sentinel = -(int(rolling_window) + 1)

    def _chunk_positions(index, length, width):
        """Absolute positions for a right-padded chunk, pad tail at the
        sentinel — rolling mode only (the sentinel both masks pad keys
        out of attention and stops their modular cache writes)."""
        ar = jnp.arange(width)[None]
        return jnp.where(ar < length[:, None], index[:, None] + ar,
                         sentinel)

    def apply_kw(aid) -> dict:
        if aid is None or adapters is None:
            return {}
        return {"adapter": adapters, "adapter_ids": aid}

    def _constrain_cache(cache):
        if cache_sharding is None:
            return cache
        return {k: (jax.lax.with_sharding_constraint(v, cache_sharding)
                    if k in ("k", "v") else v)
                for k, v in cache.items()}

    def prefill(params, tokens, length, temperature, top_k, top_p, key,
                aid=None):
        """tokens [1, S_bucket] right-padded; returns (frag_cache,
        first sampled token [1], its logprob [1])."""
        cache = _constrain_cache(init_cache(cfg, 1, frag_len))
        kw = apply_kw(aid)
        if rolling:
            kw["positions"] = _chunk_positions(
                jnp.zeros((1,), jnp.int32), length, tokens.shape[1])
        logits, cache = model.apply(
            {"params": params}, tokens, cache=cache,
            cache_index=jnp.zeros((1,), jnp.int32), **kw)
        last = jnp.take_along_axis(
            logits, (length - 1)[:, None, None], axis=1)[:, 0]  # [1, V]
        tok = sample_tokens(last, temperature, key, top_k, top_p)
        return cache, tok, _chosen_logprob(last, tok)

    def extend(params, cache, tokens, length, index, temperature,
               top_k, top_p, key, aid=None):
        """FINAL continuation chunk of a long prompt: tokens
        [1, S_bucket] right-padded, written at offset `index` [1],
        attending over the WHOLE fragment cache; samples the first
        generated token like prefill."""
        if rolling:
            positions = _chunk_positions(index, length, tokens.shape[1])
        else:
            positions = index[:, None] + jnp.arange(tokens.shape[1])[None]
        logits, cache = model.apply(
            {"params": params}, tokens, cache=cache, cache_index=index,
            positions=positions, attend_full_cache=True, **apply_kw(aid))
        last = jnp.take_along_axis(
            logits, (length - 1)[:, None, None], axis=1)[:, 0]
        tok = sample_tokens(last, temperature, key, top_k, top_p)
        return cache, tok, _chosen_logprob(last, tok)

    def extend_mid(params, cache, tokens, index, aid=None):
        """Intermediate continuation chunk: cache write + attention
        only — return_hidden skips the full-vocab unembedding whose
        sampled token would be discarded anyway. Intermediate chunks are
        always FULL (only the final piece of a prompt can be partial —
        _admit_inner), so rolling mode needs no pad sentinel here."""
        positions = index[:, None] + jnp.arange(tokens.shape[1])[None]
        _, cache = model.apply(
            {"params": params}, tokens, cache=cache, cache_index=index,
            positions=positions, attend_full_cache=True,
            return_hidden=True, **apply_kw(aid))
        return cache

    def insert(cache, frag, slot):
        """Write a prefill fragment (slot-batch 1) into slot `slot`,
        dropping the fragment's pad-headroom rows past max_len."""
        return jax.tree.map(
            lambda c, f: jax.lax.dynamic_update_slice(
                c,
                jax.lax.slice_in_dim(f, 0, c.shape[2], axis=2).astype(
                    c.dtype),
                (0, slot) + (0,) * (c.ndim - 2)), cache, frag)

    def make_decode(truncate: bool, bucket: int):
        def decode_chunk(params, cache, last_tok, index, temperature,
                         top_k, top_p, key, aid=None):
            """K decode steps under one dispatch; on-device sampling.
            last_tok/index/temperature [B]; returns (cache,
            tokens [B, K], logprobs [B, K]). The non-truncating variant
            skips the full-vocab sort/cumsum — all-greedy/
            plain-temperature traffic (the defaults) must not pay
            O(V log V) per token. Attention runs over the first `bucket`
            cache rows only (the loop picks the smallest bucket covering
            every active sequence), then the slice is written back.
            Rolling mode: the cache is `window` rows (never sliced) and
            the index passes through RAW — the model wraps it modularly
            and needs the absolute value for positions."""
            sliced = (cache if bucket == cache_len else jax.tree.map(
                lambda c: jax.lax.slice_in_dim(c, 0, bucket, axis=2),
                cache))

            def step(carry, _):
                sliced, tok, idx, key = carry
                key, sub = jax.random.split(key)
                logits, sliced = model.apply(
                    {"params": params}, tok[:, None], cache=sliced,
                    cache_index=(idx if rolling
                                 else jnp.minimum(idx, bucket - 1)),
                    **apply_kw(aid))
                if truncate:
                    nxt = sample_tokens(logits[:, 0], temperature, sub,
                                        top_k, top_p)
                else:
                    nxt = sample_tokens(logits[:, 0], temperature, sub)
                lp = _chosen_logprob(logits[:, 0], nxt)
                return (sliced, nxt, idx + 1, key), (nxt, lp)

            (sliced, _, _, _), (toks, lps) = jax.lax.scan(
                step, (sliced, last_tok, index, key), None,
                length=chunk)
            if bucket != cache_len:
                cache = jax.tree.map(
                    lambda c, s: jax.lax.dynamic_update_slice(
                        c, s, (0,) * c.ndim), cache, sliced)
            else:
                cache = sliced
            return cache, toks.T, lps.T
        return decode_chunk

    fns = {"prefill": prefill, "extend": extend, "extend_mid": extend_mid,
           "insert": insert, "make_decode": make_decode,
           "frag_len": frag_len}
    if kv_block_size > 0:
        if rolling:
            raise ValueError(
                "paged KV does not compose with the rolling cache")
        bs = int(kv_block_size)
        mb = max_len // bs  # blocks covering one full-length request

        def _gather_view(pool_leaf, tables):
            """[L, NB, bs, ...] × [B, nb] -> [L, B, nb*bs, ...]: view row
            j*bs + r is block tables[b, j] row r — logical position
            j*bs + r, because tables are position-ordered."""
            g = jnp.take(pool_leaf, tables, axis=1)  # [L, B, nb, bs, ...]
            return g.reshape(g.shape[0], g.shape[1],
                             g.shape[2] * g.shape[3], *g.shape[4:])

        def _scatter_view(pool_leaf, view_leaf, tables):
            """Write the view back to its blocks. Duplicate ids (shared
            prefix blocks across rows, NULL-block pads) are benign: a
            shared block is immutable, so every row writes its original
            values; the NULL block receives garbage nobody reads."""
            b, nb = tables.shape
            v = view_leaf.reshape(view_leaf.shape[0], b, nb, bs,
                                  *view_leaf.shape[3:])
            v = v.reshape(v.shape[0], b * nb, bs, *v.shape[4:])
            return pool_leaf.at[:, tables.reshape(-1)].set(v)

        def make_decode_paged(truncate: bool, bucket: int):
            nb = bucket // bs

            def decode_chunk(params, pool, tables, last_tok, index,
                             temperature, top_k, top_p, key, aid=None):
                """Flat `decode_chunk` semantics over a gathered block
                view: tables [B, nb] (pad entries 0 = NULL block). The
                scan body is the flat step verbatim — paged decode is
                token-identical to flat decode by construction."""
                view = jax.tree.map(lambda p: _gather_view(p, tables),
                                    pool)

                def step(carry, _):
                    view, tok, idx, key = carry
                    key, sub = jax.random.split(key)
                    logits, view = model.apply(
                        {"params": params}, tok[:, None], cache=view,
                        cache_index=jnp.minimum(idx, bucket - 1),
                        **apply_kw(aid))
                    if truncate:
                        nxt = sample_tokens(logits[:, 0], temperature,
                                            sub, top_k, top_p)
                    else:
                        nxt = sample_tokens(logits[:, 0], temperature,
                                            sub)
                    lp = _chosen_logprob(logits[:, 0], nxt)
                    return (view, nxt, idx + 1, key), (nxt, lp)

                (view, _, _, _), (toks, lps) = jax.lax.scan(
                    step, (view, last_tok, index, key), None,
                    length=chunk)
                pool = jax.tree.map(
                    lambda p, v: _scatter_view(p, v, tables), pool, view)
                return pool, toks.T, lps.T
            return decode_chunk

        def insert_paged(pool, frag, table):
            """Scatter an admission fragment's first max_len rows into
            the request's blocks. `table` [mb] is the SCATTER table:
            zero-copy shared prefix blocks are masked to the NULL block
            (they already hold these exact rows and must stay untouched
            by construction, not by luck), a freshly forked tail block
            receives its committed rows from the fragment — that write
            IS the copy-on-write copy — and entries past the allocation
            pad to NULL."""
            def leaf(p, f):
                rows = jax.lax.slice_in_dim(
                    f, 0, mb * bs, axis=2).astype(p.dtype)
                rows = rows.reshape(rows.shape[0], mb, bs,
                                    *rows.shape[3:])
                return p.at[:, table].set(rows)
            return jax.tree.map(leaf, pool, frag)

        def frag_from_pool(pool, table):
            """Rebuild a fragment cache [L, 1, frag_len, ...] from a
            block table — the admission-side gather that lets chunked
            prefill RESUME after a prefix-cache hit without the flat
            engine's stored full-length fragment copy. Rows past the
            stored prefix come back as garbage; safe for the same reason
            stale fragment rows always were (each is overwritten before
            any query position can attend it)."""
            empty = init_cache(cfg, 1, frag_len)

            def leaf(f, p):
                g = jnp.take(p, table, axis=1)  # [L, mb, bs, ...]
                g = g.reshape(g.shape[0], 1, mb * bs, *g.shape[3:])
                return jax.lax.dynamic_update_slice(
                    f, g.astype(f.dtype), (0,) * f.ndim)
            return jax.tree.map(leaf, empty, pool)

        def export_blocks(pool, table):
            """Gather `mb` whole blocks off the pool ([mb] table, NULL
            pads) — the device half of the KV wire format (serve/
            kv_transfer.py). Pad gathers return NULL-block garbage the
            host side slices away; committed rows come back exactly as
            the pool holds them, so pool → wire → pool round-trips
            byte-identically (test-pinned)."""
            return jax.tree.map(lambda p: jnp.take(p, table, axis=1),
                                pool)

        def import_blocks(pool, blocks, table):
            """Scatter shipped host blocks into the pool at `table` —
            the H2D half. Table entries masked to NULL absorb the
            shipment's pad blocks in the reserved garbage block; real
            entries land a remote prefill's committed rows without a
            single local prefill chunk."""
            return jax.tree.map(
                lambda p, b: p.at[:, table].set(b.astype(p.dtype)),
                pool, blocks)

        def insert_paged_quant(pool, frag, table):
            """`insert_paged` for the quantized pool: the fragment
            arrives at FULL precision (admission computes exact rows),
            and the scatter quantizes them — with the IDENTICAL encode
            as the decode scan's per-row writes (models/llama.py), so a
            row reaches the same bytes whether it was admitted or
            decoded; the tpk-sync twin pins that equivalence. Shared
            prefix blocks are masked to NULL exactly as in the plain
            path — their committed bytes never change."""
            qmode = kv_quant
            rows_k = jax.lax.slice_in_dim(frag["k"], 0, mb * bs, axis=2)
            rows_v = jax.lax.slice_in_dim(frag["v"], 0, mb * bs, axis=2)
            # tpk-sync: begin kv-quant-scatter admit
            # tpk-sync: sub kv_quantize_rows(k, qmode) -> kv_quantize_rows(rows_k, qmode)
            # tpk-sync: sub kv_quantize_rows(v, qmode) -> kv_quantize_rows(rows_v, qmode)
            kq, ks = kv_quantize_rows(rows_k, qmode)
            vq, vs = kv_quantize_rows(rows_v, qmode)
            # tpk-sync: end kv-quant-scatter

            def blocked(r):
                return r.reshape(r.shape[0], mb, bs, *r.shape[3:])

            out = dict(pool)
            for name, arr in (("k", kq), ("v", vq), ("ks", ks),
                              ("vs", vs)):
                out[name] = out[name].at[:, table].set(blocked(arr))
            return out

        def frag_from_pool_quant(pool, table):
            """`frag_from_pool` for the quantized pool: gather blocks +
            scale blocks, dequantize into the full-precision fragment.
            This is the ONE place full-width dequantized rows may
            materialize — admission-side reconstruction for a prefix hit
            or continuation, outside any scan (each call is a dequant
            fallback; the engine counts them)."""
            empty = init_cache(cfg, 1, frag_len)

            def rowed(g):
                return g.reshape(g.shape[0], 1, mb * bs, *g.shape[3:])

            out = {}
            for name, sname in (("k", "ks"), ("v", "vs")):
                vals = rowed(jnp.take(pool[name], table, axis=1))
                scales = rowed(jnp.take(pool[sname], table, axis=1))
                rows = kv_dequantize_rows(vals, scales,
                                          empty[name].dtype)
                out[name] = jax.lax.dynamic_update_slice(
                    empty[name], rows, (0,) * empty[name].ndim)
            return out

        fns.update(make_decode_paged=make_decode_paged,
                   insert_paged=insert_paged,
                   frag_from_pool=frag_from_pool,
                   export_blocks=export_blocks,
                   import_blocks=import_blocks)
        if kv_quant != "none":
            # The plain fns above stay textually untouched (the
            # kv_quant="none" bit-exactness pin); quantized pools swap
            # ONLY the admission boundary.
            fns.update(insert_paged=insert_paged_quant,
                       frag_from_pool=frag_from_pool_quant)
    return fns


def spec_acceptance(drafts, dlogits, tlogits, temperature, key):
    """Per-row speculative acceptance — greedy rows exact-match, sampled
    rows the standard rejection scheme (Leviathan/Chen): the draft
    proposed d_j ~ p_d (its temperature-scaled softmax), the target
    accepts with prob min(1, p_t(d_j)/p_d(d_j)) and on first rejection
    emits a sample from the residual normalize(max(p_t - p_d, 0)); full
    acceptance emits a bonus sample from p_t[gamma]. The emitted marginal
    at every position is EXACTLY the target's tempered distribution — a
    weak draft costs acceptance rate, never the sampling law.

    drafts [B, gamma] (greedy rows: argmax proposals; sampled rows: draws
    from p_d), dlogits [B, gamma, V] draft logits per proposal position,
    tlogits [B, gamma+1, V] target logits, temperature [B] (<=0 greedy).
    Returns (out [B, gamma+1] emitted tokens incl. correction/bonus,
    k [B] accepted counts, next_tok [B])."""
    b, gamma = drafts.shape
    sampled = temperature > 0
    safe_t = jnp.maximum(temperature, 1e-4)[:, None, None]
    tprobs = jax.nn.softmax(tlogits.astype(jnp.float32) / safe_t, axis=-1)
    dprobs = jax.nn.softmax(dlogits.astype(jnp.float32) / safe_t, axis=-1)
    tgreedy = jnp.argmax(tlogits, -1).astype(jnp.int32)  # [B, gamma+1]

    pt_d = jnp.take_along_axis(tprobs[:, :gamma], drafts[..., None],
                               axis=-1)[..., 0]          # [B, gamma]
    pd_d = jnp.take_along_axis(dprobs, drafts[..., None],
                               axis=-1)[..., 0]
    ukey, rkey = jax.random.split(key)
    u = jax.random.uniform(ukey, (b, gamma))
    accept_sampled = u < pt_d / jnp.maximum(pd_d, 1e-30)
    accept_greedy = drafts == tgreedy[:, :gamma]
    accept = jnp.where(sampled[:, None], accept_sampled, accept_greedy)
    k = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

    # Correction at the rejection position (sampled rows): residual
    # distribution max(p_t - p_d, 0) renormalized; on full acceptance the
    # "residual" at position gamma is p_t itself (p_d defined 0 there).
    dprobs_pad = jnp.concatenate(
        [dprobs, jnp.zeros_like(dprobs[:, :1])], axis=1)  # [B, gamma+1, V]
    pt_k = jnp.take_along_axis(tprobs, k[:, None, None], axis=1)[:, 0]
    pd_k = jnp.take_along_axis(dprobs_pad, k[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(pt_k - pd_k, 0.0)
    resid_mass = jnp.sum(resid, axis=-1, keepdims=True)
    # Degenerate residual (identical distributions): fall back to p_t.
    resid = jnp.where(resid_mass > 1e-30, resid, pt_k)
    corr_sampled = jax.random.categorical(
        rkey, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1).astype(jnp.int32)
    corr_greedy = jnp.take_along_axis(tgreedy, k[:, None], axis=1)[:, 0]
    corr = jnp.where(sampled, corr_sampled, corr_greedy)

    j = jnp.arange(gamma + 1)[None]
    padded = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    out = jnp.where(j < k[:, None], padded,
                    jnp.where(j == k[:, None], corr[:, None], 0))
    return out, k, corr


def build_spec_decode(model, draft_model, *, gamma: int, n_spec: int,
                      max_len: int, rolling_window: int = 0,
                      adapters=None, kv_block_size: int = 0):
    """Speculative decoding step functions (vLLM's draft-model speedup,
    XLA-shaped): per spec step the DRAFT autoregressively proposes `gamma`
    tokens (gamma cheap forwards inside the scan), then the TARGET scores
    all gamma+1 positions in ONE forward — the chunked-prefill path
    (explicit positions + attend_full_cache), which writes the candidate
    K/V rows before attending, so rejected rows are simply overwritten by
    the next step's write at the rewound index. Acceptance per row
    (spec_acceptance): greedy rows exact-match against the target argmax
    (emitted stream TOKEN-IDENTICAL to vanilla greedy); tempered rows the
    rejection scheme (draft samples from p_d, accept w.p. min(1,
    p_t/p_d), residual sample on rejection) whose emitted marginal is
    exactly the tempered target distribution — per step, k accepted + 1
    correction/bonus, k in [0, gamma].

    `n_spec` steps ride one dispatch (the tunnel sync amortization that
    motivates chunked decode; worst case n_spec*(gamma+1) tokens, the
    caller sizes the cache bucket for it). Returns
    make(bucket) -> spec_chunk(params, dparams, cache, dcache, last_tok,
    index, temperature, key) -> (cache, dcache,
    tokens [B, n_spec, gamma+1], logprobs [B, n_spec, gamma+1],
    accepted [B, n_spec]).

    `rolling_window` > 0: the TARGET runs a rolling sliding-window cache
    (window rows, modular writes). The verify forward writes all gamma+1
    candidate rows, but a rejection rewinds — and in a rolling cache
    those rejected writes have EVICTED live in-window rows (in a causal
    cache they merely occupy not-yet-committed rows ahead of the index).
    After acceptance the step reverts rows past the accepted count to
    their pre-verify contents, so the cache always holds exactly the
    committed stream.

    `adapters` (multi-LoRA x spec-decode): the TARGET verifies under each
    row's adapter while the draft proposes from its own base weights — a
    base-model draft can only cost acceptance rate, never correctness,
    because every emitted token comes from the target's (adapted) logits
    via exact-match/rejection acceptance.

    `kv_block_size` > 0 (spec x paged, ISSUE 18): make(bucket) returns
    the PAGED signature instead — spec_chunk(params, dparams, pool,
    dpool, tables, dtables, last_tok, index, temperature, key) — which
    gathers per-row block views of the target AND draft pools (tables /
    dtables [B, bucket//bs], pad entries 0 = NULL block), runs the flat
    spec core on the views verbatim, and scatters both back. Paged spec
    decode is token-identical to flat spec decode by construction, the
    same argument as make_decode_paged; the draft pool shares the
    target's block-id space but its tables are per-slot and never
    prefix-shared (a draft cache is private working state)."""
    rolling = int(rolling_window) > 0
    bs = int(kv_block_size)
    if bs and rolling:
        raise ValueError(
            "paged spec decode does not compose with the rolling cache")

    def _gather_view(pool_leaf, tables):
        g = jnp.take(pool_leaf, tables, axis=1)  # [L, B, nb, bs, ...]
        return g.reshape(g.shape[0], g.shape[1],
                         g.shape[2] * g.shape[3], *g.shape[4:])

    def _scatter_view(pool_leaf, view_leaf, tables):
        b, nb = tables.shape
        v = view_leaf.reshape(view_leaf.shape[0], b, nb, bs,
                              *view_leaf.shape[3:])
        v = v.reshape(v.shape[0], b * nb, bs, *v.shape[4:])
        return pool_leaf.at[:, tables.reshape(-1)].set(v)

    def make(bucket: int):
        def spec_chunk(params, dparams, cache, dcache, last_tok, index,
                       temperature, key, aid=None):
            t_kw = ({} if aid is None or adapters is None
                    else {"adapter": adapters, "adapter_ids": aid})
            def sl(c):
                # Rolling target (window rows) and its causal draft
                # (max_len rows) are never sliced — the window already
                # bounds the target's attention cost, and bucket is
                # sized for the causal layout only.
                if rolling or bucket == max_len:
                    return c
                return jax.tree.map(
                    lambda x: jax.lax.slice_in_dim(x, 0, bucket, axis=2), c)

            sliced, dsliced = sl(cache), sl(dcache)
            dcap = max_len if rolling else bucket

            def revert_rejected(cw, c0, idx, k):
                """Restore rolling-cache rows the rejected candidates
                clobbered: row (idx+j) % window keeps the verify's write
                for j <= k (committed tokens) and returns to its
                pre-verify contents otherwise."""
                j = jnp.arange(gamma + 1)
                rows = (idx[:, None] + j[None]) % rolling_window
                keep = j[None] <= k[:, None]

                def leaf(cw, c0):
                    def per_batch(cwb, c0b, r, kp):
                        old = jnp.take(c0b, r, axis=1)  # [L, gamma+1, ...]
                        new = jnp.take(cwb, r, axis=1)
                        sel = kp.reshape((1, -1) + (1,) * (cwb.ndim - 2))
                        vals = jnp.where(sel, new, old)
                        return jax.vmap(
                            lambda cl, vl: cl.at[r].set(vl))(cwb, vals)
                    return jax.vmap(per_batch, in_axes=(1, 1, 0, 0),
                                    out_axes=1)(cw, c0, rows, keep)

                return jax.tree.map(leaf, cw, c0)

            def spec_step(carry, _):
                c, dc, tok, idx, key = carry
                key, dkey, akey = jax.random.split(key, 3)

                def dstep(dcarry, skey):
                    dc, t, i = dcarry
                    dlogits, dc = draft_model.apply(
                        {"params": dparams}, t[:, None], cache=dc,
                        cache_index=jnp.minimum(i, dcap - 1))
                    row = dlogits[:, 0]
                    # Sampled rows draw from the draft's tempered softmax
                    # (the rejection scheme needs d ~ p_d); greedy rows
                    # take argmax — exactly sample_tokens' untruncated
                    # path, reused so proposal sampling can never drift
                    # from the engine's sampling semantics.
                    nxt = sample_tokens(row, temperature, skey)
                    return (dc, nxt, i + 1), (nxt, row)

                # gamma+1 iterations, gamma proposals: the extra step
                # writes the LAST proposal's K/V into the draft cache
                # (each iteration caches its INPUT, so d_{gamma-1} —
                # output-only in a gamma-length scan — would otherwise
                # leave a stale row after a fully-accepted step, and
                # every later draft forward would attend garbage there,
                # collapsing the acceptance rate).
                (dc, _, _), (drafts, dlogits) = jax.lax.scan(
                    dstep, (dc, tok, idx),
                    jax.random.split(dkey, gamma + 1))
                drafts = drafts.T[:, :gamma]           # [B, gamma]
                dlogits = dlogits.transpose(1, 0, 2)[:, :gamma]

                tokens_in = jnp.concatenate([tok[:, None], drafts], axis=1)
                positions = idx[:, None] + jnp.arange(gamma + 1)[None]
                c0 = c
                tlogits, c = model.apply(
                    {"params": params}, tokens_in, cache=c,
                    cache_index=(idx if rolling
                                 else jnp.minimum(idx, bucket - 1)),
                    positions=positions, attend_full_cache=True, **t_kw)
                out, k, nxt = spec_acceptance(
                    drafts, dlogits, tlogits, temperature, akey)
                if rolling:
                    c = revert_rejected(c, c0, idx, k)
                lps = _chosen_logprob(tlogits, out)
                return (c, dc, nxt, idx + k + 1, key), (out, lps, k)

            (sliced, dsliced, _, _, _), (outs, lps, ks) = jax.lax.scan(
                spec_step, (sliced, dsliced, last_tok, index, key), None,
                length=n_spec)

            def wb(full, s):
                if rolling or bucket == max_len:
                    return s
                return jax.tree.map(
                    lambda c, x: jax.lax.dynamic_update_slice(
                        c, x, (0,) * c.ndim), full, s)

            return (wb(cache, sliced), wb(dcache, dsliced),
                    outs.transpose(1, 0, 2), lps.transpose(1, 0, 2), ks.T)
        if not bs:
            return spec_chunk

        def spec_chunk_paged(params, dparams, pool, dpool, tables,
                             dtables, last_tok, index, temperature, key,
                             aid=None):
            cache = jax.tree.map(lambda p: _gather_view(p, tables), pool)
            dcache = jax.tree.map(lambda p: _gather_view(p, dtables),
                                  dpool)
            cache, dcache, toks, lps, ks = spec_chunk(
                params, dparams, cache, dcache, last_tok, index,
                temperature, key, aid)
            pool = jax.tree.map(
                lambda p, v: _scatter_view(p, v, tables), pool, cache)
            dpool = jax.tree.map(
                lambda p, v: _scatter_view(p, v, dtables), dpool, dcache)
            return pool, dpool, toks, lps, ks
        return spec_chunk_paged
    return make


class GenerationEngine:
    """Slot-based continuous-batching decode loop over one global cache.

    `submit()` is thread-safe and blocks until the request completes; the
    worker thread multiplexes all in-flight requests onto the slot batch.

    **Overlapped scheduling** (`pipeline_depth`, default 2): the run loop
    keeps up to `pipeline_depth` decode chunks in flight — chunk k+1 is
    dispatched *chained through the on-device cache and last-token carry*
    before chunk k's tokens are fetched, so the device never idles a
    tunnel RTT (~66 ms on the axon backend, PROFILE.md §1) between
    chunks. Admission (prefill/extend/insert) is likewise dispatched
    *between* in-flight chunks without a host sync — the newly admitted
    request's first sampled token stays on device as the decode carry and
    its host value is collected lazily — so admitting request B no longer
    stalls every active slot for a whole prefill round-trip. When a
    fetched chunk reveals EOS/budget/deadline for a slot, the chunks
    already speculatively dispatched contain dead rows for it; the fetch
    path reconciles by dropping them (`decode_wasted_tokens` /
    `decode_dead_slot_chunks` account the waste, bounded by
    `pipeline_depth - 1` chunks per retirement) and the slot is freed at
    that boundary. `pipeline_depth=1` is the escape hatch: it reproduces
    the fully synchronous dispatch→fetch loop bit-for-bit (same RNG
    stream, same host-sync points). Engines with a speculative `draft`
    always run depth 1 — the spec path's advance is data-dependent
    (accepted counts), so its carry cannot be chained on device; the spec
    chunk already amortizes the RTT by n_spec·(gamma+1) tokens.

    **Tensor parallelism** (SURVEY.md §2.2 "tensor-parallel serving"):
    pass `mesh` (a jax.sharding.Mesh with a `tensor` axis) and the engine
    shards weights and KV caches over it — KV heads over `tensor` (each
    device holds its head group), mlp/vocab per the logical rules — and
    every prefill/decode dispatch runs SPMD with XLA-inserted collectives.
    An 8B bf16 model does not fit one chip; TP-8 is how the flagship
    serves. The public API is unchanged: submit() still takes one request.
    """

    def __init__(self, model, params, cfg, *, slots: int = 4,
                 max_len: int = 256, chunk: int = 16,
                 prefill_buckets: Sequence[int] = (32, 128),
                 decode_buckets: Sequence[int] | None = None,
                 prefix_cache: int = 0, seed: int = 0,
                 mesh=None, rules=None, draft: dict | None = None,
                 adapters: dict | None = None, pipeline_depth: int = 2,
                 kv_block_size: int = 0, kv_blocks: int = 0,
                 role: str = "unified", kv_host_tier_blocks: int = 0,
                 kv_quant: str = "none"):
        self.model, self.cfg = model, cfg
        self.max_len, self.chunk, self.n_slots = int(max_len), int(chunk), int(slots)
        msl = int(getattr(cfg, "max_seq_len", 0) or 0)
        if msl and self.max_len > msl:
            # Past the model's position range the wpe/RoPE-table gather
            # CLAMPS under jit — every later token reuses the last
            # position, silently diverging from the source model.
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's position "
                f"range (max_seq_len={msl}); positions would silently "
                "clamp")
        mask_kind = getattr(cfg, "mask_kind", "causal")
        self._rolling = 0
        if mask_kind == "sliding_window":
            window = int(getattr(cfg, "mask_window", 0))
            if window < 1 and self.max_len > window:
                raise ValueError(
                    "sliding-window checkpoint with window=0 cannot be "
                    "served")
            if (self.max_len > window
                    and getattr(cfg, "sliding_pattern", "all") != "all"):
                # Alternating sliding/full layers (Gemma-2/3) past the
                # window: the full-attention layers need ALL history, so
                # nothing rolls — the cache stays full-length and the
                # sliding layers band their reads per the traced
                # per-layer flag (models/llama.py decode branch). The
                # config keeps its mask; decode runs the einsum path.
                pass
            elif self.max_len > window:
                # Serving PAST the window: rolling-buffer KV cache
                # (models/llama.py init_cache grows a "pos" plane; rows =
                # window, modular writes, position-masked reads) — the
                # vLLM/huggingfaceserver capability of serving
                # Mistral-class models at full context, exactly. (The
                # window >= 1 guard above already rejected degenerate
                # configs.)
                self._rolling = window
            else:
                # Within the window the band never clips, so causal decode
                # is exact — rebuild the module causal (params are
                # identical; the mask kind is config-only) to use the
                # faster causal KV-cache paths (bucketed decode, flash
                # prefill) instead of the rolling read.
                import dataclasses

                from kubeflow_tpu.serve.quant import QuantizedModule

                cfg = dataclasses.replace(cfg, mask_kind="causal",
                                          mask_window=0,
                                          attention_impl="auto")
                if isinstance(model, QuantizedModule):
                    # Rebuild the INNER module by replacing its cfg
                    # field (flax modules are dataclasses) — a
                    # type(module)(cfg) reconstruction would drop every
                    # other field, e.g. an MoE trunk's mlp_cls.
                    model = QuantizedModule(
                        dataclasses.replace(model.module, cfg=cfg),
                        model.dtype,
                        legacy_dequant=model.legacy_dequant)
                else:
                    model = dataclasses.replace(model, cfg=cfg)
                self.model, self.cfg = model, cfg
        elif mask_kind != "causal":
            raise ValueError(
                f"generative serving needs a causal-class model; got "
                f"mask_kind={mask_kind!r}")
        # Rolling mode clamps prompt buckets to the window: a chunk wider
        # than the window would wrap onto itself (duplicate modular write
        # rows — undefined scatter order).
        bucket_cap = min(self.max_len, self._rolling or self.max_len)
        self.prefill_buckets = sorted(
            {min(int(b), bucket_cap) for b in prefill_buckets})
        # Length-aware decode (VERDICT r2 item 4): decode compiles once PER
        # CACHE-LENGTH BUCKET over a time-sliced cache, so attention cost
        # tracks the longest ACTIVE sequence, not max_len. Default buckets:
        # powers of two from max(64, 2·chunk) up to max_len.
        # Rolling mode has ONE bucket — the window itself already bounds
        # attention cost, and rolling rows aren't prefix-ordered, so
        # time-slicing the cache would drop live in-window rows.
        if self._rolling:
            self.decode_buckets = [self._rolling]
        else:
            if decode_buckets is None:
                b, decode_buckets = max(64, 2 * self.chunk), []
                while b < self.max_len:
                    decode_buckets.append(b)
                    b *= 2
            self.decode_buckets = sorted(
                {int(b) for b in decode_buckets
                 if self.chunk < int(b) < self.max_len} | {self.max_len})
        # Paged KV cache (ROADMAP item 1, the vLLM PagedAttention design
        # TPU-shaped — serve/paging.py): `kv_block_size` > 0 swaps the
        # slot-contiguous cache [L, slots, max_len, ...] for a pool of
        # `kv_blocks` fixed-size blocks (+ the reserved NULL block).
        # `slots` becomes pure CONCURRENCY (the compiled decode width);
        # memory is the pool, so many short requests coexist where flat
        # mode would hold `slots` worst-case rows. kv_blocks=0 sizes the
        # pool to flat parity (slots*max_len tokens) — raise slots and
        # shrink kv_blocks to trade worst-case headroom for concurrency.
        # kv_block_size=0 (default) is the escape hatch: the flat engine,
        # bit-for-bit (same RNG splits, same sync points).
        self._paged = int(kv_block_size) > 0
        self._kv_bs = int(kv_block_size)
        self._kv_stash: deque = deque()  # admissions waiting for blocks
        # Disaggregated prefill/decode (ISSUE 13): KV blocks are the
        # wire format, so both split roles and the host-RAM spill tier
        # require the paged pool. role="unified" with no tier is the
        # escape hatch — bit-for-bit today's engine (same RNG splits,
        # same sync points, no extra compiles).
        if role not in ENGINE_ROLES:
            raise ValueError(
                f"role {role!r}: must be one of {ENGINE_ROLES}")
        if role != "unified" and not self._paged:
            raise ValueError(
                f"role={role!r} needs the paged KV cache (KV blocks are "
                "the prefill→decode wire unit); set kv_block_size > 0")
        if int(kv_host_tier_blocks) and not self._paged:
            raise ValueError(
                "kv_host_tier_blocks > 0 needs the paged KV cache (the "
                "host tier spills whole blocks); set kv_block_size > 0")
        self.role = role
        self._host_tier = (HostKVTier(int(kv_host_tier_blocks))
                           if self._paged and int(kv_host_tier_blocks)
                           else None)
        if self._paged:
            if self._rolling:
                raise ValueError(
                    "kv_block_size > 0 does not compose with rolling "
                    "sliding-window serving (rolling rows are not "
                    "prefix-ordered, so block tables cannot address "
                    "them); set kv_block_size=0")
            if self.max_len % self._kv_bs:
                raise ValueError(
                    f"kv_block_size {self._kv_bs} must divide max_len "
                    f"{self.max_len} (block tables address whole blocks)")
            bad = [b for b in self.decode_buckets if b % self._kv_bs]
            if bad:
                raise ValueError(
                    f"kv_block_size {self._kv_bs} must divide every "
                    f"decode bucket; offending: {bad} (pass explicit "
                    "decode_buckets or a power-of-two block size)")
            n_blocks = int(kv_blocks) or -(-self.n_slots * self.max_len
                                           // self._kv_bs)
            self._kv_alloc = BlockAllocator(n_blocks, self._kv_bs)
        # Quantized KV blocks (ISSUE 19): the pool stores int8/fp8
        # payloads + per-row f32 scale planes addressed by the same
        # block ids, so ≈2× kv_blocks fit the same HBM, host-tier
        # spills charge about half the block units, and TPKV1 fmt-3
        # ships quantized bytes. "none" (default) is the bit-exact
        # escape hatch — the unquantized code paths, textually.
        self.kv_quant = str(kv_quant or "none")
        if self.kv_quant not in KV_QUANT_MODES:
            raise ValueError(
                f"kv_quant {self.kv_quant!r}: must be one of "
                f"{KV_QUANT_MODES}")
        if self.kv_quant != "none":
            if not self._paged:
                raise ValueError(
                    "kv_quant requires the paged KV cache (quantization "
                    "is a property of pool blocks); set kv_block_size "
                    "> 0")
            if draft is not None:
                # Measured decision (bench.py quant A/B, PROFILE.md
                # §17): draft-assisted acceptance degrades measurably
                # when the verify forward reads a quantized cache, and
                # a spec rewind would re-quantize rows that were NOT
                # newly written (breaking the immutable-committed-rows
                # discipline CoW and shipments rely on). Refused loudly
                # — cpp/admission.h enforces the same cross-field rule
                # at submit time.
                raise ValueError(
                    "kv_quant does not compose with speculative "
                    "decoding (draft): a rejection rewind would "
                    "re-quantize committed rows; drop the draft or "
                    "set kv_quant='none'")
        # Prefix cache: LRU of prompt-chunk-boundary KV fragments keyed by
        # the exact token prefix; admission resumes chunked prefill after
        # the longest hit instead of recomputing it (the vLLM prefix-reuse
        # capability, at bucket granularity). Capacity in fragments —
        # OPT-IN (0 = off): each fragment is a full-length KV copy, so
        # the cache charges real HBM; enable it for shared-system-prompt
        # workloads where the recompute saving pays for the residency.
        self._prefix_cap = int(prefix_cache)
        # LRU keyed by (aid, prefix_len, hash(token_tuple)); each value is
        # (token_tuple, fragment) — the tuple verifies the hash, and the
        # (aid -> {len: count}) side index lets lookup probe by length
        # instead of scanning every entry (see _prefix_lookup).
        self._prefix_lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._prefix_lens: dict[int, dict[int, int]] = {}
        # Speculative decoding (vLLM draft-model speedup): draft =
        # {"model", "params", "cfg", "gamma"?} — greedy requests decode
        # speculatively (token-identical to vanilla greedy) and
        # plain-temperature requests via rejection sampling (exact
        # tempered-target marginal); top-k/top-p requests fall back to
        # the plain chunked decode.
        self._spec = None
        if draft is not None:
            dcfg = draft["cfg"]
            # Same windowed-checkpoint treatment the target gets above: a
            # Mistral-family draft is exact within its window (rebuild
            # causal), past it refuse with an actionable message instead
            # of crashing in the jit trace.
            dmask = getattr(dcfg, "mask_kind", "causal")
            if dmask == "sliding_window":
                dwindow = int(getattr(dcfg, "mask_window", 0))
                if self.max_len > dwindow:
                    raise ValueError(
                        f"sliding-window draft (window={dwindow}): serving "
                        f"max_len={self.max_len} exceeds the window; set "
                        "max_len <= window or use a causal draft")
                import dataclasses

                from kubeflow_tpu.serve.quant import QuantizedModule

                dcfg = dataclasses.replace(dcfg, mask_kind="causal",
                                           mask_window=0,
                                           attention_impl="auto")
                dmodel = draft["model"]
                if isinstance(dmodel, QuantizedModule):
                    # Replace the INNER module's cfg field (see the
                    # target rebuild above — reconstruction drops
                    # non-cfg module fields).
                    dmodel = QuantizedModule(
                        dataclasses.replace(dmodel.module, cfg=dcfg),
                        dmodel.dtype,
                        legacy_dequant=dmodel.legacy_dequant)
                else:
                    dmodel = dataclasses.replace(dmodel, cfg=dcfg)
                draft = dict(draft, cfg=dcfg, model=dmodel)
            elif dmask != "causal":
                raise ValueError(
                    f"speculative decoding needs a causal-class draft; "
                    f"got mask_kind={dmask!r}")
            if getattr(dcfg, "vocab_size", None) != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {getattr(dcfg, 'vocab_size', None)} != "
                    f"target vocab {cfg.vocab_size} — speculative "
                    "acceptance compares token ids, so the vocabularies "
                    "must be identical")
            dmsl = int(getattr(dcfg, "max_seq_len", 0) or 0)
            if dmsl and self.max_len > dmsl:
                raise ValueError(
                    f"max_len {self.max_len} exceeds the draft model's "
                    f"position range (max_seq_len={dmsl})")
            gamma = int(draft.get("gamma", 4))
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            if self._rolling and gamma + 1 > self._rolling:
                raise ValueError(
                    f"gamma={gamma} writes {gamma + 1} candidate rows per "
                    f"spec step, more than the rolling window "
                    f"({self._rolling}) holds")
            self._spec = {
                "model": draft["model"], "cfg": dcfg, "gamma": gamma,
                # Spec steps per dispatch: match the vanilla chunk's
                # best-case token budget so the tunnel-sync amortization
                # carries over.
                "n_spec": max(1, self.chunk // (gamma + 1)),
            }
            # Device placement happens after mesh setup below — under TP
            # the draft shards over the same mesh as the target.
            self._dparams_src = draft["params"]
        # Multi-LoRA serving (serve/multilora.py): {name: PEFT adapter
        # dir} — all adapters stacked on device, selected per request by
        # index inside the compiled program.
        self._ml_stacks = None
        self._ml_ids: dict[str, int] = {}
        if adapters:
            from kubeflow_tpu.serve.multilora import build_adapter_stacks

            self._ml_stacks, self._ml_ids = build_adapter_stacks(
                dict(adapters), self.cfg)
            if mesh is None:
                self._ml_stacks = jax.device_put(self._ml_stacks)
            else:
                # multi-LoRA x TP: adapter stacks REPLICATE over the mesh
                # (rank-r factors are tiny next to the base weights); the
                # per-row delta lands on sharded activations and XLA
                # slices it at the logical constraint right after. Spec
                # compose note: the draft proposes from the BASE model —
                # acceptance may drop for heavily-adapted targets, but
                # emitted tokens always come from the target's (adapted)
                # logits, so the sampling law is untouched.
                from jax.sharding import NamedSharding, PartitionSpec
                self._ml_stacks = jax.device_put(
                    self._ml_stacks, NamedSharding(mesh, PartitionSpec()))
            self._ml_names = {i: n for n, i in self._ml_ids.items()}
        self._mesh = mesh
        if rules is None:
            from kubeflow_tpu.parallel.sharding import DEFAULT_RULES
            rules = DEFAULT_RULES
        self._rules = tuple(rules)
        self._cache_sharding = None
        self._dcache_sharding = None
        if mesh is not None:
            self._params, self._cache_sharding = self._shard_params(params)
        else:
            self._params = jax.device_put(params)
        if self._spec is not None:
            # Spec-decode x TP: the draft shards over the SAME mesh by
            # the same logical rules (its KV heads must divide tensor
            # like the target's) — one SPMD program runs draft proposals
            # and target verify together.
            if mesh is not None:
                self._dparams, self._dcache_sharding = self._shard_params(
                    self._dparams_src, model=self._spec["model"],
                    cfg=self._spec["cfg"], role="draft")
            else:
                self._dparams = jax.device_put(self._dparams_src)
            del self._dparams_src
        if int(pipeline_depth) < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        # Spec x pipelining (ISSUE 18 move 3): the spec chunk's advance is
        # data-dependent (accepted counts pick the next index), so depth>1
        # chains spec chunk k+1 on the WORST-CASE carry — the last bonus
        # token under full acceptance. Any rejection dooms the chained
        # in-flight chunks; the fetch reconciles them exactly like
        # speculatively-dead chunks (bounded waste: depth-1 chunks per
        # rejection event). pipeline_depth bounds each sub-batch chain
        # (spec and vanilla pipeline independently since move 2).
        self.pipeline_depth = int(pipeline_depth)
        #: Live in-flight dispatch count (worker-thread writes, metrics
        #: reads — a plain int store, GIL-atomic). 0 when idle/drained;
        #: a pipeline that silently re-serializes never reads above 1.
        self.inflight_depth = 0
        self._busy_mark: float | None = None
        self._key = jax.random.key(seed)
        self._queue: queue.Queue = queue.Queue()
        self._wake = threading.Event()
        self._stop = False
        # Worker-thread writes race metrics/metadata readers; the lock
        # makes snapshots tear-free AND keeps `dict(stats)` safe against
        # the first adapter-request key insertion (an unlocked dict copy
        # concurrent with a key insert can raise RuntimeError).
        self._stats_lock = threading.Lock()
        # guarded-by: _stats_lock
        self.stats = {"requests": 0, "prompt_tokens": 0, "decode_tokens": 0,
                      "decode_seconds": 0.0, "decode_dispatches": 0,
                      "prefix_hits": 0, "prefix_hit_tokens": 0,
                      "prefix_misses": 0, "prefix_stores": 0,
                      "host_stall_seconds": 0.0,
                      "decode_fetch_blocking": 0,
                      "decode_fetch_overlapped": 0,
                      "admit_overlap": 0, "decode_dead_slot_chunks": 0,
                      "decode_wasted_tokens": 0,
                      "spec_dispatches": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_demotions": 0,
                      "spec_readmissions": 0, "spec_stale_rides": 0,
                      "kv_cow_copies": 0, "prefix_zero_copy_hits": 0,
                      # Disaggregation + host tier (ISSUE 13):
                      # prefill_chunks counts prefill/extend dispatches
                      # (a decode-role engine must pin it at 0 —
                      # DISAGGBENCH mechanism assertion), shipped/
                      # received count wire blocks, spilled/restored
                      # the host-tier traffic.
                      "prefill_chunks": 0, "remote_admits": 0,
                      "kv_blocks_shipped": 0, "kv_blocks_received": 0,
                      "kv_spilled_blocks": 0, "kv_restored_blocks": 0,
                      # Quantized KV (ISSUE 19): admission-side
                      # full-width dequant events (prefix-hit fragment
                      # reconstruction / fmt-1 import) and shipped wire
                      # bytes (fmt-3 pays about half fmt-1's).
                      "kv_dequant_fallbacks": 0, "kv_shipment_bytes": 0}
        self._compile()
        from kubeflow_tpu.models.llama import init_cache
        with self._scope():
            cache_sh = None
            if self._cache_sharding is not None:
                cache_sh = {"k": self._cache_sharding,
                            "v": self._cache_sharding}
                if self._rolling:
                    # The pos plane [L, B, W] is tiny i32 bookkeeping —
                    # replicate it.
                    from jax.sharding import NamedSharding, PartitionSpec
                    cache_sh["pos"] = NamedSharding(self._mesh,
                                                    PartitionSpec())
            if self._paged:
                if cache_sh is not None and self.kv_quant != "none":
                    # Scale planes [L, NB+1, bs, KH]: KH shards over
                    # `tensor` exactly like the value planes' head axis
                    # (the scale must be co-resident with its rows).
                    from jax.sharding import NamedSharding, PartitionSpec
                    cache_sh["ks"] = cache_sh["vs"] = NamedSharding(
                        self._mesh,
                        PartitionSpec(*self._cache_sharding.spec[:4]))
                # The pool: kv_blocks usable blocks + NULL block 0. Block
                # axis rides the slot axis's (replicated) spec; heads
                # still shard over `tensor` under TP.
                self._cache = jax.jit(
                    lambda: init_cache(cfg, self._kv_alloc.n_blocks + 1,
                                       self._kv_bs,
                                       kv_quant=self.kv_quant),
                    out_shardings=cache_sh)()
            else:
                self._cache = jax.jit(
                    lambda: init_cache(cfg, self.n_slots, self.max_len),
                    out_shardings=cache_sh)()
            if self._spec is not None:
                dcache_sh = (None if self._dcache_sharding is None else
                             {"k": self._dcache_sharding,
                              "v": self._dcache_sharding})
                if self._paged:
                    # Paged draft KV (ISSUE 18 move 1): the draft gets
                    # its own pool in the SAME block-id space as the
                    # target's (one allocator governs both), so a slot's
                    # draft blocks are ordinary allocations — per-slot,
                    # never prefix-shared, freed with the slot.
                    self._dcache = jax.jit(
                        lambda: init_cache(self._spec["cfg"],
                                           self._kv_alloc.n_blocks + 1,
                                           self._kv_bs),
                        out_shardings=dcache_sh)()
                else:
                    self._dcache = jax.jit(
                        lambda: init_cache(self._spec["cfg"], self.n_slots,
                                           self.max_len),
                        out_shardings=dcache_sh)()
            self._warmup()
        self._slots = [None] * self.n_slots  # per-slot host state
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tpk-generate")
        self._thread.start()

    # -- tensor parallelism --------------------------------------------------

    def _shard_params(self, params, model=None, cfg=None,
                      role: str = "target"):
        """Lay a weight tree out over the mesh by the model's logical
        axis annotations (the same rules engine training uses) and derive
        the matching KV-cache sharding: heads over `tensor`, everything
        else replicated. Each device ends up holding its head group / mlp
        shard; XLA inserts the collectives. Returns (sharded_params,
        cache_sharding) — also used for the DRAFT model under
        spec-decode x TP (role only flavors the error message)."""
        import flax.linen as nn

        from kubeflow_tpu.parallel.sharding import logical_to_spec
        from jax.sharding import NamedSharding

        model = model if model is not None else self.model
        cfg = cfg if cfg is not None else self.cfg
        mesh = self._mesh
        tp = mesh.shape.get("tensor", 1)
        if cfg.num_kv_heads % tp:
            raise ValueError(
                f"tensor parallelism {tp} must divide the {role} model's "
                f"num_kv_heads {cfg.num_kv_heads} (KV heads shard over "
                f"the tensor axis)")
        with mesh, nn.logical_axis_rules(self._rules):
            abstract = jax.eval_shape(
                lambda r: model.init(
                    r, jnp.zeros((1, 8), jnp.int32))["params"],
                jax.random.key(0))
        specs = nn.get_partition_spec(abstract)
        shardings = nn.logical_to_mesh_sharding(specs, mesh, self._rules)
        # Cache layout [L, B, T, KH, D]: KH rides the `heads` rule.
        cache_sharding = NamedSharding(
            mesh, logical_to_spec(("layers", None, None, "heads", "kv"),
                                  self._rules))
        # Callers hand over boxed (fresh init) or plain (orbax-restored)
        # trees; shardings are derived unboxed, so normalize first.
        from jax.sharding import PartitionSpec

        from kubeflow_tpu.serve.quant import Int8Leaf

        def put(leaf, sh):
            if isinstance(leaf, Int8Leaf):
                # int8 x TP: the int8 payload shards exactly like the
                # weight it replaces; the fp32 per-output-channel scales
                # keep the weight's spec on their >1 dims (the size-1
                # contraction dims cannot shard, and the dequantize
                # broadcast needs the scale co-resident with its shard).
                spec = list(sh.spec) + [None] * (leaf.q.ndim - len(sh.spec))
                sspec = [ax if d > 1 else None
                         for ax, d in zip(spec, leaf.scale.shape)]
                return Int8Leaf(
                    jax.device_put(leaf.q, sh),
                    jax.device_put(
                        leaf.scale,
                        NamedSharding(mesh, PartitionSpec(*sspec))))
            return jax.device_put(leaf, sh)

        return (jax.tree.map(put, nn.meta.unbox(params), shardings,
                             is_leaf=lambda x: isinstance(x, Int8Leaf)),
                cache_sharding)

    def _scope(self):
        """Mesh + logical-rules context for tracing/compiling — a no-op
        single-device. Every jit trace happens under this scope so
        in-model `nn.with_logical_constraint`s resolve to mesh axes."""
        import contextlib

        if self._mesh is None:
            return contextlib.nullcontext()
        import flax.linen as nn

        stack = contextlib.ExitStack()
        stack.enter_context(self._mesh)
        stack.enter_context(nn.logical_axis_rules(self._rules))
        return stack

    # -- compiled device functions ------------------------------------------

    def _compile(self):
        # Fragment caches carry headroom of one max bucket past max_len
        # WHEN offset writes can happen — chunked admission, or a prefix-
        # cache hit resuming mid-prompt (either makes _extend write a
        # bucket-wide update at a nonzero offset whose padding may extend
        # past max_len, and dynamic_update_slice would otherwise CLAMP the
        # start index, shifting the write backwards over real prompt rows:
        # silent KV corruption). Pad rows land in the slack and are
        # dropped at insert; real prompt rows never exceed max_len-1
        # (submit bound).
        big = self.prefill_buckets[-1]
        self._may_chunk = big < self.max_len - 1
        offset_writes = self._may_chunk or self._prefix_cap > 0
        fns = build_engine_fns(
            self.model, self.cfg, max_len=self.max_len, chunk=self.chunk,
            prefill_buckets=self.prefill_buckets,
            offset_writes=offset_writes,
            cache_sharding=self._cache_sharding,
            adapters=self._ml_stacks,
            rolling_window=self._rolling,
            kv_block_size=self._kv_bs if self._paged else 0,
            kv_quant=self.kv_quant)
        prefill_jit = jax.jit(fns["prefill"])
        self._prefill = {b: prefill_jit for b in self.prefill_buckets}
        self._extend = jax.jit(fns["extend"], donate_argnums=(1,))
        self._extend_mid = jax.jit(fns["extend_mid"], donate_argnums=(1,))
        if self._paged:
            # Same attribute names, paged signatures: _insert takes the
            # request's scatter table, _decode the per-row block tables
            # (call sites branch on self._paged). Admission fragments
            # (prefill/extend) are identical in both modes.
            self._insert = jax.jit(fns["insert_paged"],
                                   donate_argnums=(0,))
            self._frag_from_pool = jax.jit(fns["frag_from_pool"])
            # KV wire format halves (ISSUE 13): export gathers blocks
            # for a shipment/spill, import scatters shipped blocks in.
            # Jitted lazily on first use — a unified engine that never
            # ships pays nothing. DELIBERATE: one compiled shape each,
            # max_len-blocks wide — the device copy moves the full
            # width and the host slices/pads to the real block count
            # (the HTTP wire carries only committed blocks). Bucketing
            # the width like decode would shrink the D2H/H2D copies for
            # short prompts at the cost of a per-bucket executable
            # pair; revisit when a chip profile shows the copy, not the
            # handoff hop, dominating.
            self._export_blocks = jax.jit(fns["export_blocks"])
            self._import_blocks = jax.jit(fns["import_blocks"],
                                          donate_argnums=(0,))
            self._decode = {
                (b, trunc): jax.jit(fns["make_decode_paged"](trunc, b),
                                    donate_argnums=(1,))
                for b in self.decode_buckets for trunc in (False, True)}
        else:
            self._insert = jax.jit(fns["insert"], donate_argnums=(0,))
            self._decode = {
                (b, trunc): jax.jit(fns["make_decode"](trunc, b),
                                    donate_argnums=(1,))
                for b in self.decode_buckets for trunc in (False, True)}
        if self._spec is not None:
            # The draft runs the SAME admission recipe (chunked cache
            # writes, no sampling — extend_mid) over its own cache tree.
            dfns = build_engine_fns(
                self._spec["model"], self._spec["cfg"],
                max_len=self.max_len, chunk=self.chunk,
                prefill_buckets=self.prefill_buckets,
                offset_writes=True,
                cache_sharding=self._dcache_sharding,
                kv_block_size=self._kv_bs if self._paged else 0)
            self._dextend_mid = jax.jit(dfns["extend_mid"],
                                        donate_argnums=(1,))
            if self._paged:
                # Paged draft pool (ISSUE 18 move 1): insert scatters a
                # replayed draft fragment into the slot's draft blocks;
                # export/import are the wire halves for the shipment's
                # optional draft section (fmt 2) — compiled only on role
                # engines' warmup, like the target's.
                self._dinsert = jax.jit(dfns["insert_paged"],
                                        donate_argnums=(0,))
                self._dexport_blocks = jax.jit(dfns["export_blocks"])
                self._dimport_blocks = jax.jit(dfns["import_blocks"],
                                               donate_argnums=(0,))
            else:
                self._dinsert = jax.jit(dfns["insert"], donate_argnums=(0,))
            self._dfrag_len = dfns["frag_len"]
            from kubeflow_tpu.models.llama import init_cache

            self._dfrag_init = jax.jit(
                lambda: init_cache(self._spec["cfg"], 1, self._dfrag_len))
            spec_make = build_spec_decode(
                self.model, self._spec["model"],
                gamma=self._spec["gamma"], n_spec=self._spec["n_spec"],
                max_len=self.max_len, rolling_window=self._rolling,
                adapters=self._ml_stacks,
                kv_block_size=self._kv_bs if self._paged else 0)
            self._spec_decode = {
                b: jax.jit(spec_make(b), donate_argnums=(2, 3))
                for b in self.decode_buckets}

    def _warmup(self):
        """Pay every compile before serving: one prefill per bucket, one
        insert, one chunked decode (jit caches keyed on static shapes)."""
        zero_t = jnp.zeros((1,), jnp.float32)
        one_l = jnp.ones((1,), jnp.int32)
        zero_k = jnp.zeros((1,), jnp.int32)
        one_p = jnp.ones((1,), jnp.float32)
        aid1 = self._aid1(0)
        frag = None
        for b in self.prefill_buckets:
            frag, _, _ = self._prefill[b](
                self._params, jnp.zeros((1, b), jnp.int32), one_l, zero_t,
                zero_k, one_p, self._key, aid=aid1)
        if self._may_chunk or self._prefix_cap:  # offset-write paths
            # Intermediate chunks always use the largest bucket; the
            # final (sampling) chunk can land on any bucket.
            frag = self._extend_mid(
                self._params, frag,
                jnp.zeros((1, self.prefill_buckets[-1]), jnp.int32),
                zero_k, aid=aid1)
            for b in self.prefill_buckets:
                frag, _, _ = self._extend(
                    self._params, frag, jnp.zeros((1, b), jnp.int32),
                    one_l, zero_k, zero_t, zero_k, one_p, self._key,
                    aid=aid1)
        n = self.n_slots
        if self._paged:
            # All-NULL tables: the warmup writes land in the reserved
            # garbage block, never in allocatable pool blocks.
            mb = self.max_len // self._kv_bs
            self._cache = self._insert(self._cache, frag,
                                       jnp.zeros((mb,), jnp.int32))
            if self._prefix_cap:
                frag = self._frag_from_pool(self._cache,
                                            jnp.zeros((mb,), jnp.int32))
            if self.role != "unified" or self._host_tier is not None:
                # Warm the wire-format halves: a role engine's first
                # handoff (or first spill) must not pay a compile.
                mb = self.max_len // self._kv_bs
                gt = jnp.zeros((mb,), jnp.int32)
                gathered = self._export_blocks(self._cache, gt)
                # All-NULL table: the import lands in the reserved
                # garbage block, never in allocatable pool blocks.
                self._cache = self._import_blocks(self._cache, gathered,
                                                  gt)
            for (b, _), fn in self._decode.items():
                self._cache, _, _ = fn(
                    self._params, self._cache,
                    jnp.zeros((n, b // self._kv_bs), jnp.int32),
                    jnp.zeros((n,), jnp.int32),
                    jnp.zeros((n,), jnp.int32),
                    jnp.zeros((n,), jnp.float32),
                    jnp.zeros((n,), jnp.int32),
                    jnp.ones((n,), jnp.float32),
                    self._key, aid=self._aid_batch([0] * n))
        else:
            self._cache = self._insert(self._cache, frag, jnp.int32(0))
            for fn in self._decode.values():
                self._cache, _, _ = fn(
                    self._params, self._cache, jnp.zeros((n,), jnp.int32),
                    jnp.zeros((n,), jnp.int32),
                    jnp.zeros((n,), jnp.float32),
                    jnp.zeros((n,), jnp.int32),
                    jnp.ones((n,), jnp.float32),
                    self._key, aid=self._aid_batch([0] * n))
        if self._spec is not None:
            dfrag = self._dfrag_init()
            for b in self.prefill_buckets:
                dfrag = self._dextend_mid(
                    self._dparams, dfrag, jnp.zeros((1, b), jnp.int32),
                    zero_k)
            if self._paged:
                mb = self.max_len // self._kv_bs
                # All-NULL scatter/gather tables, like the target's pool
                # warmup: nothing lands in allocatable blocks.
                self._dcache = self._dinsert(self._dcache, dfrag,
                                             jnp.zeros((mb,), jnp.int32))
                if self.role != "unified":
                    gt = jnp.zeros((mb,), jnp.int32)
                    gathered = self._dexport_blocks(self._dcache, gt)
                    self._dcache = self._dimport_blocks(self._dcache,
                                                        gathered, gt)
                for b, fn in self._spec_decode.items():
                    self._cache, self._dcache, _, _, _ = fn(
                        self._params, self._dparams, self._cache,
                        self._dcache,
                        jnp.zeros((n, b // self._kv_bs), jnp.int32),
                        jnp.zeros((n, b // self._kv_bs), jnp.int32),
                        jnp.zeros((n,), jnp.int32),
                        jnp.zeros((n,), jnp.int32),
                        jnp.zeros((n,), jnp.float32), self._key,
                        aid=self._aid_batch([0] * n))
            else:
                self._dcache = self._dinsert(self._dcache, dfrag,
                                             jnp.int32(0))
                for fn in self._spec_decode.values():
                    self._cache, self._dcache, _, _, _ = fn(
                        self._params, self._dparams, self._cache,
                        self._dcache,
                        jnp.zeros((n,), jnp.int32),
                        jnp.zeros((n,), jnp.int32),
                        jnp.zeros((n,), jnp.float32), self._key,
                        aid=self._aid_batch([0] * n))

    # -- multi-LoRA ----------------------------------------------------------

    def _aid1(self, aid: int):
        """[1]-shaped adapter index for admission fns — None when the
        engine has no adapter stacks (base-only trace)."""
        if self._ml_stacks is None:
            return None
        return jnp.asarray([aid], jnp.int32)

    def _aid_batch(self, aids):
        if self._ml_stacks is None:
            return None
        return jnp.asarray(aids, jnp.int32)

    def adapter_names(self) -> list:
        """Loaded multi-LoRA adapter names (the public surface — the
        OpenAI model-id routing and metadata() both read this)."""
        return sorted(self._ml_ids)

    def _resolve_adapter(self, name) -> int:
        if name is None:
            return 0
        if self._ml_stacks is None:
            raise ValueError(
                f"adapter {name!r} requested but the engine has no "
                "adapters configured")
        try:
            return self._ml_ids[name]
        except KeyError:
            raise ValueError(
                f"unknown adapter {name!r}; loaded: "
                f"{sorted(self._ml_ids)}") from None

    # -- public API ----------------------------------------------------------

    def submit(self, input_ids: Sequence[int], *, max_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, eos_id: int | None = None,
               timeout: float = 300.0, adapter: str | None = None,
               deadline: Deadline | None = None, on_tokens=None,
               trace_id: str = "") -> dict:
        """`on_tokens(tokens, done)` (optional) is invoked from the worker
        thread as tokens are emitted — chunk-granular streaming; the final
        call has done=True. Exceptions in the callback are swallowed (a
        slow/broken stream consumer must not stall the decode loop).

        `deadline` is the request's end-to-end budget (resilience.Deadline,
        propagated from the server's timeout header): the scheduler checks
        it at admission and every chunk boundary, and an expired request
        raises DeadlineExceeded AND frees its decode slot — it stops
        burning batch capacity the moment its 504 is decided."""
        if self.role != "unified":
            # Role discipline IS the isolation claim: a decode engine
            # that ran this path would chunk-prefill locally (stealing
            # decode dispatches), a prefill engine would decode.
            raise RuntimeError(
                f"{self.role}-role engine refuses a local generate: "
                "prefill engines take prefill_ship(), decode engines "
                "take submit_remote()")
        if not input_ids:
            raise ValueError("input_ids must be non-empty")
        if len(input_ids) > self.max_len - 1:
            raise ValueError(
                f"prompt of {len(input_ids)} tokens exceeds max_len "
                f"{self.max_len}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if self._paged:
            need = blocks_for(
                self._paged_need_tokens(len(input_ids), int(max_tokens)),
                self._kv_bs)
            if (self._spec is not None and int(top_k) == 0
                    and float(top_p) >= 1.0):
                # Spec-able: the draft pool reserves the same worst-case
                # footprint again (ISSUE 18 move 1).
                need *= 2
            if need > self._kv_alloc.n_blocks:
                # Permanent: even an empty pool can't cover it — shed
                # now (503), don't let it camp in the queue to 504.
                raise KVCapacityExceeded(
                    f"request needs {need} KV blocks worst-case "
                    f"(prompt {len(input_ids)} + max_tokens "
                    f"{int(max_tokens)}) but the pool has "
                    f"{self._kv_alloc.n_blocks}")
        req = {
            "input_ids": [int(t) for t in input_ids],
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "top_p": float(top_p),
            "aid": self._resolve_adapter(adapter),
            "eos_id": eos_id,
            "out": [],
            "out_logprobs": [],
            "done": threading.Event(),
            "error": None,
            "deadline": deadline,
            "t0": time.monotonic(),
            # Trace identity + enqueue mark: the worker records this
            # request's batch-gather span (queue wait → slot admission)
            # and annotates its prefill/decode/fetch spans with the id.
            "trace": trace_id,
            "t_enq": time.perf_counter(),
            "cb": on_tokens,
        }
        self._queue.put(req)
        self._wake.set()
        wait_s = timeout
        if deadline is not None:
            # Wake as soon as the budget expires — the worker notices at
            # the next chunk boundary, but the caller's 504 must not wait
            # for it.
            wait_s = deadline.bound(timeout)
        if not req["done"].wait(wait_s):
            if deadline is not None and deadline.expired():
                req["error"] = DeadlineExceeded(
                    "request deadline expired during generation")
            else:
                req["error"] = f"generation timed out after {timeout}s"
        if isinstance(req["error"], BaseException):
            raise req["error"]
        if req["error"]:
            raise RuntimeError(req["error"])
        return {
            "output_ids": req["out"],
            "output_logprobs": req["out_logprobs"],
            "num_input_tokens": len(req["input_ids"]),
            "num_output_tokens": len(req["out"]),
            "latency_s": time.monotonic() - req["t0"],
        }

    def prefill_ship(self, input_ids: Sequence[int], *,
                     max_tokens: int = 32, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 1.0,
                     eos_id: int | None = None, timeout: float = 300.0,
                     adapter: str | None = None,
                     deadline: Deadline | None = None,
                     trace_id: str = "", extra: dict | None = None) -> dict:
        """Chunk-prefill a prompt into pool blocks and return them as a
        WIRE SHIPMENT instead of decoding (the prefill half of
        disaggregation): committed KV blocks + the prompt tokens + the
        sampled first token/logprob + this engine's post-prefill RNG key
        state, packed by serve/kv_transfer.py. The blocks are released
        back to the pool the moment they are serialized — a prefill
        replica's pool only ever holds in-flight prefills (plus its
        prefix cache, which keeps sharing/spilling as usual).

        `extra` rides the shipment metadata verbatim (the server stashes
        the caller's stream flag there). Returns {"shipment": bytes,
        "num_input_tokens", "first_token", "latency_s"}."""
        if self.role == "decode":
            raise RuntimeError(
                "decode-role engine refuses prefill work (zero prefill "
                "chunks is the disaggregation invariant)")
        if not self._paged:
            raise RuntimeError(
                "prefill_ship needs the paged KV cache (KV blocks are "
                "the wire unit); set kv_block_size > 0")
        if not input_ids:
            raise ValueError("input_ids must be non-empty")
        if len(input_ids) > self.max_len - 1:
            raise ValueError(
                f"prompt of {len(input_ids)} tokens exceeds max_len "
                f"{self.max_len}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        need = blocks_for(len(input_ids), self._kv_bs)
        if (self._spec is not None and int(top_k) == 0
                and float(top_p) >= 1.0):
            need *= 2  # prompt-width draft blocks ride the shipment
        if need > self._kv_alloc.n_blocks:
            raise KVCapacityExceeded(
                f"prompt needs {need} KV blocks but the pool has "
                f"{self._kv_alloc.n_blocks}")
        req = {
            "mode": "ship",
            "input_ids": [int(t) for t in input_ids],
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "top_p": float(top_p),
            "aid": self._resolve_adapter(adapter),
            "adapter": adapter,
            "eos_id": eos_id,
            "timeout": float(timeout),
            "extra": dict(extra or {}),
            "out": [], "out_logprobs": [],
            "done": threading.Event(),
            "error": None,
            "result": None,
            "deadline": deadline,
            "t0": time.monotonic(),
            "trace": trace_id,
            "t_enq": time.perf_counter(),
            "cb": None,
        }
        self._queue.put(req)
        self._wake.set()
        wait_s = deadline.bound(timeout) if deadline is not None else timeout
        if not req["done"].wait(wait_s):
            if deadline is not None and deadline.expired():
                req["error"] = DeadlineExceeded(
                    "request deadline expired during prefill")
            else:
                req["error"] = f"prefill timed out after {timeout}s"
        if isinstance(req["error"], BaseException):
            raise req["error"]
        if req["error"]:
            raise RuntimeError(req["error"])
        out = dict(req["result"])
        out["latency_s"] = time.monotonic() - req["t0"]
        return out

    def submit_remote(self, shipment, *, timeout: float | None = None,
                      deadline: Deadline | None = None, on_tokens=None,
                      trace_id: str = "") -> dict:
        """Admit a shipped prefill (prefill_ship bytes) straight into
        decode — the decode half of disaggregation. The shipped blocks
        scatter into this pool under a freshly reserved table (full
        decode-budget reservation, exactly the local admission
        discipline — transient exhaustion stashes head-of-line in
        `_kv_stash` like any admission), the shipped first token seeds
        the decode carry, and the shipped RNG key state is adopted so a
        single disaggregated stream is token+logprob-identical to the
        unified engine on the same seed. Sampling params AND the
        caller's request timeout travel IN the shipment (they were
        fixed at prefill; `timeout=None` adopts the shipped budget so
        a role split never silently shrinks it). Never runs a prefill
        chunk."""
        if self.role == "prefill":
            raise RuntimeError(
                "prefill-role engine refuses decode work; route "
                "shipments to a decode or unified replica")
        if not self._paged:
            raise RuntimeError(
                "submit_remote needs the paged KV cache; set "
                "kv_block_size > 0")
        meta, arrays = unpack_shipment(shipment)
        fmt = int(meta.get("fmt", 0))
        if fmt not in (1, 2, 3):
            raise ShipmentError(
                f"unknown shipment fmt {meta.get('fmt')!r}")
        if fmt == 3 and self.kv_quant == "none":
            # Never silently dequant-upcast: accepting quantized blocks
            # into a full-precision pool would make this stream's
            # numerics depend on WHICH replica prefilled it — the
            # fleet-skew failure mode the compat guard exists to refuse.
            raise ShipmentError(
                f"shipment fmt 3 carries {meta.get('kv_quant')!r}-"
                "quantized KV blocks but this engine runs "
                "kv_quant='none'; pair quantized prefill replicas with "
                "decode replicas running the same kv_quant (or drop "
                "generative.kv_quant fleet-wide)")
        if fmt == 3 and str(meta.get("kv_quant")) != self.kv_quant:
            raise ShipmentError(
                f"shipment kv_quant {meta.get('kv_quant')!r} != this "
                f"engine's {self.kv_quant!r} — mixed-precision fleets "
                "cannot exchange KV blocks (align generative.kv_quant)")
        if fmt == 2 and self._spec is None:
            # The versioned draft section is refused loudly, never
            # silently dropped: a fleet pairing draft-carrying prefill
            # replicas with draft-less decode replicas is misconfigured
            # (the decode side would re-pay the replay the shipment
            # exists to avoid) and must surface at submit.
            raise ShipmentError(
                "shipment fmt 2 carries a draft-KV section but this "
                "engine has no draft model; pair draft-carrying "
                "prefill replicas with draft-configured decode "
                "replicas (or drop generative.draft fleet-wide)")
        if int(meta.get("block_size", 0)) != self._kv_bs:
            raise ShipmentError(
                f"shipment block_size {meta.get('block_size')} != this "
                f"pool's {self._kv_bs} (pair replicas with identical "
                "kv_block_size)")
        if int(meta.get("vocab_size", 0)) != int(self.cfg.vocab_size):
            raise ShipmentError(
                f"shipment vocab {meta.get('vocab_size')} != model "
                f"vocab {self.cfg.vocab_size}")
        ids = [int(t) for t in meta["tokens"]]
        if not ids or len(ids) > self.max_len - 1:
            raise ShipmentError(
                f"shipped prompt of {len(ids)} tokens does not fit "
                f"max_len {self.max_len}")
        n_blocks = blocks_for(len(ids), self._kv_bs)
        mb = self.max_len // self._kv_bs
        ref = self._cache["k"].shape  # [L, NB+1, bs, KH, D]
        quantized = self.kv_quant != "none"
        if quantized and fmt != 3:
            # fmt-1 full-precision blocks into a quantized pool:
            # quantize at import, host-side at the admission boundary,
            # with the SAME encode decode writes and local admission use
            # — so a remotely prefilled row reaches the identical bytes
            # a local prefill of the same prompt would have written.
            for name in ("k", "v"):
                arr = arrays.get(name)
                if arr is None:
                    raise ShipmentError(
                        f"shipment missing {name!r} blocks")
                q, s = kv_quantize_rows(jnp.asarray(arr), self.kv_quant)
                arrays[name] = np.asarray(q)
                arrays[name + "s"] = np.asarray(s)
        blocks = {}
        for name in (("k", "v", "ks", "vs") if quantized
                     else ("k", "v")):
            arr = arrays.get(name)
            if arr is None:
                raise ShipmentError(f"shipment missing {name!r} blocks")
            lref = self._cache[name].shape  # scale planes drop the D axis
            want = (lref[0], n_blocks, *lref[2:])
            if tuple(arr.shape) != want:
                raise ShipmentError(
                    f"shipment {name} blocks shaped {tuple(arr.shape)}, "
                    f"this engine needs {want}")
            # Pad to the compiled [mb]-block import width; pads scatter
            # into the NULL block.
            pad = np.zeros((lref[0], mb, *lref[2:]), arr.dtype)
            pad[:, :n_blocks] = arr
            blocks[name] = pad
        draft_blocks = None
        dn_blocks = 0
        if fmt == 2:
            dmeta = dict(meta.get("draft") or {})
            dref = self._dcache["k"]
            if (int(dmeta.get("block_size", 0)) != self._kv_bs
                    or int(dmeta.get("vocab_size", 0))
                    != int(self._spec["cfg"].vocab_size)
                    or int(dmeta.get("num_layers", 0)) != int(dref.shape[0])
                    or list(dmeta.get("kv_shape", ()))
                    != list(dref.shape[2:])
                    or str(dmeta.get("dtype")) != str(dref.dtype)):
                raise ShipmentError(
                    f"shipment draft section {dmeta} does not match "
                    f"this engine's draft model (layers={dref.shape[0]}, "
                    f"kv_shape={list(dref.shape[2:])}, "
                    f"dtype={dref.dtype}, block_size={self._kv_bs}) — "
                    "mixed-precision or mixed-config fleets cannot "
                    "exchange draft KV")
            dn_blocks = int(dmeta.get("n_blocks", 0))
            if dn_blocks < 1 or dn_blocks > mb:
                raise ShipmentError(
                    f"shipment draft section claims {dn_blocks} blocks; "
                    f"this engine fits at most {mb}")
            draft_blocks = {}
            for name in ("k", "v"):
                arr = arrays.get("draft_" + name)
                if arr is None:
                    raise ShipmentError(
                        f"fmt 2 shipment missing draft_{name!r} blocks")
                want = (dref.shape[0], dn_blocks, *dref.shape[2:])
                if tuple(arr.shape) != want:
                    raise ShipmentError(
                        f"shipment draft_{name} blocks shaped "
                        f"{tuple(arr.shape)}, this engine needs {want}")
                pad = np.zeros((dref.shape[0], mb, *dref.shape[2:]),
                               arr.dtype)
                pad[:, :dn_blocks] = arr
                draft_blocks[name] = pad
        if timeout is None:
            timeout = float(meta.get("timeout", 300.0))
        max_tokens = int(meta.get("max_tokens", 32))
        need = blocks_for(self._paged_need_tokens(len(ids), max_tokens),
                          self._kv_bs)
        if (self._spec is not None and int(meta.get("top_k", 0)) == 0
                and float(meta.get("top_p", 1.0)) >= 1.0):
            need *= 2  # worst-case: the draft table mirrors the target's
        if need > self._kv_alloc.n_blocks:
            raise KVCapacityExceeded(
                f"shipped request needs {need} KV blocks worst-case but "
                f"the pool has {self._kv_alloc.n_blocks}")
        req = {
            "mode": "remote",
            "input_ids": ids,
            "max_tokens": max_tokens,
            "temperature": float(meta.get("temperature", 0.0)),
            "top_k": int(meta.get("top_k", 0)),
            "top_p": float(meta.get("top_p", 1.0)),
            "aid": self._resolve_adapter(meta.get("adapter")),
            "eos_id": meta.get("eos_id"),
            "first_tok": int(meta["first_token"]),
            "first_lp": float(meta["first_logprob"]),
            "kv_blocks": blocks,
            "n_blocks": n_blocks,
            "draft_blocks": draft_blocks,
            "dn_blocks": dn_blocks,
            "rng_key": arrays.get("rng_key"),
            "out": [], "out_logprobs": [],
            "done": threading.Event(),
            "error": None,
            "deadline": deadline,
            "t0": time.monotonic(),
            # The trace id rides the shipment meta too (router-stamped
            # via rewrite_meta): a caller that didn't thread an explicit
            # id still joins the request's distributed trace.
            "trace": trace_id or str(meta.get("trace") or ""),
            "t_enq": time.perf_counter(),
            "cb": on_tokens,
        }
        self._queue.put(req)
        self._wake.set()
        wait_s = deadline.bound(timeout) if deadline is not None else timeout
        if not req["done"].wait(wait_s):
            if deadline is not None and deadline.expired():
                req["error"] = DeadlineExceeded(
                    "request deadline expired during generation")
            else:
                req["error"] = f"generation timed out after {timeout}s"
        if isinstance(req["error"], BaseException):
            raise req["error"]
        if req["error"]:
            raise RuntimeError(req["error"])
        return {
            "output_ids": req["out"],
            "output_logprobs": req["out_logprobs"],
            "num_input_tokens": len(ids),
            "num_output_tokens": len(req["out"]),
            "latency_s": time.monotonic() - req["t0"],
        }

    def close(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5.0)

    # -- worker --------------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    # -- prefix cache --------------------------------------------------------

    def _prefix_lookup(self, ids: list[int],
                       aid: int = 0) -> tuple[int, Any] | None:
        """Longest cached chunk-boundary prefix STRICTLY shorter than the
        prompt (the final token's logits must still be computed). Keys
        carry the ADAPTER index: a prefix computed under adapter X holds
        X's K/V deltas and must never serve a request under adapter Y.

        Fast path (ISSUE 3): entries are keyed `(aid, n, hash(tokens))`
        and a per-adapter length index drives the probe — one O(n) hash
        per DISTINCT cached length (longest first) instead of the seed's
        O(cap × len) scan with a full token-list compare per entry. The
        stored token tuple still verifies each hash hit, so a collision
        can only cost a miss, never a wrong fragment.
        Returns (matched_len, fresh fragment copy) or None."""
        lens = self._prefix_lens.get(aid)
        if not lens:
            return None
        for n in sorted(lens, reverse=True):
            if n >= len(ids):
                continue
            kt = tuple(ids[:n])
            key = (aid, n, hash(kt))
            entry = self._prefix_lru.get(key)
            if entry is None or entry[0] != kt:
                continue  # absent, or a same-hash different prefix
            self._prefix_lru.move_to_end(key)
            return n, jax.tree.map(jnp.copy, entry[1])
        return None

    def _prefix_store(self, aid: int, kt: tuple, frag, *,
                      copy: bool = True) -> None:
        """Snapshot a fragment at a prompt-chunk boundary. Rows past the
        keyed prefix may hold pad/stale K/V — safe, because any reader
        overwrites row i before its query positions can reach it (absolute-
        position masking hides rows above the current index).

        `copy=False` takes `frag` by reference — used for an admission's
        FINAL fragment, which nothing donates afterwards (`_insert`
        donates the slot cache, not the fragment) and which every lookup
        hit copies out of, so the stored tree is never mutated. A store
        whose key is already resident is a pure LRU touch (no device
        work)."""
        key = (aid, len(kt), hash(kt))
        existing = self._prefix_lru.get(key)
        if existing is not None and existing[0] == kt:
            self._prefix_lru.move_to_end(key)
            return
        if existing is None:
            per = self._prefix_lens.setdefault(aid, {})
            per[len(kt)] = per.get(len(kt), 0) + 1
        self._prefix_lru[key] = (kt, frag if not copy
                                 else jax.tree.map(jnp.copy, frag))
        self._prefix_lru.move_to_end(key)
        with self._stats_lock:
            self.stats["prefix_stores"] += 1
        while len(self._prefix_lru) > self._prefix_cap:
            self._prefix_evict_oldest()

    # -- paged KV (block-table) admission ------------------------------------

    def _paged_need_tokens(self, prompt: int, max_tokens: int) -> int:
        """Worst-case cache rows a request can ever WRITE: the prompt
        plus its decode budget rounded up to whole dispatch chunks (the
        retirement chunk still writes its full width), capped at max_len
        (decode indices clamp at bucket-1, so no write ever lands past
        row max_len-1). Blocks covering this are reserved whole at
        admission — allocation never happens on the decode critical
        path, which is what lets paging compose with pipeline_depth>1
        without new host syncs. Dead in-flight chunks past a retirement
        may write beyond this bound; those rows map to NULL-block table
        pads, never to another request's blocks."""
        chunks = -(-max(int(max_tokens), 1) // self.chunk)
        return min(self.max_len, prompt + chunks * self.chunk)

    def _spec_able(self, req: dict) -> bool:
        """A request rides the spec sub-batch iff it has no truncated
        sampling: greedy and plain-temperature rows compose with the
        rejection scheme; top-k/top-p rows decode on the vanilla
        sub-batch (ISSUE 18 move 2 — per-request, not batch-wide)."""
        return (self._spec is not None
                and req.get("top_k", 0) == 0
                and req.get("top_p", 1.0) >= 1.0)

    def _draft_need_blocks(self, req: dict) -> int:
        """Worst-case DRAFT pool blocks a spec-able request reserves on
        top of the target's (ISSUE 18 move 1): the same bound as the
        target's, because the draft cache mirrors the committed index.
        Draft blocks are per-slot private working state — never
        prefix-shared, never discounted by a hit. Ship-mode reserves
        prompt blocks only, like the target (the decode replica reserves
        the decode budget)."""
        if not (self._paged and self._spec_able(req)):
            return 0
        ids = req["input_ids"]
        if req.get("mode") == "ship":
            return blocks_for(len(ids), self._kv_bs)
        return blocks_for(
            self._paged_need_tokens(len(ids), req["max_tokens"]),
            self._kv_bs)

    def _prefix_probe_paged(self, ids: list[int], aid: int, *,
                            touch: bool) -> tuple[int, tuple] | None:
        """Paged twin of `_prefix_lookup`: longest strictly-shorter
        cached prefix, returning its resident BLOCK IDS instead of a
        fragment copy. `touch=False` is the read-only peek the
        admission-fit check uses (no LRU reorder, no stats)."""
        lens = self._prefix_lens.get(aid)
        if not lens:
            return None
        for n in sorted(lens, reverse=True):
            if n >= len(ids):
                continue
            kt = tuple(ids[:n])
            key = (aid, n, hash(kt))
            entry = self._prefix_lru.get(key)
            if entry is None or entry[0] != kt:
                continue
            if touch:
                self._prefix_lru.move_to_end(key)
            return n, entry[1]
        return None

    def _prefix_store_paged(self, aid: int, kt: tuple,
                            blocks: list[int]) -> None:
        """Publish a prompt-boundary prefix as block REFERENCES
        (refcount bump — no fragment copy, no device work). The stored
        tail block may be partially filled; its owner keeps appending at
        rows >= len(kt), which never disturbs the committed rows a later
        hit reads, and the hit forks that block before writing (CoW)."""
        key = (aid, len(kt), hash(kt))
        existing = self._prefix_lru.get(key)
        if existing is not None and existing[0] == kt:
            self._prefix_lru.move_to_end(key)
            return
        if existing is None:
            per = self._prefix_lens.setdefault(aid, {})
            per[len(kt)] = per.get(len(kt), 0) + 1
        else:
            # Hash-collision overwrite: the displaced entry's block refs
            # must be dropped or they leak out of the pool forever (the
            # flat cache's displaced fragment was simply GC'd; the
            # refcounted twin needs the explicit release).
            self._kv_alloc.decref(existing[1])
        self._kv_alloc.incref(blocks)
        self._prefix_lru[key] = (kt, tuple(blocks))
        self._prefix_lru.move_to_end(key)
        with self._stats_lock:
            self.stats["prefix_stores"] += 1
        while len(self._prefix_lru) > self._prefix_cap:
            self._prefix_evict_oldest()

    def _prefix_evict_oldest(self) -> None:
        self._prefix_evict(next(iter(self._prefix_lru)))

    def _prefix_evict(self, key: tuple) -> None:
        """Drop one prefix entry + its length-index bookkeeping — shared
        by both cache flavors. The payload is a fragment tree (flat:
        Python GC reclaims it) or a block-id tuple (paged: the refs must
        be returned to the allocator explicitly). With a host tier
        configured, a paged eviction SPILLS the blocks first (cold
        blocks move down-tier instead of vanishing — restore-on-hit
        brings them back, lifting the effective pool beyond HBM)."""
        if self._paged and self._host_tier is not None:
            kt, blocks = self._prefix_lru[key]
            self._spill_prefix(key, kt, blocks)
        _, payload = self._prefix_lru.pop(key)
        eaid, en, _ = key
        per = self._prefix_lens.get(eaid, {})
        if per.get(en, 0) <= 1:
            per.pop(en, None)
            if not per:
                self._prefix_lens.pop(eaid, None)
        else:
            per[en] -= 1
        if self._paged:
            self._kv_alloc.decref(payload)

    def _kv_fits(self, req: dict) -> bool:
        """Admission-by-free-blocks (the paged replacement for "is a
        static slot free"): can the pool cover this request's worst-case
        need right now, counting zero-copy shared prefix blocks? Under
        pressure, LRU prefix-cache entries are reclaimed first — cached
        prefixes must yield to live traffic, or a pool fully pinned by
        cache references would deadlock an idle engine against a stashed
        admission.

        Reclaim discipline: the feasibility bound is computed ONCE (a
        block counts as reclaimable only when every ref on it is a
        cache ref — live tables pin the rest), for BOTH outcomes:
        keeping the peeked zero-copy hit (discounted need, hit's blocks
        unreclaimable) and sacrificing it (full need, everything
        reclaimable). If neither can ever fit, nothing is evicted at
        all. Otherwise non-hit entries go first, oldest-first, and the
        hit itself is evicted only when sacrificing its discount is the
        only way to fit — an admission can never wipe the cache while
        freeing nothing, and never destroys its own hit needlessly."""
        ids = req["input_ids"]
        mode = req.get("mode")
        if mode == "ship":
            # Prefill-only: the decode budget is the DECODE replica's
            # to reserve; this pool holds just the prompt blocks until
            # the shipment serializes.
            total = blocks_for(len(ids), self._kv_bs)
        else:
            total = blocks_for(
                self._paged_need_tokens(len(ids), req["max_tokens"]),
                self._kv_bs)
        # Spec-able requests also cover the draft pool's footprint —
        # fresh blocks only, so the prefix-hit discount never applies.
        total += self._draft_need_blocks(req)
        aid = req.get("aid", 0)
        # Remote admissions never discount by a prefix hit: their blocks
        # arrive on the wire and the reserve below allocates the FULL
        # need — a discount here could pass a request the reserve can
        # never satisfy (permanent head-of-line stall).
        hit = (self._prefix_probe_paged(ids, aid, touch=False)
               if self._prefix_cap and mode != "remote" else None)
        shared = hit[0] // self._kv_bs if hit is not None else 0
        hit_key = ((aid, hit[0], hash(tuple(ids[:hit[0]])))
                   if hit is not None else None)
        if self._kv_alloc.can_alloc(total - shared):
            return True
        if not self._prefix_lru:
            return False
        cache_refs: dict[int, int] = {}
        for _, eblocks in self._prefix_lru.values():
            for b in eblocks:
                cache_refs[b] = cache_refs.get(b, 0) + 1
        hit_blocks = set(hit[1]) if hit is not None else set()
        free = self._kv_alloc.free_blocks
        reclaim_all = sum(1 for b, c in cache_refs.items()
                          if self._kv_alloc.refcount(b) == c)
        reclaim_keep_hit = sum(1 for b, c in cache_refs.items()
                               if self._kv_alloc.refcount(b) == c
                               and b not in hit_blocks)
        keep_hit = (hit_key is not None
                    and free + reclaim_keep_hit >= total - shared)
        if not keep_hit and free + reclaim_all < total:
            return False
        protect = hit_key if keep_hit else None
        while True:
            resident = (hit_key is not None
                        and hit_key in self._prefix_lru)
            disc = shared if resident else 0
            if self._kv_alloc.can_alloc(total - disc):
                return True
            victim = next((k for k in self._prefix_lru if k != protect),
                          None)
            if victim is None:
                return False  # unreachable under the exact bounds above
            self._prefix_evict(victim)

    def _free_slot_blocks(self, st: dict) -> None:
        """Return a retired request's block references to the pool
        (idempotent — the pop guards double-retirement paths). Blocks
        still referenced by the prefix cache or by zero-copy sharers
        survive; in-flight dead chunks may still write to truly-freed
        blocks, which is safe because any re-admission's insert is
        dispatched AFTER them and rewrites every block it was handed
        (device stream order is dispatch order)."""
        if not self._paged:
            return
        blocks = st.pop("blocks", None)
        if blocks:
            self._kv_alloc.decref(blocks)
        dblocks = st.pop("dblocks", None)
        if dblocks:
            self._kv_alloc.decref(dblocks)

    @property
    def kv_blocks_free(self):
        return self._kv_alloc.free_blocks if self._paged else None

    @property
    def kv_blocks_used(self):
        return self._kv_alloc.used_blocks if self._paged else None

    def kv_info(self) -> dict | None:
        """Paged-pool snapshot for metadata()/debugging (None = flat)."""
        if not self._paged:
            return None
        info = {"block_size": self._kv_bs,
                "blocks": self._kv_alloc.n_blocks,
                "blocks_free": self._kv_alloc.free_blocks,
                "blocks_used": self._kv_alloc.used_blocks}
        if self._host_tier is not None:
            info["host_tier"] = self._host_tier.stats_snapshot()
        return info

    @property
    def kv_spill_blocks(self):
        """Host-tier resident blocks (None = no tier) — the
        tpk_kv_spill_blocks gauge."""
        return (self._host_tier.resident_blocks
                if self._host_tier is not None else None)

    def _admit_inner_paged(self, slot: int, req: dict) -> None:
        """Paged admission: the fragment pipeline (prefill/extend over a
        contiguous fragment cache) is IDENTICAL to flat — only where the
        fragment lands differs (scatter into this request's blocks
        instead of a slot row), plus the block-table bookkeeping:

          * zero-copy prefix hit: the stored prefix's fully-committed
            blocks map into this table by reference (refcount bump);
            only the partially-filled tail block is forked — its
            committed rows ride the fragment into a fresh block, which
            IS the copy-on-write copy (`kv_cow_copies`).
          * the whole worst-case block need is allocated here, off the
            decode critical path (see `_paged_need_tokens`).

        The chunked-prefill loop is a deliberate textual copy of
        `_admit_inner`'s (flat must stay byte-untouched); the
        `admit-chunked-prefill` / `admit-slot-state` tpk-sync regions
        enforce the twinning mechanically — a fix landing in only one
        loop fails tier-1 (rule sync-regions) instead of breaking the
        seeded flat-vs-paged identity test at runtime.
        """
        ids = req["input_ids"]
        aid = req.get("aid", 0)
        aid1 = self._aid1(aid)
        bs = self._kv_bs
        mb = self.max_len // bs
        sample_args = (
            jnp.asarray([req["temperature"]], jnp.float32),
            jnp.asarray([req.get("top_k", 0)], jnp.int32),
            jnp.asarray([req.get("top_p", 1.0)], jnp.float32),
        )
        big = self.prefill_buckets[-1]
        frag, tok0, done = None, None, 0
        shared: list[int] = []
        gather_tbl: tuple | None = None
        cow_fork = False
        hit = None
        ship = req.get("mode") == "ship"
        if self._prefix_cap:
            hit = self._prefix_probe_paged(ids, aid, touch=True)
            if hit is None and self._host_tier is not None:
                # Host-tier restore-on-hit: a prefix spilled under pool
                # pressure comes back through the same wire format and
                # re-publishes as an HBM cache entry before this
                # admission consumes it like any zero-copy hit. The
                # request rides along so the restore can prove THIS
                # admission still fits afterwards (see the livelock
                # note in _restore_spilled).
                hit = self._restore_spilled(ids, aid, req)
            if hit is not None:
                done, hit_blocks = hit
                shared = list(hit_blocks[:done // bs])
                cow_fork = done % bs > 0
                gather_tbl = hit_blocks
        if ship:
            # Prompt blocks only — see _kv_fits; the decode budget is
            # reserved by the decode replica at submit_remote.
            need = blocks_for(len(ids), bs)
            fresh = self._kv_alloc.alloc(max(0, need - len(shared)))
            if fresh is None:
                raise _NeedKVBlocks()
        else:
            # _admit_waiting's _kv_fits precheck makes the reserve
            # failure unreachable in the normal flow; defense against
            # future reordering. The remote-admit twin must reserve by
            # the IDENTICAL worst-case rule — a drifted copy would let
            # a shipped request out-reserve (or under-reserve) a local
            # one and break the pool accounting.
            # tpk-sync: begin kv-block-reserve admit
            need = blocks_for(
                self._paged_need_tokens(len(ids), req["max_tokens"]),
                bs)
            fresh = self._kv_alloc.alloc(max(0, need - len(shared)))
            if fresh is None:
                raise _NeedKVBlocks()
            # tpk-sync: end kv-block-reserve
        # Draft blocks ride the same pool, per-slot and never
        # prefix-shared (the draft cache holds draft-model activations —
        # a target prefix block would be garbage to it). Allocated
        # atomically with the target reserve: both or neither, so the
        # _kv_fits precheck (which counts both) stays the single
        # admission gate.
        dtable: list[int] | None = None
        dneed = self._draft_need_blocks(req)
        if dneed:
            dtable = self._kv_alloc.alloc(dneed)
            if dtable is None:
                self._kv_alloc.decref(fresh)
                raise _NeedKVBlocks()
        if self._prefix_cap:
            with self._stats_lock:
                if hit is not None:
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_hit_tokens"] += done
                    if shared:
                        self.stats["prefix_zero_copy_hits"] += 1
                    if cow_fork:
                        self.stats["kv_cow_copies"] += 1
                else:
                    self.stats["prefix_misses"] += 1
        self._kv_alloc.incref(shared)
        table = shared + fresh
        boundaries: list[int] = []
        start_done = done
        try:
            if gather_tbl is not None:
                # Resume chunked prefill mid-prompt: seed the fragment
                # from the hit's blocks (includes the partial tail —
                # read-only; its committed rows become the fork copy).
                gt = np.zeros((mb,), np.int32)
                gt[:len(gather_tbl)] = gather_tbl
                frag = self._frag_from_pool(self._cache, jnp.asarray(gt))
                if self.kv_quant != "none":
                    # The ONE full-width dequant materialization the
                    # quantized design permits (admission-side fragment
                    # rebuild, outside any scan) — counted so a fleet
                    # can see when prefix-hit traffic pays it.
                    with self._stats_lock:
                        self.stats["kv_dequant_fallbacks"] += 1
            # tpk-sync: begin admit-chunked-prefill paged
            # tpk-sync: sub self._prefix_store(aid, tuple(ids[:done]), frag, copy=done < len(ids)) -> boundaries.append(done)
            while done < len(ids):
                piece = ids[done:done + big]
                final = done + len(piece) >= len(ids)
                bucket = self._bucket_for(len(piece))
                toks = np.zeros((1, bucket), np.int32)
                toks[0, :len(piece)] = piece
                if done == 0:
                    self._key, sub = jax.random.split(self._key)
                    frag, tok0, lp0 = self._prefill[bucket](
                        self._params, jnp.asarray(toks),
                        jnp.asarray([len(piece)], jnp.int32),
                        *sample_args, sub, aid=aid1)
                elif final:
                    self._key, sub = jax.random.split(self._key)
                    frag, tok0, lp0 = self._extend(
                        self._params, frag, jnp.asarray(toks),
                        jnp.asarray([len(piece)], jnp.int32),
                        jnp.asarray([done], jnp.int32), *sample_args,
                        sub, aid=aid1)
                else:  # intermediate chunk: no sampling, no unembedding
                    frag = self._extend_mid(
                        self._params, frag, jnp.asarray(toks),
                        jnp.asarray([done], jnp.int32), aid=aid1)
                done += len(piece)
                if self._prefix_cap:
                    # Same boundary gate as flat (skip entries a later
                    # boundary of this admission would immediately
                    # evict); the store itself is deferred until the
                    # blocks are written by the insert below.
                    chunks_left = -(-(len(ids) - done) // big)
                    if chunks_left < self._prefix_cap:
                        boundaries.append(done)
            # tpk-sync: end admit-chunked-prefill
            # Scatter table: shared prefix blocks masked to NULL (their
            # rows are already resident and immutable), owned blocks
            # receive their fragment rows — including the CoW fork and
            # the pad/garbage tail that decode will overwrite in place.
            st_tbl = np.zeros((mb,), np.int32)
            st_tbl[len(shared):len(table)] = fresh
            self._cache = self._insert(self._cache, frag,
                                       jnp.asarray(st_tbl))
            if dtable is not None:
                # The draft must hold the same prompt history (flat
                # admission's rule): chunked replay over the draft's own
                # fragment cache, scattered into this slot's draft
                # blocks. Never prefix-shared, so the whole table is a
                # fresh scatter target.
                dt = np.zeros((mb,), np.int32)
                dt[:len(dtable)] = dtable
                self._dcache = self._dinsert(self._dcache,
                                             self._draft_replay(ids),
                                             jnp.asarray(dt))
        except BaseException:
            self._kv_alloc.decref(table)
            if dtable is not None:
                self._kv_alloc.decref(dtable)
            raise
        for m in boundaries:
            self._prefix_store_paged(aid, tuple(ids[:m]),
                                     table[:blocks_for(m, bs)])
        with self._stats_lock:
            self.stats["prefill_chunks"] += -(-(len(ids) - start_done)
                                              // big)
        if ship:
            self._finish_ship(req, table, tok0, lp0, dtable)
            return
        draft_ok = dtable is not None
        # tpk-sync: begin admit-slot-state paged
        # tpk-sync: sub 'aid': aid} -> 'aid': aid, 'blocks': table, 'dblocks': dtable}
        st = {"req": req, "idx": len(ids), "disp": len(ids), "last": None,
              "pending": None, "draft_ok": draft_ok, "aid": aid,
              "blocks": table, "dblocks": dtable}
        if self.pipeline_depth > 1:
            for arr in (tok0, lp0):
                getattr(arr, "copy_to_host_async", lambda: None)()
            st["pending"] = (tok0, lp0)
            self._slots[slot] = st
        else:
            st["last"] = int(tok0[0])
            self._slots[slot] = st
        with self._stats_lock:
            self.stats["requests"] += 1
            self.stats["prompt_tokens"] += len(ids)
            if aid:
                per = dict(self.stats.get("adapter_requests", {}))
                name = self._ml_names[aid]
                per[name] = per.get(name, 0) + 1
                self.stats["adapter_requests"] = per
        if st["pending"] is None:
            self._emit(slot, st, [st["last"]], [float(lp0[0])])
        # tpk-sync: end admit-slot-state

    def _finish_ship(self, req: dict, table: list[int], tok0,
                     lp0, dtable: list[int] | None = None) -> None:
        """Serialize a ship-mode admission's committed blocks into the
        wire format and release them. Runs on the worker thread right
        after the fragment insert. The fetches here ARE device syncs:
        on a prefill-role engine there are never decode chunks in
        flight to stall, which is the design point. KNOWN COST on an
        "any"-role replica playing the prefill phase (the symmetric
        role_split fallback): each handoff's export fetch completes
        behind any in-flight decode dispatches — a per-handoff stall
        the dedicated prefill role exists to avoid. Prefer real
        prefill replicas under mixed load; the fallback trades tail
        latency for not stranding decode specialists."""
        ids = req["input_ids"]
        mb = self.max_len // self._kv_bs
        gt = np.zeros((mb,), np.int32)
        gt[:len(table)] = table
        gathered = self._export_blocks(self._cache, jnp.asarray(gt))
        arrays = {name: np.asarray(leaf)[:, :len(table)]
                  for name, leaf in gathered.items()}
        draft_meta = None
        if dtable is not None:
            # Optional draft-block section (fmt 2): the decode replica
            # speculates from position 0 without replaying the prompt
            # through its own draft. The section's config identity lets
            # a mismatched fleet refuse loudly at submit_remote instead
            # of decoding garbage.
            dgt = np.zeros((mb,), np.int32)
            dgt[:len(dtable)] = dtable
            dgathered = self._dexport_blocks(self._dcache,
                                             jnp.asarray(dgt))
            for name, leaf in dgathered.items():
                arrays["draft_" + name] = np.asarray(leaf)[:, :len(dtable)]
            self._kv_alloc.decref(dtable)
            dref = self._dcache["k"]
            draft_meta = {
                "block_size": self._kv_bs,
                "vocab_size": int(self._spec["cfg"].vocab_size),
                "n_blocks": len(dtable),
                "kv_shape": list(dref.shape[2:]),
                "num_layers": int(dref.shape[0]),
                "dtype": str(dref.dtype),
            }
        # Post-prefill RNG state: a decode engine adopting it continues
        # the exact key-split stream the unified engine would have used
        # (the disagg-vs-unified identity pin).
        arrays["rng_key"] = np.asarray(jax.random.key_data(self._key))
        first_tok = int(np.asarray(tok0)[0])
        # fmt 3: quantized blocks — the arrays dict already carries the
        # ks/vs scale planes (export is tree-generic over the pool), so
        # the wire ships quantized bytes + f32 scales, ≈2× smaller than
        # the same blocks at fmt 1. kv_quant in the meta lets the decode
        # side refuse a precision-skewed fleet loudly at submit_remote.
        # (fmt 2 never combines: kv_quant × draft is refused at init.)
        meta = {
            "fmt": (2 if draft_meta is not None
                    else 3 if self.kv_quant != "none" else 1),
            "block_size": self._kv_bs,
            "vocab_size": int(self.cfg.vocab_size),
            "tokens": list(ids),
            "committed": len(ids),
            "first_token": first_tok,
            "first_logprob": float(np.asarray(lp0)[0]),
            "max_tokens": req["max_tokens"],
            "temperature": req["temperature"],
            "top_k": req.get("top_k", 0),
            "top_p": req.get("top_p", 1.0),
            "eos_id": req.get("eos_id"),
            "adapter": req.get("adapter"),
            # The CALLER's request timeout rides the shipment so the
            # decode replica waits as long as the unified engine would
            # have — a role split must not silently shrink budgets.
            "timeout": req.get("timeout", 300.0),
            "extra": req.get("extra") or {},
        }
        if draft_meta is not None:
            meta["draft"] = draft_meta
        if self.kv_quant != "none":
            meta["kv_quant"] = self.kv_quant
        payload = pack_shipment(meta, arrays)
        res_metrics.observe("tpk_kv_shipment_bytes", len(payload),
                            buckets=_SHIPMENT_BUCKETS)
        self._kv_alloc.decref(table)
        with self._stats_lock:
            self.stats["requests"] += 1
            self.stats["prompt_tokens"] += len(ids)
            self.stats["kv_blocks_shipped"] += len(table)
            self.stats["kv_shipment_bytes"] += len(payload)
            aid = req.get("aid", 0)
            if aid:
                per = dict(self.stats.get("adapter_requests", {}))
                name = self._ml_names[aid]
                per[name] = per.get(name, 0) + 1
                self.stats["adapter_requests"] = per
        req["result"] = {"shipment": payload,
                         "num_input_tokens": len(ids),
                         "first_token": first_tok,
                         "kv_blocks": len(table)}
        req["done"].set()

    # Decode-side admission of a shipped prefill: import + bookkeeping
    # only — NO prefill chunk, no host fetch of device values (the
    # shipped first token/logprob are already host scalars), so remote
    # admission composes with pipeline_depth > 1 exactly like local
    # paged admission (allocation off the decode critical path).
    # tpk-hot: remote-admit
    def _admit_remote_paged(self, slot: int, req: dict) -> None:
        ids = req["input_ids"]
        aid = req.get("aid", 0)
        bs = self._kv_bs
        mb = self.max_len // bs
        shared: list[int] = []
        # tpk-sync: begin kv-block-reserve remote
        need = blocks_for(
            self._paged_need_tokens(len(ids), req["max_tokens"]),
            bs)
        fresh = self._kv_alloc.alloc(max(0, need - len(shared)))
        if fresh is None:
            raise _NeedKVBlocks()
        # tpk-sync: end kv-block-reserve
        dtable: list[int] | None = None
        dneed = self._draft_need_blocks(req)
        if dneed:
            dtable = self._kv_alloc.alloc(dneed)
            if dtable is None:
                self._kv_alloc.decref(fresh)
                raise _NeedKVBlocks()
        table = shared + fresh
        n_blocks = req["n_blocks"]
        try:
            # Scatter the shipped blocks into the FIRST n_blocks table
            # entries; the reservation's decode-budget tail keeps its
            # stale contents (decode writes every row before any query
            # position can attend it, exactly as local admission does)
            # and the shipment's pad blocks land in the NULL block.
            st_tbl = np.zeros((mb,), np.int32)
            st_tbl[:n_blocks] = table[:n_blocks]
            dev_blocks = {name: jnp.asarray(arr)
                          for name, arr in req["kv_blocks"].items()}
            self._cache = self._import_blocks(self._cache, dev_blocks,
                                              jnp.asarray(st_tbl))
            if dtable is not None:
                dship = req.get("draft_blocks")
                if dship is not None:
                    # fmt 2: the prompt's draft KV rode the shipment —
                    # import into the first dn_blocks entries, exactly
                    # like the target import above.
                    dst = np.zeros((mb,), np.int32)
                    dn = min(req["dn_blocks"], len(dtable))
                    dst[:dn] = dtable[:dn]
                    ddev = {name: jnp.asarray(arr)
                            for name, arr in dship.items()}
                    self._dcache = self._dimport_blocks(
                        self._dcache, ddev, jnp.asarray(dst))
                else:
                    # fmt 1 from a draft-less prefill replica: rebuild
                    # the draft history locally (one replay — the cost
                    # fmt 2 shipments avoid), so this decode replica
                    # still speculates.
                    dt = np.zeros((mb,), np.int32)
                    dt[:len(dtable)] = dtable
                    self._dcache = self._dinsert(self._dcache,
                                                 self._draft_replay(ids),
                                                 jnp.asarray(dt))
        except BaseException:
            self._kv_alloc.decref(table)
            if dtable is not None:
                self._kv_alloc.decref(dtable)
            raise
        kd = req.get("rng_key")
        if kd is not None:
            # Adopt the prefill engine's post-admission key stream —
            # concurrent shipments multiplex this one key exactly as
            # concurrent local admissions always have (last admit
            # wins); per-stream identity is what the seeded test pins.
            self._key = jax.random.wrap_key_data(jnp.asarray(kd))
        st = {"req": req, "idx": len(ids), "disp": len(ids),
              "last": req["first_tok"], "pending": None,
              "draft_ok": dtable is not None, "aid": aid,
              "blocks": table, "dblocks": dtable}
        self._slots[slot] = st
        with self._stats_lock:
            self.stats["requests"] += 1
            self.stats["remote_admits"] += 1
            self.stats["kv_blocks_received"] += n_blocks
        self._emit(slot, st, [req["first_tok"]], [req["first_lp"]])

    def _restore_spilled(self, ids: list[int], aid: int,
                         req: dict) -> tuple[int, tuple] | None:
        """Restore the longest host-tier prefix covering `ids` back into
        pool blocks and re-publish it as an HBM prefix-cache entry —
        returns the (matched_len, block_ids) contract of
        `_prefix_probe_paged`, or None (no spill, no pool room, or a
        payload this engine cannot verify). The tier entry retires on
        take(); an un-restorable payload is simply dropped (the pool
        recomputes — never serves bytes it cannot validate).

        LIVELOCK GUARD: the restore must leave room for THIS
        admission's own reserve (full need minus the restored full
        blocks it maps zero-copy). Checking `can_alloc(n_blocks)` alone
        allowed a tight pool to ping-pong forever: _kv_fits sacrifices
        the hit (spill), the admission restores it (consuming the last
        headroom), its reserve then fails and stashes head-of-line, and
        the next pass spills/restores the same prefix again — so the
        restore is attempted only when restore + reserve provably fit
        together; otherwise the admission proceeds cold, which always
        terminates."""
        tier = self._host_tier
        n = tier.probe_longest(aid, ids)
        if n is None:
            return None
        n_blocks = blocks_for(n, self._kv_bs)
        if req.get("mode") == "ship":
            total = blocks_for(len(ids), self._kv_bs)
        else:
            total = blocks_for(
                self._paged_need_tokens(len(ids), req["max_tokens"]),
                self._kv_bs)
        shared_after = n // self._kv_bs  # full blocks mapped zero-copy
        if not self._kv_alloc.can_alloc(n_blocks + total - shared_after):
            return None  # leave it spilled; admission proceeds cold
        kt = tuple(ids[:n])
        taken = tier.take(aid, kt)
        if taken is None:
            return None
        _, payload = taken
        names = (("k", "v", "ks", "vs") if self.kv_quant != "none"
                 else ("k", "v"))
        try:
            meta, arrays = unpack_shipment(payload)
            # A quantized pool restores only payloads it spilled itself
            # (same kv_quant, scale planes present); anything else —
            # including a full-precision spill left over from a config
            # change — is un-verifiable here and drops to recompute.
            if (int(meta.get("block_size", 0)) != self._kv_bs
                    or list(meta.get("tokens", ())) != list(kt)
                    or str(meta.get("kv_quant", "none")) != self.kv_quant
                    or any(x not in arrays
                           or tuple(arrays[x].shape)
                           != (self._cache[x].shape[0], n_blocks,
                               *self._cache[x].shape[2:])
                           for x in names)):
                raise ShipmentError("spilled payload mismatch")
        except ShipmentError:
            return None
        blocks = self._kv_alloc.alloc(n_blocks)
        if blocks is None:
            return None
        mb = self.max_len // self._kv_bs
        st_tbl = np.zeros((mb,), np.int32)
        st_tbl[:n_blocks] = blocks
        dev = {}
        for name in names:
            lref = self._cache[name].shape
            pad = np.zeros((lref[0], mb, *lref[2:]),
                           arrays[name].dtype)
            pad[:, :n_blocks] = arrays[name]
            dev[name] = jnp.asarray(pad)
        self._cache = self._import_blocks(self._cache, dev,
                                          jnp.asarray(st_tbl))
        # Publish as a cache entry (its incref owns the blocks), then
        # drop our allocation ref — restore-on-hit leaves exactly the
        # refcounts an HBM-resident entry would have had.
        self._prefix_store_paged(aid, kt, blocks)
        self._kv_alloc.decref(blocks)
        with self._stats_lock:
            self.stats["kv_restored_blocks"] += n_blocks
        return n, tuple(blocks)

    def _spill_prefix(self, key: tuple, kt: tuple,
                      blocks: tuple) -> None:
        """Serialize one evicted prefix entry's blocks into the host
        tier (same wire format as a prefill shipment). Called just
        before the eviction decrefs — the gather must happen while the
        blocks still hold the committed rows."""
        aid, _, _ = key
        mb = self.max_len // self._kv_bs
        gt = np.zeros((mb,), np.int32)
        gt[:len(blocks)] = blocks
        gathered = self._export_blocks(self._cache, jnp.asarray(gt))
        arrays = {name: np.asarray(leaf)[:, :len(blocks)]
                  for name, leaf in gathered.items()}
        meta = {"fmt": 3 if self.kv_quant != "none" else 1,
                "block_size": self._kv_bs,
                "vocab_size": int(self.cfg.vocab_size),
                "tokens": list(kt), "committed": len(kt)}
        charge = len(blocks)
        if self.kv_quant != "none":
            meta["kv_quant"] = self.kv_quant
            # Charge the tier by actual payload weight, in full-
            # precision-block units: a quantized block is D bytes of
            # values + 4 bytes of f32 scale per row-head against
            # D·itemsize full-width — so an unchanged
            # kv_host_tier_blocks budget holds ≈2× the entries.
            d = int(self._cache["k"].shape[-1])
            fitem = jnp.dtype(self.cfg.dtype).itemsize
            charge = max(1, -(-len(blocks) * (d + 4) // (d * fitem)))
        payload = pack_shipment(meta, arrays)
        if self._host_tier.put(aid, kt, charge, payload):
            with self._stats_lock:
                self.stats["kv_spilled_blocks"] += len(blocks)

    def _admit(self, slot: int, req: dict) -> None:
        tracer = obs.get_tracer()
        if tracer.enabled:
            # Queue wait (submit enqueue → slot admission): the engine's
            # continuous batcher is this request's "batch gather".
            tracer.record("serve.batch_gather",
                          req.get("t_enq") or time.perf_counter(),
                          time.perf_counter(), req.get("trace", ""),
                          slot=slot)
        with self._scope():
            with obs.span("serve.prefill", trace_id=req.get("trace", ""),
                          slot=slot,
                          prompt_tokens=len(req["input_ids"])):
                self._admit_inner(slot, req)

    def _admit_inner(self, slot: int, req: dict) -> None:
        if req.get("mode") == "remote":
            return self._admit_remote_paged(slot, req)
        if self._paged:
            return self._admit_inner_paged(slot, req)
        ids = req["input_ids"]
        aid = req.get("aid", 0)
        aid1 = self._aid1(aid)
        sample_args = (
            jnp.asarray([req["temperature"]], jnp.float32),
            jnp.asarray([req.get("top_k", 0)], jnp.int32),
            jnp.asarray([req.get("top_p", 1.0)], jnp.float32),
        )
        # Prompts longer than the largest bucket prefill in CHUNKS: the
        # first chunk is a plain prefill, the rest are continuation
        # chunks attending over the whole fragment cache — no silent
        # truncation (submit() already bounds the prompt by max_len).
        # The recipe (piece slicing, bucket choice, RNG split order,
        # boundary gating) is duplicated in _admit_inner_paged so the
        # flat path stays textually untouched; the tpk-sync regions
        # below enforce the twinning — a change landing in only one
        # side fails tier-1 (rule sync-regions) instead of breaking the
        # paged-is-token-identical-to-flat invariant the seeded test
        # pins.
        big = self.prefill_buckets[-1]
        frag, tok0, done = None, None, 0
        if self._prefix_cap:
            hit = self._prefix_lookup(ids, aid)
            if hit is not None:
                done, frag = hit
                with self._stats_lock:
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_hit_tokens"] += done
            else:
                with self._stats_lock:
                    self.stats["prefix_misses"] += 1
        start_done = done
        # tpk-sync: begin admit-chunked-prefill flat
        while done < len(ids):
            piece = ids[done:done + big]
            final = done + len(piece) >= len(ids)
            bucket = self._bucket_for(len(piece))
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :len(piece)] = piece
            if done == 0:
                self._key, sub = jax.random.split(self._key)
                frag, tok0, lp0 = self._prefill[bucket](
                    self._params, jnp.asarray(toks),
                    jnp.asarray([len(piece)], jnp.int32), *sample_args, sub,
                    aid=aid1)
            elif final:
                self._key, sub = jax.random.split(self._key)
                frag, tok0, lp0 = self._extend(
                    self._params, frag, jnp.asarray(toks),
                    jnp.asarray([len(piece)], jnp.int32),
                    jnp.asarray([done], jnp.int32), *sample_args, sub,
                    aid=aid1)
            else:  # intermediate chunk: no sampling, no unembedding
                frag = self._extend_mid(
                    self._params, frag, jnp.asarray(toks),
                    jnp.asarray([done], jnp.int32), aid=aid1)
            done += len(piece)
            if self._prefix_cap:
                # Skip fragments a LATER boundary of this same admission
                # would immediately LRU-evict (cap < boundaries left: the
                # seed copied them only to pop them milliseconds later),
                # and hand the final fragment over by reference — nothing
                # donates it after the loop, so the full-fragment HBM
                # copy the seed paid on every admission is gone.
                chunks_left = -(-(len(ids) - done) // big)
                if chunks_left < self._prefix_cap:
                    self._prefix_store(aid, tuple(ids[:done]), frag,
                                       copy=done < len(ids))
        # tpk-sync: end admit-chunked-prefill
        with self._stats_lock:
            self.stats["prefill_chunks"] += -(-(len(ids) - start_done)
                                              // big)
        self._cache = self._insert(self._cache, frag, jnp.int32(slot))
        spec_able = (req.get("top_k", 0) == 0
                     and req.get("top_p", 1.0) >= 1.0)
        draft_ok = False
        if self._spec is not None and spec_able:
            # The draft must hold the same prompt history: run the chunked
            # admission over its own cache (no sampling — the first
            # generated token reaches the draft as next decode input).
            # Greedy AND plain-temperature requests decode speculatively
            # (exact match / rejection sampling); top-k/top-p requests
            # skip this pass — they never take the spec path, so their
            # draft rows would be dead weight.
            self._dcache = self._dinsert(self._dcache,
                                         self._draft_replay(ids),
                                         jnp.int32(slot))
            draft_ok = True
        # tpk-sync: begin admit-slot-state flat
        st = {"req": req, "idx": len(ids), "disp": len(ids), "last": None,
              "pending": None, "draft_ok": draft_ok, "aid": aid}
        if self.pipeline_depth > 1:
            # Off-critical-path admission: do NOT fetch the first sampled
            # token here — that host sync would serialize the prefill
            # behind every in-flight decode chunk and stall the loop for
            # all slots. The token stays on device as the slot's decode
            # carry; its host value lands via the async copy and is
            # emitted at the next poll/fetch boundary.
            for arr in (tok0, lp0):
                getattr(arr, "copy_to_host_async", lambda: None)()
            st["pending"] = (tok0, lp0)
            self._slots[slot] = st
        else:
            st["last"] = int(tok0[0])
            self._slots[slot] = st
        with self._stats_lock:
            self.stats["requests"] += 1
            self.stats["prompt_tokens"] += len(ids)
            if aid:
                # Copy-on-write: stats_snapshot() copies stats SHALLOWLY
                # from another thread — swapping in a fresh dict keeps
                # any in-flight snapshot's inner reference immutable.
                per = dict(self.stats.get("adapter_requests", {}))
                name = self._ml_names[aid]
                per[name] = per.get(name, 0) + 1
                self.stats["adapter_requests"] = per
        if st["pending"] is None:
            self._emit(slot, st, [st["last"]], [float(lp0[0])])
        # tpk-sync: end admit-slot-state

    def _draft_replay(self, ids: list[int]) -> Any:
        """Chunked draft-cache build over a token sequence — the ONE
        admission recipe shared by initial admission and re-admission
        (no sampling: _dextend_mid only)."""
        big = self.prefill_buckets[-1]
        dfrag = self._dfrag_init()
        done = 0
        while done < len(ids):
            piece = ids[done:done + big]
            bucket = self._bucket_for(len(piece))
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :len(piece)] = piece
            dfrag = self._dextend_mid(self._dparams, dfrag,
                                      jnp.asarray(toks),
                                      jnp.asarray([done], jnp.int32))
            done += len(piece)
        return dfrag

    def _readmit_worthwhile(self, st: dict) -> bool:
        """Cost gate for draft re-admission: replaying the whole history
        to speculate a handful of remaining tokens (or a history vastly
        longer than the remainder) costs more than it saves. Checked
        PER SLOT (ADVICE r5 partial fix): an unworthy slot is simply not
        replayed — it rides the spec chunk with its stale draft rows —
        instead of keeping the whole batch vanilla for its lifetime."""
        req = st["req"]
        remaining = req["max_tokens"] - len(req["out"])
        history = len(req["input_ids"]) + len(req["out"]) - 1
        return remaining >= self.chunk and history <= 32 * remaining

    def _readmit_draft(self, slot: int, st: dict) -> None:
        """Rebuild a demoted slot's draft cache from its token history
        (prompt + all emitted but the pending last = positions
        0..idx-1), restoring speculative decoding after a vanilla chunk
        invalidated the draft rows — mixed traffic costs spec throughput
        only WHILE the truncated-sampling request is in flight, not for
        the rest of every concurrent request (r4 advisor finding)."""
        req = st["req"]
        ids = req["input_ids"] + req["out"][:-1]
        if self._paged:
            mb = self.max_len // self._kv_bs
            dt = np.zeros((mb,), np.int32)
            dblocks = st["dblocks"]
            dt[:len(dblocks)] = dblocks
            target = jnp.asarray(dt)
        else:
            target = jnp.int32(slot)
        self._dcache = self._dinsert(self._dcache, self._draft_replay(ids),
                                     target)
        st["draft_ok"] = True
        with self._stats_lock:
            self.stats["spec_readmissions"] += 1

    def _emit(self, slot: int, st: dict, tokens: list[int],
              logprobs: list[float] | None = None) -> None:
        """Append generated tokens to `st`'s request; retire on EOS /
        budget / context exhaustion. Streams newly appended tokens to the
        request's on_tokens callback when one is set. `st` is passed
        explicitly (not read from the slot) because in pipelined mode a
        fetched chunk may belong to a request that already retired and
        whose slot was re-admitted — the caller reconciles by identity."""
        req = st["req"]
        new: list[int] = []
        finished = req["done"].is_set()
        for j, t in enumerate(tokens):
            if finished:
                break
            req["out"].append(t)
            if logprobs is not None:
                req["out_logprobs"].append(logprobs[j])
            new.append(t)
            if ((req["eos_id"] is not None and t == req["eos_id"])
                    or len(req["out"]) >= req["max_tokens"]):
                finished = True
        if st["idx"] >= self.max_len - 1:
            finished = True
        # Stream BEFORE signalling completion: done.set() wakes submit()
        # in the caller's thread, and a final summary racing ahead of the
        # last token chunk would truncate the stream.
        if req["cb"] is not None and (new or finished):
            try:
                req["cb"](new, finished)
            except Exception:
                pass
        if finished:
            req["done"].set()
            if self._slots[slot] is st:
                self._slots[slot] = None
            self._free_slot_blocks(st)

    def _expire(self, req: dict) -> bool:
        """Finish `req` with DeadlineExceeded when its budget is gone.
        True means the request is done and must not (or no longer) hold a
        decode slot. No metrics here: the serving surface that returns
        the error counts each expired request exactly once."""
        if req["done"].is_set():
            return True  # already finished (e.g. EOS raced the sweep)
        dl = req.get("deadline")
        if dl is None or not dl.expired():
            return False
        req["error"] = DeadlineExceeded(
            "request deadline expired during generation")
        req["done"].set()
        return True

    def _admit_waiting(self, overlap: bool) -> None:
        """Admit waiting requests into free slots (chunk boundary).
        Each free slot keeps popping past already-expired entries
        (their callers were 504'd) and failed admissions, so a
        backlog of dead requests can't make live ones wait a chunk
        per corpse; one empty probe ends the whole scan (no
        per-slot queue.Empty churn on the idle hot loop). Queued
        admissions coalesce: every free slot fills in ONE pass, so a
        burst of arrivals costs one trip through the admission
        dispatches before the next decode chunk goes out.

        With `overlap` (decode chunks in flight), the prefill/extend/
        insert dispatches enqueue BEHIND them on the device stream and
        no host sync happens (`_admit_inner` defers the first-token
        fetch) — admission is off the critical path, counted by
        `admit_overlap`.

        Paged mode adds the free-block gate: a request whose worst-case
        block need the pool cannot cover yet is STASHED head-of-line
        (`_kv_fits` — which first reclaims LRU prefix-cache blocks) and
        the scan stops, so admission stays FIFO and a big request can't
        be starved by smaller ones slipping past it."""
        queue_empty = False
        for slot in range(self.n_slots):
            if queue_empty:
                break
            while self._slots[slot] is None:
                if self._kv_stash:
                    req = self._kv_stash.popleft()
                else:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        queue_empty = True
                        break
                if self._expire(req):
                    continue  # never admitted; try the next waiter
                if self._paged and not self._kv_fits(req):
                    self._kv_stash.appendleft(req)
                    queue_empty = True  # FIFO: nothing jumps the line
                    break
                try:
                    self._admit(slot, req)
                except _NeedKVBlocks:
                    self._kv_stash.appendleft(req)
                    queue_empty = True
                    break
                except Exception as e:  # surface to the caller
                    req["error"] = f"{type(e).__name__}: {e}"
                    req["done"].set()
                    self._slots[slot] = None
                    continue  # slot still free; try the next waiter
                if overlap:
                    with self._stats_lock:
                        self.stats["admit_overlap"] += 1
                break

    def _emit_pending(self, slot: int, st: dict) -> None:
        """Deliver a deferred first token (deep-pipeline admission). By
        the time this runs the prefill has long completed (it precedes
        any decode chunk containing the slot in stream order) and the
        async host copy has usually landed — the fetch is a no-wait."""
        tok0, lp0 = st["pending"]
        st["pending"] = None
        first = int(np.asarray(tok0)[0])
        st["last"] = first
        self._emit(slot, st, [first], [float(np.asarray(lp0)[0])])

    def _poll_pending_first(self) -> None:
        """Emit deferred first tokens whose async host copy already
        landed — chunk-granular TTFT without waiting for the next fetch
        boundary, and an EOS / max_tokens=1 finish frees the slot before
        the next dispatch wastes a chunk on it (like the sync path)."""
        for slot, st in enumerate(self._slots):
            if st is None or st.get("pending") is None:
                continue
            try:
                if not st["pending"][0].is_ready():
                    continue
            except AttributeError:  # older jaxlib: fetch at the boundary
                continue
            self._emit_pending(slot, st)

    def _worth_speculating(self, active: list[int]) -> bool:
        """Gate for dispatching chunk k+1 before chunk k is fetched:
        never speculate past the context end (the write would clamp — or
        wrap, in rolling mode), and never when every active request's
        remaining budget is already covered by in-flight tokens (the
        chunk would be pure waste). EOS is unknowable on the host; that
        waste is the price of overlap, bounded by pipeline_depth-1
        chunks per retirement and accounted in decode_wasted_tokens."""
        if (max(self._slots[i]["disp"] for i in active) + self.chunk
                > self.max_len):
            return False
        for i in active:
            st = self._slots[i]
            inflight = st["disp"] - st["idx"] + (1 if st["pending"] else 0)
            if len(st["req"]["out"]) + inflight < st["req"]["max_tokens"]:
                return True
        return False

    def _van_riders_fit(self, van_batch: list[int]) -> bool:
        """Flat-mode guard for the vanilla sub-batch: live rows OUTSIDE
        the batch (spec rows) park their batch-wide write at their own
        disp — near the context end that write would clamp backwards
        over committed rows, so the dispatch waits the few chunks until
        those rows retire. Paged riders write the NULL block; nothing
        to check."""
        if self._paged:
            return True
        vb = set(van_batch)
        for j, stj in enumerate(self._slots):
            if (stj is not None and j not in vb
                    and stj["disp"] + self.chunk > self.max_len):
                return False
        return True

    def _spec_batch(self, active: list[int], van_covered: set,
                    spec_chain: list) -> tuple[list[int], list[int]]:
        """Plan this round's SPEC sub-batch (per-sub-batch dispatch):
        greedy + plain-temperature rows speculate; top-k/top-p rows
        decode vanilla in their own sub-batch — one truncated-sampling
        request no longer disables speculation for concurrent traffic.

        Returns (parts, fallback): `parts` rows ride a spec dispatch
        now; `fallback` rows join the vanilla sub-batch this round
        (possible only when no spec chunk is in flight — a row covered
        by an in-flight spec record has its true last token on device,
        so it can neither splice into a vanilla dispatch nor re-admit
        its draft until the chain drains back to disp == idx)."""
        if self._spec is None:
            return [], []
        rows = [i for i in active
                if self._spec_able(self._slots[i]["req"])
                and i not in van_covered]
        if not rows:
            return [], []
        chained = bool(spec_chain)
        if chained and spec_chain[-1]["doomed"]:
            return [], []  # drain the doomed chain before re-dispatching
        if chained and self._rolling:
            # Rolling cache: a doomed over-dispatch would have
            # wrap-written window rows still inside every later query's
            # attention span — unrecoverable at reconcile, so rolling
            # engines pin the spec chain to depth 1.
            return [], []
        worst = self._spec["n_spec"] * (self._spec["gamma"] + 1)
        if self._rolling and len(rows) != len(active):
            # Rolling riders would wrap-clobber live window rows the
            # same way; mixed traffic keeps the all-or-nothing gate on
            # the (flat, rolling) escape hatch.
            return [], (rows if not chained else [])
        if self._paged:
            # Per-row block tables: rows that no longer fit a worst-case
            # advance drop to the vanilla tail individually.
            fit = [i for i in rows
                   if self._slots[i]["disp"] + worst <= self.max_len]
        else:
            # Flat: one batch-wide bucket, and rider rows park their
            # writes at their own disp — the headroom gate must cover
            # every live row or a clamped write would walk backwards
            # over committed KV.
            high = max(st["disp"] for st in self._slots if st is not None)
            fit = rows if high + worst <= self.max_len else []
        tail = [i for i in rows if i not in fit]
        def usable(i: int) -> bool:
            st = self._slots[i]
            return bool(st.get("draft_ok")) or (
                st["disp"] == st["idx"] and st["pending"] is None
                and self._readmit_worthwhile(st))
        if fit and not any(usable(i) for i in fit):
            # Nobody would propose from a live (or replayable) draft
            # cache — the spec dispatch would be pure overhead over a
            # vanilla chunk.
            tail, fit = rows, []
        if not fit:
            return [], (tail if not chained else [])
        if chained:
            # Chain chunk k+1 only while some participant's budget is
            # not already covered in flight (same rule as the vanilla
            # chain) — otherwise the over-dispatch is pure waste.
            for i in fit:
                st = self._slots[i]
                infl = (st["disp"] - st["idx"]
                        + (1 if st["pending"] else 0))
                if len(st["req"]["out"]) + infl < st["req"]["max_tokens"]:
                    break
            else:
                return [], []
        return fit, (tail if not chained else [])

    # tpk-hot: spec-dispatch
    def _dispatch_spec_chunk(self, parts: list[int],
                             carry: dict | None = None) -> dict:
        """Issue one speculative dispatch over the spec sub-batch
        WITHOUT fetching: draft proposes gamma tokens per step, target
        verifies (greedy rows exact-match the target argmax — token-
        identical to vanilla greedy; tempered rows rejection-sample the
        exact target marginal). `carry` chains chunk k+1 on chunk k's
        WORST-CASE carry — the last bonus token, valid iff every
        proposal was accepted; `_fetch_spec_chunk` dooms over-advanced
        records at reconcile exactly like speculatively-dead chunks,
        which is what lifts the old forced pipeline_depth=1.

        Per-slot draft re-admission rides here (gated to rows with no
        chunk in flight: the replay reads finalized token history);
        permanently-unworthy demoted rows ride with STALE draft rows —
        a pure acceptance-rate cost counted in spec_stale_rides, never
        a correctness one."""
        spec = self._spec
        worst = spec["n_spec"] * (spec["gamma"] + 1)
        worthy = []
        stale = 0
        for i in parts:
            st = self._slots[i]
            if st.get("draft_ok"):
                continue
            if (st["disp"] == st["idx"] and st["pending"] is None
                    and self._readmit_worthwhile(st)):
                worthy.append(i)
            else:
                stale += 1
        with self._scope():
            for i in worthy:
                self._readmit_draft(i, self._slots[i])
        if stale:
            with self._stats_lock:
                self.stats["spec_stale_rides"] += stale
        last = np.zeros((self.n_slots,), np.int32)
        idx = np.zeros((self.n_slots,), np.int32)
        temps = np.zeros((self.n_slots,), np.float32)
        ks = np.zeros((self.n_slots,), np.int32)
        ps = np.ones((self.n_slots,), np.float32)
        aids = np.zeros((self.n_slots,), np.int32)
        # The row-gather below is the tpk-sync twin of the vanilla
        # dispatch loop's: the spec sub-batch must snapshot slot state
        # by the identical recipe (ks/ps are gathered for the twinning
        # but never dispatched — spec rows are never truncated).
        # tpk-sync: begin dispatch-row-gather spec
        # tpk-sync: sub for i in active: -> for i in parts:
        for i in parts:
            st = self._slots[i]
            idx[i] = st["disp"]
            temps[i] = st["req"]["temperature"]
            ks[i] = st["req"].get("top_k", 0)
            ps[i] = st["req"].get("top_p", 1.0)
            aids[i] = st.get("aid", 0)
            if st["pending"] is None and st["last"] is not None:
                last[i] = st["last"]
        # tpk-sync: end dispatch-row-gather
        assumed = {i: self._slots[i]["disp"] for i in parts}
        partset = set(parts)
        if not self._paged:
            for j, stj in enumerate(self._slots):
                if stj is None or j in partset:
                    continue
                # Rider parking: a live row excluded from this sub-batch
                # aims its batch-wide write at its own uncommitted tail
                # (idx 0 would clobber committed prompt KV; paged riders
                # write the NULL block instead and need no parking).
                idx[j] = stj["disp"]
        need = int(max(idx)) + worst
        bucket = next((b for b in self.decode_buckets if b >= need),
                      self.decode_buckets[-1])
        self._key, sub = jax.random.split(self._key)
        t0 = time.monotonic()
        p0 = time.perf_counter()
        with self._scope():
            last_dev = (jnp.asarray(last) if carry is None
                        else carry["toks"][:, -1, -1])
            for i in parts:
                st = self._slots[i]
                if carry is not None and carry["parts"].get(i) is st:
                    continue  # row rides the on-device worst-case carry
                if st["pending"] is not None:
                    last_dev = last_dev.at[i].set(st["pending"][0][0])
                elif carry is not None:
                    last_dev = last_dev.at[i].set(np.int32(st["last"]))
            if self._paged:
                nb = bucket // self._kv_bs
                tables = np.zeros((self.n_slots, nb), np.int32)
                dtables = np.zeros((self.n_slots, nb), np.int32)
                for i in parts:
                    st = self._slots[i]
                    blk = st["blocks"]
                    k = min(len(blk), nb)
                    tables[i, :k] = blk[:k]
                    dbl = st["dblocks"]
                    k = min(len(dbl), nb)
                    dtables[i, :k] = dbl[:k]
                self._cache, self._dcache, toks, lps, acc = \
                    self._spec_decode[bucket](
                        self._params, self._dparams, self._cache,
                        self._dcache, jnp.asarray(tables),
                        jnp.asarray(dtables), last_dev,
                        jnp.asarray(idx), jnp.asarray(temps), sub,
                        aid=self._aid_batch(aids))
            else:
                self._cache, self._dcache, toks, lps, acc = \
                    self._spec_decode[bucket](
                        self._params, self._dparams, self._cache,
                        self._dcache, last_dev, jnp.asarray(idx),
                        jnp.asarray(temps), sub,
                        aid=self._aid_batch(aids))
        for arr in (toks, lps, acc):
            getattr(arr, "copy_to_host_async", lambda: None)()
        with self._stats_lock:
            self.stats["decode_dispatches"] += 1
            self.stats["spec_dispatches"] += 1
        rec_parts: dict[int, dict] = {}
        for i in parts:
            st = self._slots[i]
            st["disp"] += worst
            rec_parts[i] = st
        return {"kind": "spec", "toks": toks, "lps": lps, "acc": acc,
                "parts": rec_parts, "assumed": assumed, "worst": worst,
                "doomed": False, "t0": t0, "p0": p0}

    # tpk-hot: spec-reconcile
    def _fetch_spec_chunk(self, rec: dict, inflight,
                          overlapped: bool) -> None:
        """Fetch one spec record (the host sync point) and reconcile.
        Three row outcomes, mirroring the vanilla dead-chunk reconcile:
          * dead — the dispatch-time occupant retired; rows dropped.
          * over-advanced — an earlier record's partial acceptance
            falsified this record's all-accepted start assumption (or
            it was doomed wholesale): rows dropped, disp rolled back by
            this record's worst-case width. The garbage KV it wrote
            sits past the committed index, masked until sequential
            decode rewrites it.
          * valid — emit per the accepted counts; any acceptance short
            of worst-case dooms every LATER in-flight spec record (its
            carry token and start indices are fabrications).
        The doomed protocol is whole-record: bounded waste
        (pipeline_depth-1 records per rejection event), zero carry
        splicing."""
        t0 = time.monotonic()
        pf0 = time.perf_counter()
        # tpk-lint: allow(host-sync) reason=the designed per-spec-chunk fetch boundary; D2H was prestaged by copy_to_host_async at dispatch
        toks = np.asarray(rec["toks"])  # [B, n_spec, gamma+1]
        # tpk-lint: allow(host-sync) reason=second half of the designed spec fetch boundary (logprobs ride the same prestaged copy)
        lps = np.asarray(rec["lps"])
        # tpk-lint: allow(host-sync) reason=accepted counts ARE the reconcile input — each row's next index is decided by them, on host, once per record
        acc = np.asarray(rec["acc"])    # [B, n_spec] accepted counts
        now = time.monotonic()
        pf1 = time.perf_counter()
        tracer = obs.get_tracer()
        if tracer.enabled:
            for i, st in rec["parts"].items():
                trace = st["req"].get("trace", "")
                tracer.record("serve.decode_chunk", rec["p0"], pf0,
                              trace, slot=i, spec=True,
                              overlapped=overlapped)
                tracer.record("serve.fetch", pf0, pf1, trace, slot=i)
        start = (rec["t0"] if self._busy_mark is None
                 else max(self._busy_mark, rec["t0"]))
        with self._stats_lock:
            self.stats["host_stall_seconds"] += now - t0
            self.stats["decode_fetch_overlapped" if overlapped
                        else "decode_fetch_blocking"] += 1
            self.stats["decode_seconds"] += now - start
        self._busy_mark = now
        worst = rec["worst"]
        spec = self._spec

        def doom_later() -> None:
            for r in inflight:
                if r.get("kind") == "spec":
                    r["doomed"] = True

        for i, st in rec["parts"].items():
            if self._slots[i] is not st:
                with self._stats_lock:
                    self.stats["decode_dead_slot_chunks"] += 1
                    self.stats["decode_wasted_tokens"] += worst
                continue
            if st["pending"] is not None:
                # First token of a mid-pipe admission: emit it before
                # the spec tokens (the record decoded FROM it).
                self._emit_pending(i, st)
                if self._slots[i] is not st:  # EOS/budget at token 1
                    with self._stats_lock:
                        self.stats["decode_dead_slot_chunks"] += 1
                        self.stats["decode_wasted_tokens"] += worst
                    continue
            if rec["doomed"] or st["idx"] != rec["assumed"][i]:
                # Over-advanced: decoded from a start index that partial
                # acceptance upstream made fictional. Settle this
                # record's disp contribution and drop the rows.
                st["disp"] -= worst
                with self._stats_lock:
                    self.stats["decode_wasted_tokens"] += worst
                continue
            emit_t: list[int] = []
            emit_l: list[float] = []
            accepted = 0
            for s in range(spec["n_spec"]):
                kk = int(acc[i, s])
                emit_t += [int(t) for t in toks[i, s, :kk + 1]]
                emit_l += [float(v) for v in lps[i, s, :kk + 1]]
                accepted += kk
            st["idx"] += len(emit_t)
            st["disp"] -= worst - len(emit_t)
            st["last"] = emit_t[-1]
            if len(emit_t) < worst:
                # Partial acceptance: every later in-flight spec record
                # chained on the all-accepted assumption — doom them
                # wholesale (they reconcile as drops above).
                doom_later()
            with self._stats_lock:
                self.stats["spec_proposed"] += (spec["gamma"]
                                                * spec["n_spec"])
                self.stats["spec_accepted"] += accepted
                self.stats["decode_tokens"] += len(emit_t)
            self._emit(i, st, emit_t, emit_l)

    # tpk-hot: engine-dispatch
    def _dispatch_chunk(self, active: list[int],
                        carry: dict | None = None) -> dict:
        """Issue one chunked decode dispatch over the slot batch WITHOUT
        fetching its result. `carry` is the previous (still in-flight)
        dispatch record: its on-device last-token column chains straight
        into this dispatch, so back-to-back chunks execute with no host
        round-trip between them. Rows that didn't ride the carry — a
        slot admitted mid-pipe (its prefill's sampled token is spliced in
        as an on-device scalar) or one re-synced after a drain — are
        overridden individually. Truncation costs a full-vocab sort per
        step; only pay it when some active request actually asked for
        top-k/top-p. The cache-length bucket is the smallest covering
        every active sequence after this chunk — short conversations
        never pay max_len-wide attention."""
        last = np.zeros((self.n_slots,), np.int32)
        idx = np.zeros((self.n_slots,), np.int32)
        temps = np.zeros((self.n_slots,), np.float32)
        ks = np.zeros((self.n_slots,), np.int32)
        ps = np.ones((self.n_slots,), np.float32)
        aids = np.zeros((self.n_slots,), np.int32)
        # tpk-sync: begin dispatch-row-gather van
        for i in active:
            st = self._slots[i]
            idx[i] = st["disp"]
            temps[i] = st["req"]["temperature"]
            ks[i] = st["req"].get("top_k", 0)
            ps[i] = st["req"].get("top_p", 1.0)
            aids[i] = st.get("aid", 0)
            if st["pending"] is None and st["last"] is not None:
                last[i] = st["last"]
        # tpk-sync: end dispatch-row-gather
        trunc = any(ks[i] > 0 or ps[i] < 1.0 for i in active)
        if not self._paged:
            partset = set(active)
            for j, stj in enumerate(self._slots):
                if stj is None or j in partset:
                    continue
                # Rider parking: a live row excluded from this sub-batch
                # (it belongs to the spec sub-batch) aims its batch-wide
                # write at its own uncommitted tail — idx 0 would
                # clobber committed prompt KV (paged riders write the
                # NULL block instead and need no parking).
                idx[j] = stj["disp"]
        need = int(max(idx)) + self.chunk
        bucket = next((b for b in self.decode_buckets if b >= need),
                      self.decode_buckets[-1])
        self._key, sub = jax.random.split(self._key)
        t0 = time.monotonic()
        p0 = time.perf_counter()  # span clock for the decode-chunk span
        with self._scope():
            last_dev = (jnp.asarray(last) if carry is None
                        else carry["toks"][:, -1])
            for i in active:
                st = self._slots[i]
                if carry is not None and carry["parts"].get(i) is st:
                    continue  # row rides the on-device carry
                if st["pending"] is not None:
                    # Mid-pipe admission: splice the prefill's on-device
                    # first token into the carried vector (a scalar
                    # update dispatch, no host round-trip).
                    last_dev = last_dev.at[i].set(st["pending"][0][0])
                elif carry is not None:
                    last_dev = last_dev.at[i].set(np.int32(st["last"]))
            if self._paged:
                # Per-row block tables, padded with the NULL block. Built
                # from host lists fixed at admission — no device sync, so
                # chained pipelined dispatch works exactly as flat.
                nb = bucket // self._kv_bs
                tables = np.zeros((self.n_slots, nb), np.int32)
                for i in active:
                    blk = self._slots[i]["blocks"]
                    k = min(len(blk), nb)
                    tables[i, :k] = blk[:k]
                self._cache, toks, lps = self._decode[(bucket, trunc)](
                    self._params, self._cache, jnp.asarray(tables),
                    last_dev, jnp.asarray(idx), jnp.asarray(temps),
                    jnp.asarray(ks), jnp.asarray(ps), sub,
                    aid=self._aid_batch(aids))
            else:
                self._cache, toks, lps = self._decode[(bucket, trunc)](
                    self._params, self._cache, last_dev, jnp.asarray(idx),
                    jnp.asarray(temps), jnp.asarray(ks), jnp.asarray(ps),
                    sub, aid=self._aid_batch(aids))
        # Start the D2H transfer now; the fetch a pipeline-depth later
        # should find the bytes already on host.
        for arr in (toks, lps):
            getattr(arr, "copy_to_host_async", lambda: None)()
        with self._stats_lock:
            self.stats["decode_dispatches"] += 1
        parts: dict[int, dict] = {}
        for i in active:
            st = self._slots[i]
            st["disp"] += self.chunk
            parts[i] = st
        return {"kind": "van", "toks": toks, "lps": lps, "parts": parts,
                "t0": t0, "p0": p0, "chunk": self.chunk}

    # tpk-hot: engine-fetch
    def _fetch_chunk(self, rec: dict, overlapped: bool) -> None:
        """Fetch one dispatch record's tokens (the host sync point) and
        reconcile: a slot whose dispatch-time occupant already retired
        (EOS / budget / deadline at an earlier boundary) gets its rows
        dropped — the chunk was speculatively dead for it. `overlapped`
        records whether another chunk was still in flight during this
        fetch (the steady-state pipelining invariant the CPU dispatch-
        count guard test pins)."""
        t0 = time.monotonic()
        pf0 = time.perf_counter()
        # THE one designed host sync of the decode pipeline: everything
        # below is host numpy. (The runtime fetch-count guard test pins
        # exactly one fetch pair per chunk.)
        # tpk-lint: allow(host-sync) reason=the designed per-chunk fetch boundary; D2H was prestaged by copy_to_host_async at dispatch
        toks = np.asarray(rec["toks"])  # host sync point: [B, chunk]
        # tpk-lint: allow(host-sync) reason=second half of the designed per-chunk fetch boundary (logprobs ride the same prestaged copy)
        lps = np.asarray(rec["lps"])
        now = time.monotonic()
        pf1 = time.perf_counter()
        tracer = obs.get_tracer()
        if tracer.enabled:
            # Chunk-granular spans (never per-token — the hot loop adds
            # no syncs, and the ring stays bounded): one decode-chunk
            # span per rider covering dispatch→fetch-start, one fetch
            # span per rider covering the host sync itself.
            for i, st in rec["parts"].items():
                trace = st["req"].get("trace", "")
                tracer.record("serve.decode_chunk", rec["p0"], pf0, trace,
                              slot=i, chunk=rec["chunk"],
                              overlapped=overlapped)
                tracer.record("serve.fetch", pf0, pf1, trace, slot=i)
        # decode_seconds sums ENGINE-BUSY wall time (non-overlapping
        # intervals), so throughput() stays honest when chunks overlap.
        start = (rec["t0"] if self._busy_mark is None
                 else max(self._busy_mark, rec["t0"]))
        with self._stats_lock:
            self.stats["host_stall_seconds"] += now - t0
            self.stats["decode_fetch_overlapped" if overlapped
                        else "decode_fetch_blocking"] += 1
            self.stats["decode_seconds"] += now - start
        self._busy_mark = now
        for i, st in rec["parts"].items():
            if self._slots[i] is not st:
                with self._stats_lock:
                    self.stats["decode_dead_slot_chunks"] += 1
                    self.stats["decode_wasted_tokens"] += rec["chunk"]
                continue
            if st["pending"] is not None:
                # First token of a mid-pipe admission: emit it before
                # the chunk tokens (the chunk was decoded FROM it).
                self._emit_pending(i, st)
                if self._slots[i] is not st:  # EOS/budget at token 1
                    with self._stats_lock:
                        self.stats["decode_dead_slot_chunks"] += 1
                        self.stats["decode_wasted_tokens"] += rec["chunk"]
                    continue
            st["idx"] += rec["chunk"]
            st["last"] = int(toks[i, -1])
            # This vanilla chunk left the slot's DRAFT cache rows
            # unwritten — spec decoding must not trust them until
            # re-admission replays the slot's history
            # (_readmit_draft, once the batch is all-spec-able
            # again). spec_demotions / spec_readmissions count both
            # sides (perf effects, never correctness).
            with self._stats_lock:
                if st.get("draft_ok"):
                    self.stats["spec_demotions"] += 1
                self.stats["decode_tokens"] += rec["chunk"]
            st["draft_ok"] = False
            self._emit(i, st, [int(t) for t in toks[i]],
                       [float(v) for v in lps[i]])

    # tpk-hot: engine-loop
    def _loop(self) -> None:
        """The scheduler: admit → sweep deadlines → keep up to
        `pipeline_depth` decode chunks in flight → fetch the oldest.
        At depth 1 each iteration dispatches then immediately fetches —
        the synchronous engine, bit-for-bit (same RNG splits, same sync
        points). At depth >= 2 the fetch of chunk k overlaps the device
        executing chunk k+1 (and any admission dispatches), hiding the
        host/tunnel round-trip that capped 1-slot decode at ~200 tok/s
        regardless of chip speed (PROFILE.md §5).

        Each round splits the batch into TWO sub-batches dispatched
        independently (per-sub-batch dispatch): the SPEC sub-batch
        (greedy + plain-temperature rows, when a draft model is
        configured) and the VANILLA sub-batch (top-k/top-p rows, plus
        spec rows falling back near the context end). Each kind keeps
        its own chain of up to `pipeline_depth` records in flight;
        fetches drain oldest-first across both. Pure-vanilla traffic
        reduces bit-for-bit to the single-chain loop above; pure-spec
        traffic at depth 1 reproduces the classic synchronous spec
        engine."""
        inflight: deque = deque()
        while not self._stop:
            self._admit_waiting(overlap=bool(inflight))
            # Chunk-boundary deadline sweep: an expired request frees its
            # slot NOW instead of decoding tokens its caller (already
            # 504'd) will never read — expiry costs the batch at most
            # pipeline_depth chunks of waste.
            for i, st in enumerate(self._slots):
                if st is not None and self._expire(st["req"]):
                    self._slots[i] = None
                    self._free_slot_blocks(st)
            self._poll_pending_first()
            active = [i for i, s in enumerate(self._slots)
                      if s is not None]
            if not active and not inflight:
                self._busy_mark = None
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            while active:
                dispatched = False
                spec_chain = [r for r in inflight if r["kind"] == "spec"]
                van_chain = [r for r in inflight if r["kind"] == "van"]
                van_covered = {i for r in van_chain
                               for i, st in r["parts"].items()
                               if self._slots[i] is st}
                parts, fallback = self._spec_batch(active, van_covered,
                                                   spec_chain)
                if parts and len(spec_chain) < self.pipeline_depth:
                    inflight.append(self._dispatch_spec_chunk(
                        parts,
                        carry=spec_chain[-1] if spec_chain else None))
                    self.inflight_depth = len(inflight)
                    dispatched = True
                spec_rows = {i for i in active
                             if self._spec is not None
                             and self._spec_able(self._slots[i]["req"])}
                fb = set(fallback)
                van_batch = [i for i in active
                             if i not in spec_rows or i in fb]
                if (van_batch and len(van_chain) < self.pipeline_depth
                        and (not van_chain
                             or self._worth_speculating(van_batch))
                        and self._van_riders_fit(van_batch)):
                    inflight.append(self._dispatch_chunk(
                        van_batch,
                        carry=van_chain[-1] if van_chain else None))
                    self.inflight_depth = len(inflight)
                    dispatched = True
                if not dispatched:
                    break
            if inflight:
                rec = inflight.popleft()
                self.inflight_depth = len(inflight)
                if rec["kind"] == "spec":
                    self._fetch_spec_chunk(rec, inflight,
                                           overlapped=bool(inflight))
                else:
                    self._fetch_chunk(rec, overlapped=bool(inflight))

    def stats_snapshot(self) -> dict:
        """Tear-free copy of the engine counters for metrics/metadata
        readers on other threads. Shallow by design: inner values are
        swapped whole (copy-on-write), never mutated in place."""
        with self._stats_lock:
            return dict(self.stats)

    def throughput(self) -> float:
        s = self.stats_snapshot()
        return s["decode_tokens"] / s["decode_seconds"] if s["decode_seconds"] else 0.0


class GenerativeJAXModel(Model):
    """KServe-Model-shaped wrapper: load() builds the engine (AOT compiles
    prefill buckets + decode); generate() is the request surface. Also
    answers plain predict() with a full-forward logits call for protocol
    parity (v1/v2 infer on a generative model)."""

    def __init__(self, name: str, model, params, cfg, *,
                 generation: dict | None = None):
        super().__init__(name)
        self._model, self._params, self.cfg = model, params, cfg
        self._gen_cfg = dict(generation or {})
        self.engine: GenerationEngine | None = None
        self.eos_id = self._gen_cfg.pop("eos_id", None)
        self.tokenizer = self._gen_cfg.pop("tokenizer", None)
        # {"tensor": N, ...} from the bundle / ISVC spec — resolved to a
        # device mesh at load() time, when the devices exist.
        self._mesh_spec = dict(self._gen_cfg.pop("mesh", None) or {})
        # Speculative decoding spec: {"checkpoint": <HF dir>, "gamma": N,
        # "model_overrides": {...}} — the draft checkpoint is resolved at
        # load() time (same import path as the target).
        self._draft_spec = dict(self._gen_cfg.pop("draft", None) or {})

    def _build_mesh(self):
        import math

        from kubeflow_tpu.parallel.mesh import (MESH_AXES, MeshConfig,
                                                build_mesh)

        unknown = set(self._mesh_spec) - set(MESH_AXES)
        if unknown:
            raise ValueError(
                f"mesh spec has unknown axes {sorted(unknown)}; "
                f"valid: {list(MESH_AXES)}")
        sizes = {k: int(v) for k, v in self._mesh_spec.items()}
        if any(v < 1 for v in sizes.values()):
            raise ValueError(f"mesh axis sizes must be >= 1: {sizes}")
        need = math.prod(sizes.values())
        devs = jax.devices()
        if len(devs) < need:
            raise ValueError(
                f"mesh {sizes} needs {need} devices, have {len(devs)}")
        sizes.setdefault("data", 1)
        return build_mesh(MeshConfig(**sizes), devs[:need])

    def load(self) -> bool:
        t0 = time.monotonic()
        kwargs = dict(self._gen_cfg)
        if self._mesh_spec:
            kwargs["mesh"] = self._build_mesh()
        if self._draft_spec:
            spec = dict(self._draft_spec)
            ckpt = spec.pop("checkpoint", None)
            overrides = spec.pop("model_overrides", None) or {}
            gamma = spec.pop("gamma", None)
            if spec:
                # Validate BEFORE the (potentially GB-scale) checkpoint
                # import — a typo'd key must fail in milliseconds.
                raise ValueError(
                    f"unknown generative.draft keys {sorted(spec)}")
            if not ckpt:
                raise ValueError(
                    "generative.draft needs a 'checkpoint' (HF dir of "
                    "the draft model)")
            from kubeflow_tpu.models.hf_import import build_from_hf

            dmodule, dcfg, dparams = build_from_hf(ckpt, **overrides)
            draft = {"model": dmodule, "params": dparams, "cfg": dcfg}
            if gamma is not None:
                draft["gamma"] = int(gamma)
            kwargs["draft"] = draft
        self.engine = GenerationEngine(
            self._model, self._params, self.cfg, **kwargs)
        self.load_time_s = time.monotonic() - t0
        self.ready = True
        return True

    def unload(self) -> None:
        self.ready = False
        if self.engine:
            self.engine.close()
            self.engine = None

    def _resolve_ids(self, payload: dict) -> list[int]:
        from kubeflow_tpu.serve.tokenizer_util import resolve_ids

        return resolve_ids(self.tokenizer, payload)

    def _decode_text(self, ids: list[int]) -> str:
        from kubeflow_tpu.serve.tokenizer_util import decode_ids

        return decode_ids(self.tokenizer, ids)

    def _submit_kwargs(self, payload: dict) -> dict:
        return dict(
            max_tokens=int(payload.get("max_tokens", 32)),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            eos_id=payload.get("eos_id", self.eos_id),
            adapter=payload.get("adapter"),
            timeout=float(payload.get("timeout", 300.0)),
            # In-process deadline/trace propagation: the server stashes
            # the request's Deadline under "_deadline" and its
            # X-Request-Id under "_trace" (never wire fields).
            deadline=payload.get("_deadline"),
            trace_id=payload.get("_trace", ""))

    def generate(self, payload: dict) -> dict:
        if not self.ready or self.engine is None:
            raise RuntimeError(f"model {self.name} is not loaded")
        ids = self._resolve_ids(payload)
        out = self.engine.submit(ids, **self._submit_kwargs(payload))
        if self.tokenizer is not None:
            out["text"] = self._decode_text(out["output_ids"])
        out["decode_tokens_per_sec"] = round(self.engine.throughput(), 2)
        return out

    def generate_stream(self, payload: dict):
        """Generator of streaming events: {"tokens": [...]} (plus
        "text_delta" when a tokenizer is bundled) per emitted chunk, then
        a final {"done": true, ...summary} — the huggingfaceserver
        streaming surface, chunk-granular (the engine's scheduling
        quantum)."""
        if not self.ready or self.engine is None:
            raise RuntimeError(f"model {self.name} is not loaded")
        ids = self._resolve_ids(payload)
        kwargs = self._submit_kwargs(payload)
        events: queue.Queue = queue.Queue()

        def on_tokens(tokens, done):
            events.put(("tok", tokens, done))

        def run():
            try:
                events.put(("final", self.engine.submit(
                    ids, on_tokens=on_tokens, **kwargs), None))
            except Exception as e:  # surfaced to the consumer
                events.put(("error", e, None))

        threading.Thread(target=run, daemon=True,
                         name="tpk-generate-stream").start()
        emitted: list[int] = []
        # Windowed incremental detokenization (the vLLM recipe): decode
        # only from a trailing offset, emit the suffix beyond the
        # previously rendered window, and hold back while the tail is an
        # incomplete codepoint — O(window) per chunk instead of
        # re-decoding the whole prefix (quadratic in output length), and
        # deltas telescope to the exact full decode.
        prefix_off = read_off = 0
        sent_text = ""
        deadline = time.monotonic() + kwargs["timeout"] + 10.0
        while True:
            try:
                kind, val, done = events.get(
                    timeout=max(deadline - time.monotonic(), 1.0))
            except queue.Empty:
                raise RuntimeError(
                    f"generation stream timed out after "
                    f"{kwargs['timeout']}s") from None
            if kind == "error":
                raise val
            if kind == "final":
                out = dict(val)
                if self.tokenizer is not None:
                    out["text"] = self._decode_text(out["output_ids"])
                    # Flush anything still held back by the window.
                    out["text_delta"] = (
                        out["text"][len(sent_text):]
                        if out["text"].startswith(sent_text) else "")
                out["decode_tokens_per_sec"] = round(
                    self.engine.throughput(), 2)
                yield {"done": True, **out}
                return
            if not val:
                continue
            emitted.extend(val)
            ev: dict = {"tokens": [int(t) for t in val]}
            if self.tokenizer is not None:
                prev = self._decode_text(emitted[prefix_off:read_off])
                text = self._decode_text(emitted[prefix_off:])
                # Emit ONLY when the new rendering strictly extends the
                # previous one and its tail is not a possibly-incomplete
                # codepoint (U+FFFD). Anything else — a held partial, or
                # a rewrite where a completing codepoint replaces an
                # earlier U+FFFD — stays buffered: an emitted delta can
                # never be retracted, and the final event's residue flush
                # delivers whatever was held, so deltas always join to
                # the exact full decode.
                if (len(text) > len(prev) and text.startswith(prev)
                        and not text.endswith("�")):
                    ev["text_delta"] = text[len(prev):]
                    prefix_off, read_off = read_off, len(emitted)
                else:
                    ev["text_delta"] = ""
                sent_text += ev["text_delta"]
            yield ev

    def prefill_ship(self, payload: dict) -> dict:
        """POST :prefill backend — chunk-prefill and return the KV
        shipment (disaggregation phase 1). The caller's stream flag and
        requested surface ride the shipment's `extra` so the decode
        replica can answer in the right shape."""
        if not self.ready or self.engine is None:
            raise RuntimeError(f"model {self.name} is not loaded")
        ids = self._resolve_ids(payload)
        kwargs = self._submit_kwargs(payload)
        kwargs.pop("timeout", None)
        deadline = kwargs.pop("deadline", None)
        trace = kwargs.pop("trace_id", "")
        return self.engine.prefill_ship(
            ids, deadline=deadline, trace_id=trace,
            timeout=float(payload.get("timeout", 300.0)),
            extra={"stream": bool(payload.get("stream"))}, **kwargs)

    def decode_remote(self, shipment, *, deadline=None,
                      trace_id: str = "") -> dict:
        """POST :decode backend (non-stream): admit a shipment straight
        into decode and block for the full result."""
        if not self.ready or self.engine is None:
            raise RuntimeError(f"model {self.name} is not loaded")
        out = self.engine.submit_remote(shipment, deadline=deadline,
                                        trace_id=trace_id)
        if self.tokenizer is not None:
            out["text"] = self._decode_text(out["output_ids"])
        out["decode_tokens_per_sec"] = round(self.engine.throughput(), 2)
        return out

    def decode_remote_stream(self, shipment, *, deadline=None,
                             trace_id: str = ""):
        """Streaming :decode backend: the generate_stream event shape
        (chunk token events, final done summary) over a remote
        admission.

        RESUME CURSOR (ISSUE 14): `resume_skip` in the shipment meta is
        the number of leading tokens the original caller was already
        served before its previous decode replica died mid-stream. The
        engine replays the SAME deterministic token stream (the shipment
        carries the post-prefill RNG key and sampling params), and this
        layer suppresses the first `resume_skip` tokens from the CHUNK
        events — the resumed stream continues exactly where the dead one
        stopped. The final done event still carries the FULL output_ids/
        logprobs, identical to an uninterrupted run's."""
        if not self.ready or self.engine is None:
            raise RuntimeError(f"model {self.name} is not loaded")
        from kubeflow_tpu.serve.kv_transfer import peek_meta

        meta = peek_meta(shipment)
        skip = int(meta.get("resume_skip", 0))
        if skip < 0 or skip > int(meta.get("max_tokens", 32)):
            raise ValueError(
                f"resume_skip {skip} outside [0, max_tokens="
                f"{meta.get('max_tokens')}]")
        # Bound the event wait by the SHIPPED request budget (+ grace),
        # mirroring generate_stream's clock — never a magic constant
        # coupled to submit_remote's default.
        timeout_s = float(meta.get("timeout", 300.0))
        events: queue.Queue = queue.Queue()

        def on_tokens(tokens, done):
            events.put(("tok", tokens, done))

        def run():
            try:
                events.put(("final", self.engine.submit_remote(
                    shipment, deadline=deadline, trace_id=trace_id,
                    on_tokens=on_tokens), None))
            except Exception as e:
                events.put(("error", e, None))

        threading.Thread(target=run, daemon=True,
                         name="tpk-decode-remote-stream").start()
        stream_deadline = time.monotonic() + timeout_s + 10.0
        while True:
            try:
                kind, val, _done = events.get(
                    timeout=max(stream_deadline - time.monotonic(), 1.0))
            except queue.Empty:
                raise RuntimeError(
                    f"remote decode stream timed out after "
                    f"{timeout_s}s") from None
            if kind == "error":
                raise val
            if kind == "final":
                out = dict(val)
                if self.tokenizer is not None:
                    out["text"] = self._decode_text(out["output_ids"])
                out["decode_tokens_per_sec"] = round(
                    self.engine.throughput(), 2)
                yield {"done": True, **out}
                return
            if skip:
                # Replayed tokens the caller already holds: drop them
                # from the chunk stream (the done summary stays full).
                dropped = min(skip, len(val))
                skip -= dropped
                val = val[dropped:]
            if val:
                yield {"tokens": [int(t) for t in val]}

    def predict(self, inputs):
        """Full-forward logits (no cache) — v1/v2 infer parity."""
        toks = jnp.asarray(np.asarray(inputs[0], np.int32))
        logits = self._model.apply({"params": self._params}, toks)
        return [np.asarray(logits, np.float32)]

    def metadata(self) -> dict:
        md = super().metadata()
        md.update({
            "generative": True,
            "max_len": self._gen_cfg.get("max_len", 256),
            "vocab_size": getattr(self.cfg, "vocab_size", None),
            "stats": self.engine.stats_snapshot() if self.engine else {},
            "mesh": self._mesh_spec or None,
        })
        if self.engine:
            md["decode_buckets"] = list(self.engine.decode_buckets)
            md["pipeline_depth"] = self.engine.pipeline_depth
            md["speculative"] = self.engine._spec is not None
            md["paged_kv"] = self.engine.kv_info()
            md["role"] = self.engine.role
            if self.engine.adapter_names():
                md["adapters"] = self.engine.adapter_names()
        return md
