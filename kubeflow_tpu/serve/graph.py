"""InferenceGraph executor — KServe's router (⟨kserve: cmd/router —
InferenceGraph sequence/switch/ensemble/splitter nodes⟩, SURVEY.md §2.2).

The reference deploys a Go router container that fans HTTP requests across
InferenceServices per a graph CR. Here the graph is a spec interpreted by
`GraphModel`, which plugs into the ModelRepository like any model — so a
graph is served through the same v1/v2 HTTP surface, composing models
hosted in-process (or any callable, e.g. an HTTP client to a remote
InferenceService endpoint).

Node types (KServe parity):
  sequence  steps run in order, each output feeding the next input
  switch    route by a request field against per-case targets
  ensemble  run members on the same input, merge outputs
  splitter  weighted random routing across targets (canary/AB)

Spec:
  {"root": "pre",
   "nodes": {
     "pre":  {"type": "sequence", "steps": [{"model": "tokenizer"},
                                             {"node": "route"}]},
     "route": {"type": "switch", "field": "lang",
               "cases": {"en": {"model": "clf_en"}},
               "default": {"model": "clf_multi"}},
     "ab":   {"type": "splitter", "targets": [{"model": "v1"},
                                               {"model": "v2"}],
              "weights": [0.9, 0.1]},
     "vote": {"type": "ensemble", "members": [{"model": "a"},
                                               {"model": "b"}],
              "merge": "average"}}}
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable, Mapping

import numpy as np

from kubeflow_tpu.serve.model import Model


class GraphError(ValueError):
    pass


# A target is {"model": name} (resolved through predict_fn) or
# {"node": name} (recurse into the graph).
PredictFn = Callable[[str, Any], Any]


class GraphExecutor:
    def __init__(self, spec: Mapping[str, Any], predict_fn: PredictFn,
                 seed: int | None = None):
        self.nodes = dict(spec.get("nodes") or {})
        self.root = spec.get("root")
        if not self.root or self.root not in self.nodes:
            raise GraphError(f"graph root {self.root!r} not in nodes")
        self.predict_fn = predict_fn
        self._rng = random.Random(seed)
        for name, node in self.nodes.items():
            self._validate_node(name, node)

    def _validate_node(self, name: str, node: Mapping[str, Any]) -> None:
        t = node.get("type")
        if t == "sequence":
            if not node.get("steps"):
                raise GraphError(f"sequence node {name!r} has no steps")
            targets = node["steps"]
        elif t == "switch":
            if not node.get("field"):
                raise GraphError(f"switch node {name!r} needs `field`")
            targets = list((node.get("cases") or {}).values())
            if node.get("default"):
                targets.append(node["default"])
            if not targets:
                raise GraphError(f"switch node {name!r} has no cases")
        elif t == "ensemble":
            if not node.get("members"):
                raise GraphError(f"ensemble node {name!r} has no members")
            targets = node["members"]
        elif t == "splitter":
            targets = node.get("targets") or []
            weights = node.get("weights") or []
            if not targets or len(weights) != len(targets):
                raise GraphError(
                    f"splitter node {name!r} needs targets + matching "
                    f"weights")
            if any(w < 0 for w in weights) or sum(weights) <= 0:
                raise GraphError(
                    f"splitter node {name!r}: weights must be >= 0 with a "
                    f"positive sum, got {weights}")
        else:
            raise GraphError(f"node {name!r}: unknown type {t!r}")
        for tgt in targets:
            if "node" in tgt:
                if tgt["node"] not in self.nodes:
                    raise GraphError(
                        f"node {name!r} references unknown node "
                        f"{tgt['node']!r}")
            elif "model" not in tgt:
                raise GraphError(
                    f"node {name!r}: target needs `model` or `node`: {tgt}")

    def _run_target(self, target: Mapping[str, Any], payload: Any,
                    depth: int) -> Any:
        if "node" in target:
            return self._run_node(target["node"], payload, depth + 1)
        return self.predict_fn(target["model"], payload)

    def _run_node(self, name: str, payload: Any, depth: int = 0) -> Any:
        if depth > 32:
            raise GraphError("graph recursion depth exceeded (cycle?)")
        node = self.nodes[name]
        t = node["type"]
        if t == "sequence":
            for step in node["steps"]:
                payload = self._run_target(step, payload, depth)
            return payload
        if t == "switch":
            key = None
            if isinstance(payload, Mapping):
                key = payload.get(node["field"])
            case = (node.get("cases") or {}).get(str(key))
            if case is None:
                case = node.get("default")
            if case is None:
                raise GraphError(
                    f"switch {name!r}: no case for {key!r} and no default")
            return self._run_target(case, payload, depth)
        if t == "ensemble":
            outs = [self._run_target(m, payload, depth)
                    for m in node["members"]]
            return self._merge(node.get("merge", "all"), outs)
        if t == "splitter":
            (target,) = self._rng.choices(node["targets"],
                                          weights=node["weights"])
            return self._run_target(target, payload, depth)
        raise GraphError(f"unknown node type {t!r}")  # unreachable

    @staticmethod
    def _merge(mode: str, outs: list) -> Any:
        # Normalize member outputs to arrays: {"instances": ...} payload
        # dicts (the GraphModel HTTP flow), [tensor, ...] lists (direct
        # model outputs), or bare arrays.
        def arr(o):
            if isinstance(o, Mapping):
                return np.asarray(o["instances"])
            if isinstance(o, (list, tuple)):
                return np.asarray(o[0])
            return np.asarray(o)

        vals = [arr(o) for o in outs]
        if mode == "all":
            merged = [v.tolist() for v in vals]
        elif mode == "average":
            merged = np.mean(vals, axis=0)
        elif mode == "concat":
            merged = np.concatenate(vals, axis=-1)
        else:
            raise GraphError(f"unknown ensemble merge {mode!r}")
        if isinstance(outs[0], Mapping):
            rest = {k: v for k, v in outs[0].items() if k != "instances"}
            return {**rest, "instances": merged}
        if isinstance(outs[0], (list, tuple)) and mode != "all":
            return [merged]
        return merged

    def __call__(self, payload: Any) -> Any:
        return self._run_node(self.root, payload)


class GraphModel(Model):
    """Serves an InferenceGraph through the model server: registered in the
    ModelRepository like any model, its predict() walks the graph against
    sibling models in the same repository.

    Graphs take the RAW request body (`wants_raw_payload`): the server
    hands predict() the JSON dict (`{"instances": ..., **fields}`) instead
    of pre-extracted tensors, so switch nodes can route on request fields —
    per-request routing is fundamentally incompatible with cross-request
    batch coalescing, so graphs bypass the batcher entirely (sibling models
    invoked through the graph still use their own compiled buckets)."""

    wants_raw_payload = True

    # Guards mutual recursion BETWEEN GraphModels (A -> B -> A): each
    # predict() walk shares one thread-local depth budget.
    _recursion = threading.local()

    def __init__(self, name: str, spec: Mapping[str, Any], repo,
                 seed: int | None = None):
        super().__init__(name)
        self.spec = dict(spec)
        self.repo = repo
        self.executor = GraphExecutor(spec, self._predict_model, seed=seed)

    def _predict_model(self, model_name: str, payload: Any) -> Any:
        if model_name == self.name:
            raise GraphError("graph cannot reference itself")
        model = self.repo.get(model_name)
        if isinstance(payload, Mapping):
            # HTTP flow: pull tensors out, run the model, thread the
            # routing fields through so downstream switches still see them.
            inputs = [np.asarray(payload["instances"])]
            outs = model.predict(inputs)
            out = outs[0] if isinstance(outs, (list, tuple)) else outs
            rest = {k: v for k, v in payload.items() if k != "instances"}
            return {**rest, "instances": np.asarray(out)}
        if isinstance(payload, (list, tuple)):
            return model.predict(payload)
        return model(payload)

    def load(self) -> bool:
        self.ready = True
        return True

    def predict(self, inputs: Any) -> Any:
        depth = getattr(self._recursion, "depth", 0)
        if depth > 16:
            raise GraphError(
                "graph recursion depth exceeded (mutually referencing "
                "graphs?)")
        self._recursion.depth = depth + 1
        try:
            return self.executor(inputs)
        finally:
            self._recursion.depth = depth

    def metadata(self) -> dict:
        return {"name": self.name, "platform": "tpk-inference-graph",
                "inputs": [], "outputs": [],
                "nodes": sorted(self.executor.nodes)}
