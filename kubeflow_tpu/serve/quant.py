"""Weight-only int8 quantization for serving.

The reference's LLM runtime leans on vLLM's GPU quantization back ends
(⟨kserve: python/huggingfaceserver — vLLM engine args⟩, SURVEY.md §2.2).
The TPU-native equivalent for a serving-side win is *weight-only* int8:
weights sit in HBM at half the bf16 footprint and the dequantize (a
per-channel multiply) fuses into the consuming matmul's operand read under
XLA — decode steps are HBM-bandwidth-bound, so halving weight bytes is a
direct throughput lever (ops/ROADMAP.md item: quantized serving).

Scheme: symmetric per-channel (max-abs over the largest axis — the
contraction/in dim for 2-D kernels and scanned layer stacks alike) int8,
fp32 scales with that axis kept at 1 for broadcast. Quantized leaves are a
registered pytree node (`Int8Leaf`), so the quantized tree flows through
jit / device_put / AOT lowering like any params tree, and `QuantizedModule`
makes it transparent to every consumer that calls `model.apply` (the
generation engine, AOT-bucketed predictors, graph nodes).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class Int8Leaf:
    """int8 values + fp32 per-channel scales; w ≈ q * scale."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    def dequantize(self, dtype=jnp.bfloat16):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _is_quant_leaf(x: Any) -> bool:
    return isinstance(x, Int8Leaf)


def _contraction_axes(path_names: list[str], ndim: int) -> tuple[int, ...]:
    """Axes to max-abs over = the matmul CONTRACTION axes, so scales are
    per-output-channel (the standard weight-only scheme) and tiny. Known
    kernel families by name; a leading scan/layers dim (ndim >= 3) is
    never reduced — scales stay per-layer. Reducing over axis 0
    unconditionally (the old scheme) maxed over LAYERS on scanned stacks
    and stored a near-full-size fp32 scale tensor."""
    if any(n in path_names for n in ("o_proj", "o")) and ndim >= 3:
        return (ndim - 3, ndim - 2)  # [..., heads, head_dim, out]
    if any(n in path_names
           for n in ("q_proj", "k_proj", "v_proj", "q", "k", "v")) \
            and ndim >= 3:
        return (ndim - 3,)           # [..., in, heads, head_dim]
    if path_names and path_names[-1] in ("embed", "wte",
                                         "shared_embedding"):
        # Tied embeddings across families (Llama "embed", GPT-2 "wte",
        # T5 "shared_embedding"): [vocab, D], the unembed contracts D.
        return (ndim - 1,)
    return (ndim - 2,)               # [..., in, out]


def quantize_tree(params: Any, *, min_size: int = 4096) -> Any:
    """Replace large float leaves with Int8Leaf.

    Leaves smaller than `min_size` elements (norm scales, biases) stay in
    full precision — they are bandwidth-irrelevant and precision-critical.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)

    def quant(path, leaf):
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        arr = jnp.asarray(leaf)
        if arr.ndim < 2 or arr.size < min_size:
            return leaf
        # Dict keys only: boxed params (nn.Partitioned) append attr keys
        # like `.value` that would shadow the trailing param name.
        names = [str(k.key) for k in path if hasattr(k, "key")]
        if "router" in names:
            # MoE router: int8 noise can FLIP top-k expert assignment —
            # a discrete routing change, not a smooth dequant error. The
            # tensor is bandwidth-trivial next to the experts it gates.
            return leaf
        if names and names[-1] == "bias":
            # Additive biases (Qwen2 QKV): bandwidth-trivial, and the
            # name-based contraction-axis table below is kernel-shaped —
            # it would pick a nonsense scale axis for a bias tensor.
            return leaf
        a32 = arr.astype(jnp.float32)
        amax = jnp.max(jnp.abs(a32),
                       axis=_contraction_axes(names, arr.ndim),
                       keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(a32 / scale), -127, 127).astype(jnp.int8)
        return Int8Leaf(q, scale)

    return jax.tree_util.tree_unflatten(
        treedef, [quant(p, l) for p, l in flat])


def dequantize_tree(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse of quantize_tree; runs inside jit so XLA fuses the multiply
    into the consuming matmul's operand read."""
    return jax.tree.map(
        lambda leaf: leaf.dequantize(dtype) if _is_quant_leaf(leaf) else leaf,
        params, is_leaf=_is_quant_leaf)


def quantized_bytes(params: Any) -> dict:
    """{"quantized": n, "full": n} parameter byte counts for metadata.
    `full` is the bf16 baseline (what the server would otherwise hold),
    so full/quantized is the honest HBM saving — about 2×."""
    qb = fb = 0
    for leaf in jax.tree.leaves(params, is_leaf=_is_quant_leaf):
        if _is_quant_leaf(leaf):
            qb += leaf.q.size + leaf.scale.size * 4
            fb += leaf.q.size * 2  # bf16
        elif hasattr(leaf, "nbytes"):
            qb += leaf.nbytes
            fb += leaf.nbytes
    return {"quantized": int(qb), "full": int(fb)}


class QuantizedModule:
    """Wraps a flax module so `apply` sees dequantized params — quantization
    becomes a storage detail invisible to the model code and to every
    serving path that holds a (module, params) pair."""

    def __init__(self, module: Any, dtype: Any = jnp.bfloat16):
        self.module = module
        self.dtype = dtype

    def apply(self, variables: dict, *args, **kwargs):
        variables = dict(variables)
        variables["params"] = dequantize_tree(variables["params"],
                                              self.dtype)
        return self.module.apply(variables, *args, **kwargs)

    def __getattr__(self, name):  # cfg etc. pass through
        return getattr(self.module, name)
