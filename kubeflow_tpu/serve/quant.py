"""Weight-only int8 quantization for serving.

The reference's LLM runtime leans on vLLM's GPU quantization back ends
(⟨kserve: python/huggingfaceserver — vLLM engine args⟩, SURVEY.md §2.2).
The TPU-native equivalent for a serving-side win is *weight-only* int8:
weights sit in HBM at half the bf16 footprint and the dequantize (a
per-channel multiply) fuses into the consuming matmul's operand read under
XLA — decode steps are HBM-bandwidth-bound, so halving weight bytes is a
direct throughput lever (ops/ROADMAP.md item: quantized serving).

Scheme: symmetric per-channel (max-abs over the largest axis — the
contraction/in dim for 2-D kernels and scanned layer stacks alike) int8,
fp32 scales with that axis kept at 1 for broadcast. Quantized leaves are a
registered pytree node (`Int8Leaf`), so the quantized tree flows through
jit / device_put / AOT lowering like any params tree, and `QuantizedModule`
makes it transparent to every consumer that calls `model.apply` (the
generation engine, AOT-bucketed predictors, graph nodes).

Dequant placement (the SERVEBENCH 0.747x defect, ROADMAP item 4): the
original wrapper dequantized the WHOLE tree per `apply` — `(q * scale)`
is a full-weight-shaped multiply, and a multiply feeding a dot operand
does not fuse into the matmul's operand read, so every decode step
inside the chunk scan materialized every weight at full bf16 width
(verified in the compiled HLO: the convert+multiply fusions carry
`while/body` metadata). Per step that is int8 + bf16 weight traffic —
~1.5x the bf16 baseline's bytes, which is exactly the measured 0.747x
throughput. The fix moves the scale to the OTHER side of the matmul:
`x @ (q * s) == (x @ q) * s` when `s` is per-output-channel (the
contraction dims of the scale are 1), so `Int8DenseGeneral` feeds the
dot the RAW int8 kernel through a bare convert — which XLA does fuse
into the operand read — and applies the scale to the `[B, S, out]`
output, a bandwidth-trivial multiply. No full-size dequantized weight
tensor exists anywhere in the program; the HLO-shape guard test pins
this (tests/test_kv_transfer.py is the serving suite; the guard lives
in tests/test_quant_dequant.py).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class Int8Leaf:
    """int8 values + fp32 per-channel scales; w ≈ q * scale."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    def dequantize(self, dtype=jnp.bfloat16):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _is_quant_leaf(x: Any) -> bool:
    return isinstance(x, Int8Leaf)


def _contraction_axes(path_names: list[str], ndim: int) -> tuple[int, ...]:
    """Axes to max-abs over = the matmul CONTRACTION axes, so scales are
    per-output-channel (the standard weight-only scheme) and tiny. Known
    kernel families by name; a leading scan/layers dim (ndim >= 3) is
    never reduced — scales stay per-layer. Reducing over axis 0
    unconditionally (the old scheme) maxed over LAYERS on scanned stacks
    and stored a near-full-size fp32 scale tensor."""
    if any(n in path_names for n in ("o_proj", "o")) and ndim >= 3:
        return (ndim - 3, ndim - 2)  # [..., heads, head_dim, out]
    if any(n in path_names
           for n in ("q_proj", "k_proj", "v_proj", "q", "k", "v")) \
            and ndim >= 3:
        return (ndim - 3,)           # [..., in, heads, head_dim]
    if path_names and path_names[-1] in ("embed", "wte",
                                         "shared_embedding"):
        # Tied embeddings across families (Llama "embed", GPT-2 "wte",
        # T5 "shared_embedding"): [vocab, D], the unembed contracts D.
        return (ndim - 1,)
    return (ndim - 2,)               # [..., in, out]


def quantize_tree(params: Any, *, min_size: int = 4096) -> Any:
    """Replace large float leaves with Int8Leaf.

    Leaves smaller than `min_size` elements (norm scales, biases) stay in
    full precision — they are bandwidth-irrelevant and precision-critical.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)

    def quant(path, leaf):
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        arr = jnp.asarray(leaf)
        if arr.ndim < 2 or arr.size < min_size:
            return leaf
        # Dict keys only: boxed params (nn.Partitioned) append attr keys
        # like `.value` that would shadow the trailing param name.
        names = [str(k.key) for k in path if hasattr(k, "key")]
        if "router" in names:
            # MoE router: int8 noise can FLIP top-k expert assignment —
            # a discrete routing change, not a smooth dequant error. The
            # tensor is bandwidth-trivial next to the experts it gates.
            return leaf
        if names and names[-1] == "bias":
            # Additive biases (Qwen2 QKV): bandwidth-trivial, and the
            # name-based contraction-axis table below is kernel-shaped —
            # it would pick a nonsense scale axis for a bias tensor.
            return leaf
        a32 = arr.astype(jnp.float32)
        amax = jnp.max(jnp.abs(a32),
                       axis=_contraction_axes(names, arr.ndim),
                       keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(a32 / scale), -127, 127).astype(jnp.int8)
        return Int8Leaf(q, scale)

    return jax.tree_util.tree_unflatten(
        treedef, [quant(p, l) for p, l in flat])


def dequantize_tree(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse of quantize_tree; runs inside jit so XLA fuses the multiply
    into the consuming matmul's operand read."""
    return jax.tree.map(
        lambda leaf: leaf.dequantize(dtype) if _is_quant_leaf(leaf) else leaf,
        params, is_leaf=_is_quant_leaf)


def quantized_bytes(params: Any) -> dict:
    """{"quantized": n, "full": n} parameter byte counts for metadata.
    `full` is the bf16 baseline (what the server would otherwise hold),
    so full/quantized is the honest HBM saving — about 2×."""
    qb = fb = 0
    for leaf in jax.tree.leaves(params, is_leaf=_is_quant_leaf):
        if _is_quant_leaf(leaf):
            qb += leaf.q.size + leaf.scale.size * 4
            fb += leaf.q.size * 2  # bf16
        elif hasattr(leaf, "nbytes"):
            qb += leaf.nbytes
            fb += leaf.nbytes
    return {"quantized": int(qb), "full": int(fb)}


# --- KV-cache block quantization (ISSUE 19) ---------------------------
#
# The paged KV pool's blocks become int8/fp8 payloads with f32 scales in
# a parallel pool, addressed by the SAME block ids ("ks"/"vs" next to
# "k"/"v") — so every layer that trades in block ids (prefix refs, CoW
# forks, host-tier spills, TPKV1 shipments) carries scales by carrying
# ids, and `BlockAllocator` never learns about quantization. Scales are
# per-row-per-head (amax over head_dim): a coarser per-block scale could
# not honor "scatter-back re-quantizes only newly written rows" — the
# new row would either move the shared scale (silently re-encoding every
# committed row in the block) or clip against the old one. Row scales
# make each row's encoding independent, so committed rows are immutable
# bytes exactly like the unquantized pool.
#
# Dequant placement mirrors Int8DenseGeneral (the ISSUE 13 lesson),
# lifted to attention: Q·Kᵀ and probs·V read the RAW quantized cache
# through a bare convert, and the row scales land on the score/prob
# tensors ([B, KH, G, S, T]-shaped — no [..., T, KH, D] cache-width
# multiply anywhere in the decode scan). See ops/reference.py
# `naive_attention(k_scale=, v_scale=)`.

#: Legal `kv_quant` knob values. "none" is the bit-exact escape hatch.
KV_QUANT_MODES = ("none", "int8", "fp8")


def kv_qdtype(mode: str):
    """Storage dtype of a quantized KV pool."""
    return {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}[mode]


def kv_qmax(mode: str) -> float:
    """Largest representable magnitude the scale normalizes amax onto."""
    return {"int8": 127.0, "fp8": 448.0}[mode]


def kv_quantize_rows(rows, mode: str):
    """Quantize `[..., D]` float rows → (q `[..., D]`, scale `[...]` f32).

    Symmetric per-row max-abs over the head_dim axis; int8 rounds to
    nearest, fp8 relies on the cast's RNE. All-zero rows get the eps
    scale and encode to exact zeros, so NULL-block garbage stays inert.
    """
    r32 = rows.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(r32), axis=-1),
                        1e-12) / kv_qmax(mode)
    q = r32 / scale[..., None]
    if mode == "int8":
        q = jnp.clip(jnp.round(q), -127, 127)
    return q.astype(kv_qdtype(mode)), scale


def kv_dequantize_rows(q, scale, dtype=jnp.bfloat16):
    """Inverse of kv_quantize_rows — ADMISSION-side only (fragment
    reconstruction for prefix reuse / shipment import). The decode scan
    never calls this: it would be exactly the full-width materialization
    the HLO guard forbids."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


class Int8DenseGeneral(nn.Module):
    """`nn.DenseGeneral` twin that understands `Int8Leaf` kernels.

    Same constructor surface as the subset the model families use
    (features tuple, `axis`, optional bias, dtype/param_dtype, inits)
    and the same param names/shapes, so a quantized tree produced from
    an `nn.DenseGeneral` init slots straight in. With a plain-array
    kernel it reproduces DenseGeneral's math (promote + dot_general) —
    but the plain path only ever runs at init: the class is selected by
    `cfg.quantized_dense`, which only `QuantizedModule` sets, so
    unquantized serving never constructs it.

    The Int8 path is the dequant-placement fix (module docstring): the
    dot reads the int8 kernel through a bare convert (fusable into the
    operand read — no full-size weight temp), and the per-output-channel
    scale lands on the `[..., out]` OUTPUT in f32 before the cast back,
    which is also where the legacy scheme's precision lived (f32
    multiply, then cast)."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    use_bias: bool = True
    dtype: Any = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, inputs):
        feats = ((self.features,) if isinstance(self.features, int)
                 else tuple(self.features))
        axes = ((self.axis,) if isinstance(self.axis, int)
                else tuple(self.axis))
        axes = tuple(a % inputs.ndim for a in axes)
        kshape = tuple(inputs.shape[a] for a in axes) + feats
        kernel = self.param("kernel", self.kernel_init, kshape,
                            self.param_dtype)
        bias = (self.param("bias", self.bias_init, feats,
                           self.param_dtype) if self.use_bias else None)
        contract = ((axes, tuple(range(len(axes)))), ((), ()))
        if isinstance(kernel, Int8Leaf):
            out_dtype = self.dtype or inputs.dtype
            # f32 accumulation: int8 dots natively accumulate wide (the
            # MXU does this for free), and the f32 partials + f32 scale
            # make this path strictly MORE precise than the legacy
            # dequantize-then-bf16-matmul, not just cheaper.
            y = jax.lax.dot_general(inputs.astype(out_dtype),
                                    kernel.q.astype(out_dtype), contract,
                                    preferred_element_type=jnp.float32)
            scale = kernel.scale.reshape(feats)  # contraction dims are 1
            y = (y * scale).astype(out_dtype)
        else:
            inputs, kernel = nn.dtypes.promote_dtype(inputs, kernel,
                                                     dtype=self.dtype)
            y = jax.lax.dot_general(inputs, kernel, contract)
        if bias is not None:
            bias = jnp.asarray(bias, y.dtype)
            y = y + bias.reshape((1,) * (y.ndim - len(feats)) + feats)
        return y


def quant_embed_lookup(embed: Any, tokens, dtype):
    """Token-embedding gather with Int8Leaf awareness: gather the int8
    rows and the matching per-row scales, multiply AFTER the gather —
    `[B, S, D]` work instead of dequantizing the whole `[V, D]` table
    per call (which the decode scan would otherwise pay per step)."""
    if not isinstance(embed, Int8Leaf):
        return embed.astype(dtype)[tokens]
    rows = embed.q[tokens].astype(jnp.float32)
    return (rows * embed.scale[tokens]).astype(dtype)


def quant_unembed(x, embed: Any, dtype):
    """Tied-embedding unembed `x @ embed.T` with the scale applied to
    the logits (per-vocab-row scale = per-output-channel of the
    transposed matmul) — the same output-side placement as
    Int8DenseGeneral."""
    if not isinstance(embed, Int8Leaf):
        return jnp.einsum("bsh,vh->bsv", x, embed.astype(dtype))
    logits = jnp.einsum("bsh,vh->bsv", x, embed.q.astype(dtype))
    return (logits.astype(jnp.float32)
            * embed.scale.reshape(1, 1, -1)).astype(dtype)


class QuantizedModule:
    """Wraps a flax module so `apply` serves a quantized params tree —
    quantization stays a storage detail invisible to every serving path
    that holds a (module, params) pair.

    Modules whose config carries a `quantized_dense` field (the Llama
    family — llama/mistral/qwen/gemma configs) are REBUILT with the flag
    set: their dense/embed sites consume `Int8Leaf` leaves natively
    (`Int8DenseGeneral` — output-side scale, no full-weight dequant), so
    `apply` passes `kernel`/`embed` leaves through raw and dequantizes
    only the rest (MoE expert stacks, other families' tensors).
    `legacy_dequant=True` restores the old dequantize-everything wrapper
    — the A/B control for the SERVEBENCH `quant` row."""

    def __init__(self, module: Any, dtype: Any = jnp.bfloat16,
                 legacy_dequant: bool = False):
        self.dtype = dtype
        self.legacy_dequant = bool(legacy_dequant)
        cfg = getattr(module, "cfg", None)
        self._native_quant = (not legacy_dequant and cfg is not None
                              and hasattr(cfg, "quantized_dense"))
        if self._native_quant and not cfg.quantized_dense:
            import dataclasses

            # Rebuild by REPLACING the module's cfg field, never by
            # re-constructing `type(module)(cfg)`: flax modules are
            # dataclasses, and reconstruction would drop every other
            # field (MoELlama's mlp_cls=MoEBlock — the routed-expert
            # trunk would silently become a dense MLPBlock whose params
            # don't exist).
            module = dataclasses.replace(
                module,
                cfg=dataclasses.replace(cfg, quantized_dense=True))
        self.module = module

    def _prepare(self, params: Any) -> Any:
        if not self._native_quant:
            return dequantize_tree(params, self.dtype)

        flat, treedef = jax.tree_util.tree_flatten_with_path(
            params, is_leaf=_is_quant_leaf)

        def prep(path, leaf):
            if not _is_quant_leaf(leaf):
                return leaf
            names = [str(k.key) for k in path if hasattr(k, "key")]
            tail = names[-1] if names else ""
            # Handled natively by the quant-aware sites; everything else
            # (MoE expert stacks etc.) keeps the legacy dequant.
            if tail in ("kernel", "embed"):
                return leaf
            return leaf.dequantize(self.dtype)

        return jax.tree_util.tree_unflatten(
            treedef, [prep(p, l) for p, l in flat])

    def apply(self, variables: dict, *args, **kwargs):
        variables = dict(variables)
        variables["params"] = self._prepare(variables["params"])
        return self.module.apply(variables, *args, **kwargs)

    def __getattr__(self, name):  # cfg etc. pass through
        return getattr(self.module, name)
