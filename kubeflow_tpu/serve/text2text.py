"""Text2text serving — the huggingfaceserver text2text_generation task.

Encoder-decoder checkpoints (T5 family) serve through `greedy_generate`
(models/t5.py): the WHOLE generate call — encoder, cross-KV precompute,
and the scanned decoder loop — is one AOT-compiled XLA executable per
input-length bucket. One host dispatch per request, which on the axon
tunnel (~66 ms per synchronous fetch, PROFILE.md §1) beats a per-token
decode loop by two orders of magnitude in dispatch overhead.

Trade-off vs the decoder-only GenerationEngine (serve/generation.py):
no continuous batching or streaming — text2text outputs are short
(translation/summarization), so whole-program latency is the right
shape; the engine's slot machinery would buy little and cost the
per-token host loop.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.serve.model import Model


class Text2TextJAXModel(Model):
    """KServe-Model-shaped wrapper over T5-class greedy generation.

    `generation` spec: {"in_buckets": [...], "max_tokens": N,
    "tokenizer": ... ("bytes" | HF tokenizer), "pad_id": 0}.
    """

    def __init__(self, name: str, model, params, cfg, *,
                 generation: dict | None = None):
        super().__init__(name)
        gen = dict(generation or {})
        self._model, self._params, self.cfg = model, params, cfg
        self.in_buckets = sorted({int(b) for b in
                                  gen.get("in_buckets", (16, 64))})
        self.max_tokens = int(gen.get("max_tokens", 64))
        self.pad_id = int(gen.get("pad_id", 0))
        self.tokenizer = gen.get("tokenizer")
        self._compiled: dict[int, Any] = {}
        # Requests run on arbitrary tornado executor threads (unlike the
        # engine's single worker) — the compile cache and stats need the
        # lock or two first requests double-compile a bucket.
        self._lock = threading.Lock()
        self.stats = {"requests": 0, "generated_tokens": 0,
                      "generate_s": 0.0, "compiles": 0}

    def _fn(self):
        from kubeflow_tpu.models.t5 import greedy_generate

        def run(params, input_ids, enc_mask):
            return greedy_generate(self._model, params, input_ids,
                                   enc_mask, max_tokens=self.max_tokens)

        return run

    def _executable(self, bucket: int):
        exe = self._compiled.get(bucket)
        if exe is not None:
            return exe
        with self._lock:
            exe = self._compiled.get(bucket)
            if exe is None:
                args = (jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                        jax.ShapeDtypeStruct((1, bucket), jnp.bool_))
                exe = (jax.jit(self._fn())
                       .lower(self._params, *args).compile())
                self._compiled[bucket] = exe
                self.stats["compiles"] += 1
        return exe

    def load(self) -> bool:
        t0 = time.monotonic()
        self._params = jax.device_put(self._params)
        self._executable(self.in_buckets[0])
        self.load_time_s = time.monotonic() - t0
        self.ready = True
        return True

    def unload(self) -> None:
        self.ready = False
        self._compiled.clear()

    def _resolve_ids(self, payload: dict) -> list[int]:
        from kubeflow_tpu.serve.tokenizer_util import resolve_ids

        ids = resolve_ids(self.tokenizer, payload)
        if len(ids) > self.in_buckets[-1]:
            raise ValueError(
                f"input of {len(ids)} tokens exceeds the largest bucket "
                f"{self.in_buckets[-1]}")
        return ids

    def generate(self, payload: dict) -> dict:
        if not self.ready:
            raise RuntimeError(f"model {self.name} is not loaded")
        ids = self._resolve_ids(payload)
        max_tokens = int(payload.get("max_tokens", self.max_tokens))
        if max_tokens > self.max_tokens:
            raise ValueError(
                f"max_tokens {max_tokens} exceeds the compiled budget "
                f"{self.max_tokens}")
        bucket = next(b for b in self.in_buckets if len(ids) <= b)
        toks = np.full((1, bucket), self.pad_id, np.int32)
        toks[0, :len(ids)] = ids
        mask = np.zeros((1, bucket), bool)
        mask[0, :len(ids)] = True
        t0 = time.monotonic()
        out_toks, n_valid = self._executable(bucket)(
            self._params, toks, mask)
        n = min(int(n_valid[0]), max_tokens)
        out_ids = [int(t) for t in np.asarray(out_toks)[0, :n]]
        dt = time.monotonic() - t0
        with self._lock:
            self.stats["requests"] += 1
            self.stats["generated_tokens"] += n
            self.stats["generate_s"] += dt
        result = {
            "output_ids": out_ids,
            "num_input_tokens": len(ids),
            "num_output_tokens": n,
            "latency_s": round(dt, 4),
        }
        if self.tokenizer is not None:
            from kubeflow_tpu.serve.tokenizer_util import decode_ids

            result["text"] = decode_ids(self.tokenizer, out_ids)
        return result

    def metadata(self) -> dict:
        return {"name": self.name, "platform": "jax-tpu",
                "task": "text2text_generation",
                "in_buckets": self.in_buckets,
                "max_tokens": self.max_tokens,
                "stats": dict(self.stats)}
