"""ModelServer — the serving data plane (KServe model server equivalent).

Speaks both protocols the reference serves (⟨kserve: python/kserve —
ModelServer, v1/v2 endpoints⟩, SURVEY.md §2.2/§3.3):

  v1:  POST /v1/models/{name}:predict      {"instances": [...]}
       GET  /v1/models/{name}              readiness
       GET  /v1/models                     list
  v2:  GET  /v2/health/{live,ready}
       GET  /v2/models/{name}[/ready]      metadata / readiness
       POST /v2/models/{name}/infer        open-inference tensors
       POST /v2/repository/models/{name}/{load,unload}
  ops: GET  /metrics                       prometheus text format

Inference runs through the coalescing Batcher (batcher.py) so concurrent
requests share one padded AOT device call; handlers stay async and await the
batcher future, keeping the event loop free (the reference gets the same
effect from uvicorn workers + the agent sidecar batcher).
"""

from __future__ import annotations

import argparse
import asyncio
import functools
import json
import math
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any

import numpy as np
import tornado.httpserver
import tornado.iostream
import tornado.ioloop
import tornado.netutil
import tornado.web

from kubeflow_tpu.serve.batcher import Batcher
from kubeflow_tpu.serve.generation import KVCapacityExceeded
from kubeflow_tpu.serve.model import Model, _v2_dtype, v2_to_numpy_dtype
# The wire header names live in serve/headers.py (dependency-free) so
# the router process can import them without paying THIS module's
# engine-stack import; they are re-exported here for compatibility.
# DEADLINE_HEADER: the KServe/Istio-style relative budget, deadline-
# propagated in-process — expiry anywhere on the request path
# (admission queue, batcher, generation) returns 504. REQUEST_ID_HEADER:
# the one trace identity (SURVEY.md §5.1 rebuild), threaded through
# admission, the batcher, and the engine spans (see /debug/trace).
from kubeflow_tpu.serve.headers import (DEADLINE_HEADER, DRAINING_HEADER,
                                        REQUEST_ID_HEADER)
from kubeflow_tpu.utils import obs
from kubeflow_tpu.utils.resilience import (Deadline, DeadlineExceeded,
                                           metrics as res_metrics)

#: GenerationEngine stats → /metrics series (ISSUE 3 observability): the
#: engine's own counters rendered per model on every scrape, so the
#: overlapped-scheduling claim (host-stall removal, overlapped fetches,
#: off-critical-path admissions) and the prefix-cache economy are
#: observable in Prometheus, not just in SERVEBENCH.json. The sentinel
#: "__depth__" row reads the engine attribute instead of a stats key.
_ENGINE_METRICS = (
    ("requests", "tpk_engine_requests_total", "counter"),
    ("prompt_tokens", "tpk_engine_prompt_tokens_total", "counter"),
    ("decode_tokens", "tpk_engine_decode_tokens_total", "counter"),
    ("decode_dispatches", "tpk_decode_dispatch_total", "counter"),
    ("prefix_hits", "tpk_engine_prefix_hits_total", "counter"),
    ("prefix_hit_tokens", "tpk_engine_prefix_hit_tokens_total", "counter"),
    ("prefix_misses", "tpk_engine_prefix_misses_total", "counter"),
    ("host_stall_seconds", "tpk_engine_host_stall_seconds_total",
     "counter"),
    ("admit_overlap", "tpk_admit_overlap_total", "counter"),
    ("decode_fetch_blocking", "tpk_engine_decode_fetch_blocking_total",
     "counter"),
    ("decode_fetch_overlapped",
     "tpk_engine_decode_fetch_overlapped_total", "counter"),
    ("decode_wasted_tokens", "tpk_engine_decode_wasted_tokens_total",
     "counter"),
    ("spec_dispatches", "tpk_engine_spec_dispatch_total", "counter"),
    # Speculative decoding observability (ISSUE 18): proposal/accept
    # volume and stale draft rides per model, so the sub-batch split
    # ("mixed traffic still speculates") and draft quality are
    # observable in production, not just in SERVEBENCH.json. The
    # accept-rate gauge is computed at scrape (accepted/proposed) and
    # only emitted once proposals flowed — draft-less engines and
    # idle spec engines emit no rate, never a fake 0.
    ("spec_proposed", "tpk_spec_proposed_total", "counter"),
    ("spec_accepted", "tpk_spec_accepted_total", "counter"),
    ("spec_stale_rides", "tpk_spec_stale_rides_total", "counter"),
    ("__spec_accept_rate__", "tpk_spec_accept_rate", "gauge"),
    # Paged KV cache (ISSUE 6): prefix hits served as zero-copy block
    # references, copy-on-write tail-block forks, and the live pool
    # occupancy admission decides by. Flat engines (kv_block_size=0)
    # emit the counters at 0 and skip the pool gauges.
    ("kv_cow_copies", "tpk_kv_cow_copies_total", "counter"),
    ("prefix_zero_copy_hits", "tpk_prefix_zero_copy_hits_total",
     "counter"),
    ("__kv_free__", "tpk_kv_blocks_free", "gauge"),
    ("__kv_used__", "tpk_kv_blocks_used", "gauge"),
    # Disaggregated prefill/decode + host-RAM spill tier (ISSUE 13):
    # prefill-chunk dispatches (a decode-role replica must read 0 —
    # the DISAGGBENCH mechanism pin), shipped/received wire blocks,
    # remote admissions, spill-tier traffic and residency.
    ("prefill_chunks", "tpk_engine_prefill_chunks_total", "counter"),
    ("remote_admits", "tpk_engine_remote_admits_total", "counter"),
    ("kv_blocks_shipped", "tpk_kv_blocks_shipped_total", "counter"),
    ("kv_blocks_received", "tpk_kv_blocks_received_total", "counter"),
    ("kv_spilled_blocks", "tpk_kv_spilled_blocks_total", "counter"),
    ("kv_restored_blocks", "tpk_kv_restored_blocks_total", "counter"),
    ("__kv_spill__", "tpk_kv_spill_blocks", "gauge"),
    # Quantized KV blocks (ISSUE 19): admission-side full-width dequant
    # materializations (prefix-hit fragment rebuilds — the ONE place
    # the quantized design allows one; the decode scan never pays it).
    # The mode itself renders as the tpk_kv_quant_mode info gauge
    # below, next to tpk_engine_role.
    ("kv_dequant_fallbacks", "tpk_kv_dequant_fallbacks_total",
     "counter"),
    # Live in-flight dispatch count (0 when drained; stuck at ≤1 means
    # the pipeline re-serialized) vs the configured ceiling.
    ("__inflight__", "tpk_decode_inflight_depth", "gauge"),
    ("__depth__", "tpk_engine_pipeline_depth", "gauge"),
)


class AdmissionController:
    """Bounded admission for the inference data plane — the KServe/
    Knative containerConcurrency + activator-queue behavior, in-process.

    At most `max_inflight` inference requests are admitted concurrently
    (admitted = queued in a batcher/engine OR executing). Beyond that the
    server SHEDS: 503 + `Retry-After` instead of unbounded queueing, and
    the readiness probe degrades (`/v2/health/ready` → 503) while the
    replica is actively rejecting work so the platform's LB/controller
    routes around it — fail fast and visibly, never silently queue into
    timeout. Merely being full does NOT degrade readiness (Knative's
    queue-proxy stays ready at containerConcurrency): a single long
    request on a small-capacity replica must not pull it from endpoints
    when nothing was rejected."""

    def __init__(self, max_inflight: int = 256,
                 retry_after_s: float = 1.0):
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got "
                             f"{max_inflight}")
        self.max_inflight = int(max_inflight)
        self.retry_after_s = float(retry_after_s)
        self._inflight = 0  # guarded-by: _lock
        self._last_shed = -float("inf")  # guarded-by: _lock
        self._lock = threading.Lock()

    def try_acquire(self, component: str = "serve") -> bool:
        """`component` labels the shed counter with the data plane that
        hit the gate (serve vs serve_grpc), mirroring the deadline
        counter's surface labels."""
        with self._lock:
            if self._inflight >= self.max_inflight:
                self._last_shed = time.monotonic()
                res_metrics.inc("tpk_shed_total", component=component)
                return False
            self._inflight += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1

    def note_shed(self, component: str = "serve") -> None:
        """Record an out-of-band shed — e.g. the generation engine
        refusing a request whose worst-case paged-KV footprint can never
        fit its pool — so the shed counter and the readiness-degradation
        window see it exactly like a queue-full rejection."""
        with self._lock:
            self._last_shed = time.monotonic()
        res_metrics.inc("tpk_shed_total", component=component)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def shedding(self) -> bool:
        """True while the replica is at capacity AND rejected a request
        within the last `retry_after_s` (readiness degrades): degradation
        tracks actual rejections, so a full-but-quiet replica stays in
        the endpoint set and recovers the moment load drains."""
        with self._lock:
            return (self._inflight >= self.max_inflight
                    and time.monotonic() - self._last_shed
                    <= self.retry_after_s)


class ModelRepository:
    """Name → Model with load/unload — the multi-model surface the reference
    exposes via its repository API + agent model puller."""

    #: Seconds a replaced model version stays loaded after a swap so
    #: in-flight requests against it finish (class attr: tests shrink it).
    UNLOAD_GRACE_S = 10.0

    def __init__(self):
        self._models: dict[str, Model] = {}
        self._batchers: dict[str, Batcher] = {}
        self._dirs: dict[str, str] = {}
        self._meshes: dict[str, dict] = {}
        self._load_errors: dict[str, str] = {}
        # Async-load intents: name -> wanted model_dir ("" = unload was
        # requested mid-load; the worker discards its result).
        self._want: dict[str, str] = {}
        self._inflight: set[str] = set()
        self._lock = threading.Lock()

    def register(self, model: Model, *, load: bool = True,
                 max_batch_size: int = 32, max_latency_ms: float = 5.0,
                 model_dir: str | None = None,
                 mesh: dict | None = None) -> Model:
        if load and not model.ready:
            model.load()
        with self._lock:
            old_model = self._models.get(model.name)
            self._models[model.name] = model
            if model_dir:
                self._dirs[model.name] = model_dir
            if mesh:
                # Remembered per name so every RELOAD path (load(),
                # load_async() on a model_dir update) re-applies the
                # tensor-parallel layout — a TP model silently reloaded
                # single-device would OOM on real hardware.
                self._meshes[model.name] = dict(mesh)
            else:
                # A meshless registration is an intent change (e.g. the
                # name now points at a single-device or non-generative
                # bundle): drop the remembered mesh or it would be
                # re-applied to a bundle it no longer fits.
                self._meshes.pop(model.name, None)
            old = self._batchers.pop(model.name, None)
            self._batchers[model.name] = Batcher(
                model.predict, max_batch_size=max_batch_size,
                max_latency_ms=max_latency_ms)
        if old:
            old.close()
        if old_model is not None and old_model is not model:
            # Drop the replaced version's device buffers/AOT executables —
            # without this, a TrainedModel version swap keeps BOTH
            # versions resident until GC, which can OOM HBM-constrained
            # serving. Deferred by a grace window so requests that
            # grabbed the old model just before the swap (e.g. oversized
            # calls that bypass the drained batcher) finish first; a
            # request still running after the grace sees the same cut a
            # rolling pod replacement would give it. The callback
            # re-checks the live registration: a rollback can re-register
            # the same object inside the grace window, and unloading it
            # then would kill the now-live model.
            def _deferred_unload(name=model.name, old=old_model):
                # Check-and-unload under the lock so it serializes with a
                # concurrent rollback's install; the post-install re-load
                # below covers the remaining interleaving.
                with self._lock:
                    if self._models.get(name) is old:
                        return  # rolled back — old is live again
                    old.unload()

            t = threading.Timer(self.UNLOAD_GRACE_S, _deferred_unload)
            t.daemon = True  # never delays interpreter exit
            t.start()
        if load and not model.ready:
            # A stale grace-window timer from an earlier swap can unload
            # this object between our readiness check above and the
            # install; now that we ARE the live registration any later
            # timer spares us, so one re-load makes this race-free.
            model.load()
        return model

    def get(self, name: str) -> Model:
        try:
            return self._models[name]
        except KeyError:
            raise tornado.web.HTTPError(
                404, reason=f"model {name!r} not found") from None

    def batcher(self, name: str) -> Batcher:
        self.get(name)
        return self._batchers[name]

    def names(self) -> list[str]:
        return sorted(self._models)

    def load(self, name: str) -> Model:
        """(Re)load by name — from its recorded model dir if registered that
        way, else by flipping the in-process model's lifecycle."""
        with self._lock:
            model_dir = self._dirs.get(name)
            mesh = self._meshes.get(name)
        if model_dir:
            from kubeflow_tpu.serve import runtimes
            model = runtimes.load_model(model_dir, name=name, mesh=mesh)
            return self.register(model, model_dir=model_dir, mesh=mesh)
        model = self.get(name)
        model.load()
        return model

    def load_async(self, name: str, model_dir: str) -> None:
        """Attach a new model from `model_dir` in a background thread (the
        TrainedModel path): AOT compiles take seconds, and the control
        plane's POST must return immediately — the controller polls
        /v2/models/{name}/ready until the load lands. Latest intent wins:
        a newer model_dir (or an unload) arriving mid-load supersedes the
        in-flight result instead of being dropped."""
        with self._lock:
            self._want[name] = model_dir
            self._load_errors.pop(name, None)
            if name in self._inflight:
                return  # the worker re-checks _want when it finishes
            self._inflight.add(name)

        def work():
            from kubeflow_tpu.serve import runtimes

            while True:
                with self._lock:
                    target = self._want.get(name, "")
                    if not target:  # unloaded / intent cleared mid-load
                        self._inflight.discard(name)
                        return
                with self._lock:
                    mesh = self._meshes.get(name)
                try:
                    model = runtimes.load_model(target, name=name,
                                                mesh=mesh)
                except Exception as e:
                    # Exit decisions happen under the SAME lock that
                    # releases _inflight — a concurrent load_async either
                    # sees us in flight (and we loop on the new intent)
                    # or sees us gone (and starts its own worker).
                    with self._lock:
                        if self._want.get(name, "") == target:
                            self._load_errors[name] = (
                                f"{type(e).__name__}: {e}")
                            self._inflight.discard(name)
                            return
                    continue  # intent changed while failing: retry
                with self._lock:
                    if self._want.get(name, "") != target:
                        continue  # newer dir (or unload) requested: redo
                self.register(model, model_dir=target, mesh=mesh)
                with self._lock:
                    want_now = self._want.get(name, "")
                    if want_now == target:
                        self._inflight.discard(name)
                        return
                if not want_now:  # unload arrived during register
                    self.get(name).unload()
                    with self._lock:
                        if not self._want.get(name, ""):
                            self._inflight.discard(name)
                            return
                # newer dir requested: loop to load it

        threading.Thread(target=work, daemon=True,
                         name=f"tpk-load-{name}").start()

    def loading_error(self, name: str) -> str | None:
        with self._lock:
            return self._load_errors.get(name)

    def model_dir(self, name: str) -> str | None:
        with self._lock:
            return self._dirs.get(name)

    def unload(self, name: str) -> None:
        with self._lock:
            in_flight = name in self._inflight
            self._want[name] = ""  # cancels an in-flight load
            self._load_errors.pop(name, None)
            known = name in self._models
        if not known:
            if in_flight:
                return  # cancelled before it ever registered
            raise tornado.web.HTTPError(
                404, reason=f"model {name!r} not found")
        self.get(name).unload()

    def close(self) -> None:
        for b in self._batchers.values():
            b.close()


# -- handlers ---------------------------------------------------------------


async def pump_stream(handler, it, render, render_error) -> None:
    """Drive a blocking generator into a chunked HTTP response: one
    executor hop per event, shared by the ndjson :generate stream and the
    OpenAI SSE surfaces. Pre-stream failures raise clean HTTP errors
    (ValueError/RuntimeError → 400 request faults; anything else → 500 so
    bugs hit server-side monitoring, matching the non-stream path);
    mid-stream failures become a terminal `render_error` frame (the
    status line is already on the wire). A client disconnect closes the
    generator (the engine still decodes the request to completion — no
    cancellation in v1). `render(ev, first) -> bool` writes frames and
    returns True to end the stream."""
    _END = object()

    def step():
        try:
            return ("ev", next(it, _END))
        except DeadlineExceeded as e:
            return ("expired", f"{type(e).__name__}: {e}")
        except KVCapacityExceeded as e:
            # Before ValueError/RuntimeError: pool exhaustion is an
            # overload shed, not a bad request — same 503 contract as
            # the non-stream paths.
            return ("shed", str(e))
        except (ValueError, RuntimeError) as e:
            return ("badreq", f"{type(e).__name__}: {e}")
        except Exception as e:
            return ("err", f"{type(e).__name__}: {e}")

    loop = asyncio.get_event_loop()
    kind, ev = await loop.run_in_executor(None, step)
    if kind == "expired":
        # Streams surface the expiry here (once per request — the inner
        # layers only free resources, they never count).
        res_metrics.inc("tpk_deadline_expired_total", component="serve")
        raise tornado.web.HTTPError(504, reason=ev)
    if kind == "shed":
        # Pre-stream shed (submit refused before any frame went out).
        handler.write_capacity_shed(ev)
        return
    if kind == "badreq":
        raise tornado.web.HTTPError(400, reason=ev)
    if kind == "err":
        raise tornado.web.HTTPError(500, reason=ev)
    first = True
    try:
        while ev is not _END:
            if kind != "ev":
                if kind == "expired":
                    # Mid-stream expiry: status line already went out, so
                    # the 504 becomes a terminal error frame — but it is
                    # still one expired request for the counter.
                    res_metrics.inc("tpk_deadline_expired_total",
                                    component="serve")
                handler.write(render_error(ev))
                await handler.flush()
                break
            done = render(ev, first)
            first = False
            await handler.flush()
            if done:
                break
            kind, ev = await loop.run_in_executor(None, step)
    except tornado.iostream.StreamClosedError:
        it.close()


class _Base(tornado.web.RequestHandler):
    def initialize(self, server: "ModelServer"):
        self.server = server
        self.repo = server.repo

    def prepare(self) -> None:
        # One trace id per request, caller-set or assigned, echoed back —
        # every span this request opens downstream carries it.
        self.trace_id = obs.sanitize_trace_id(
            self.request.headers.get(REQUEST_ID_HEADER))
        self.set_header(REQUEST_ID_HEADER, self.trace_id)

    def write_json(self, obj: Any, status: int = 200) -> None:
        self.set_status(status)
        self.set_header("Content-Type", "application/json")
        self.finish(json.dumps(obj))

    def body_json(self) -> dict:
        try:
            return json.loads(self.request.body or b"{}")
        except json.JSONDecodeError as e:
            raise tornado.web.HTTPError(400, reason=f"bad JSON: {e}") from None

    # -- resilience (deadline + admission) ----------------------------------

    def request_deadline(self) -> Deadline | None:
        """The request's end-to-end budget from DEADLINE_HEADER (None
        when the client set none)."""
        raw = self.request.headers.get(DEADLINE_HEADER)
        if raw is None:
            return None
        try:
            ms = float(raw)
            # NaN/inf would defeat every expiry comparison downstream.
            if not math.isfinite(ms) or ms <= 0:
                raise ValueError
        except ValueError:
            raise tornado.web.HTTPError(
                400, reason=f"{DEADLINE_HEADER} must be a positive "
                            f"number of milliseconds, got {raw!r}") \
                from None
        return Deadline(ms / 1e3)

    def admit(self) -> bool:
        """Admission-gate an inference request. False = the 503 +
        Retry-After shed response has been written; the caller must
        return without releasing. True = admitted; the caller owns one
        release()."""
        if self.server.draining:
            # Drain rejection, NOT an overload shed: marked with
            # DRAINING_HEADER so the front-door router retries the
            # request on a surviving replica instead of forwarding the
            # 503 as backpressure. In-flight requests (already past this
            # gate) keep running to completion.
            self.set_header("Retry-After", "1")
            self.set_header(DRAINING_HEADER, "1")
            self.write_json(self.capacity_body("replica draining"),
                            status=503)
            return False
        adm = self.server.admission
        with obs.span("serve.admit", trace_id=self.trace_id,
                      path=self.request.path) as sp:
            if adm is None or adm.try_acquire():
                sp.set(admitted=True)
                return True
            sp.set(admitted=False)
        self.set_header("Retry-After",
                        str(max(int(adm.retry_after_s), 1)))
        self.write_json(self.shed_body(), status=503)
        return False

    def shed_body(self) -> dict:
        """The 503 shed response body — facades with their own error
        envelope (OpenAI) override this so SDK clients can parse it."""
        return {"error": "server overloaded: admission queue full"}

    def capacity_body(self, msg: str) -> dict:
        """503 body for a paged-KV capacity shed (KVCapacityExceeded) —
        same override contract as shed_body."""
        return {"error": msg}

    def write_capacity_shed(self, msg: str) -> None:
        """THE shed path for paged-KV capacity refusals, shared by every
        HTTP surface (native :generate, streaming, OpenAI): count it
        like a queue-full rejection (tpk_shed_total + the readiness
        window), then write 503 + Retry-After with this surface's
        envelope. Written directly — send_error would clear the
        Retry-After header."""
        adm = self.server.admission
        if adm is not None:
            adm.note_shed("serve")
        else:
            res_metrics.inc("tpk_shed_total", component="serve")
        self.set_header("Retry-After", "1")
        self.write_json(self.capacity_body(msg), status=503)

    def _release(self) -> None:
        adm = self.server.admission
        if adm is None:
            return
        held_by = getattr(self, "_slot_rides_with", None)
        if held_by is not None:
            # The request 504'd but its blocking work may still be
            # running: the admission slot stays held until the work
            # really finishes (immediately, if it was cancelled in the
            # queue) — so max_inflight bounds CONCURRENT WORK, not just
            # concurrent waiting callers.
            held_by.add_done_callback(lambda _f: adm.release())
        else:
            adm.release()

    def submit_blocking(self, fn, *args) -> Future:
        """Run `fn(*args)` on the server's worker pool, returning the
        concurrent future. Gated handlers use this instead of
        run_in_executor so await_bounded can tie the admission slot to
        the work's true completion on expiry."""
        return self.server.executor.submit(fn, *args)

    async def await_bounded(self, fut, deadline: Deadline | None):
        """Await a (concurrent or asyncio) future under the request
        deadline; expiry — whether raised by the work itself (batcher
        queue pruning) or by the clock here — maps to 504. The work is
        not preempted mid-dispatch; instead an expired request's
        admission slot rides the concurrent future to completion, so the
        gate still bounds total concurrent work."""
        cfut = fut if isinstance(fut, Future) else None
        if cfut is not None:
            fut = asyncio.wrap_future(cfut)
        if deadline is None:
            # No budget: the work's own errors (including a model-raised
            # TimeoutError) must keep their 500 path, not map to 504.
            return await fut
        rem = deadline.remaining()
        if rem is None:  # Deadline.never(): unbounded, same as None
            return await fut
        try:
            return await asyncio.wait_for(fut, max(rem, 0.0))
        except (asyncio.TimeoutError, DeadlineExceeded) as e:
            if (not isinstance(e, DeadlineExceeded)
                    and not deadline.expired()):
                # On py3.11+ asyncio.TimeoutError IS builtin
                # TimeoutError, so a timeout raised by the work's own
                # internals lands here too — with budget left it is a
                # server fault (500 path), not an expired deadline.
                raise
            if cfut is not None:
                self._slot_rides_with = cfut
            # This surface raises at most once per request, and the
            # inner layers (batcher prune, engine sweep) never count —
            # so the counter is exactly one increment per expiry.
            res_metrics.inc("tpk_deadline_expired_total",
                            component="serve")
            raise tornado.web.HTTPError(
                504, reason=f"request deadline exceeded "
                            f"({type(e).__name__})") from e

    def write_error(self, status_code: int, **kwargs) -> None:
        reason = self._reason
        if "exc_info" in kwargs:
            exc = kwargs["exc_info"][1]
            if not isinstance(exc, tornado.web.HTTPError):
                reason = f"{type(exc).__name__}: {exc}"
        self.write_json({"error": reason}, status=status_code)

    def on_finish(self) -> None:
        # Inference traffic only — health/metadata probes and repository
        # control calls would pollute the data-plane log (the reference's
        # logger samples the data plane, not the control plane).
        rl = self.server.request_logger
        path = self.request.path
        if (rl is not None and self.request.method == "POST"
                and (path.endswith(":predict") or path.endswith(":generate")
                     or path.endswith(":prefill") or path.endswith(":decode")
                     or path.endswith("/infer")
                     or path.endswith("/generate"))):
            args = self.path_args or (None,)
            rl.log(self, args[0])


def admission_gated(method):
    """Wrap an async inference handler method behind the admission gate:
    shed (503 already written) or run with a guaranteed release. Every
    inference entry point uses this ONE wrapper, so a new handler can't
    silently become an unbounded side door around --max-inflight."""
    @functools.wraps(method)
    async def gated(self, *args, **kwargs):
        if not self.admit():
            return
        try:
            return await method(self, *args, **kwargs)
        finally:
            self._release()
    return gated


class V1ListHandler(_Base):
    def get(self):
        self.write_json({"models": self.repo.names()})


class V1ModelHandler(_Base):
    def get(self, name: str):
        model = self.repo.get(name)
        if not model.ready:
            raise tornado.web.HTTPError(
                503, reason=f"model {name!r} not ready")
        self.write_json({"name": name, "ready": model.ready})


class V1PredictHandler(_Base):
    @admission_gated
    async def post(self, name: str):
        model = self.repo.get(name)
        deadline = self.request_deadline()
        body = model.preprocess(self.body_json())
        instances = body.get("instances")
        if instances is None:
            raise tornado.web.HTTPError(
                400, reason='v1 request needs "instances"')
        t0 = time.monotonic()
        if getattr(model, "wants_raw_payload", False):
            # InferenceGraphs take the whole JSON body (routing fields
            # included) and bypass the batcher — per-request routing can't
            # survive cross-request coalescing.
            out = await self.await_bounded(
                self.submit_blocking(model.predict, body), deadline)
            preds = out.get("instances") if isinstance(out, dict) else out
            self.server.observe(name, len(instances),
                                time.monotonic() - t0)
            self.write_json({"predictions": np.asarray(preds).tolist()})
            return
        # v1 protocol is single-tensor: "instances" stack along batch dim 0.
        spec = getattr(model, "input_spec", None)
        inputs = [np.asarray(instances, dtype=spec[0][1] if spec else None)]
        fut = self.repo.batcher(name).submit(inputs, deadline=deadline,
                                             trace_id=self.trace_id)
        outs = await self.await_bounded(fut, deadline)
        outs = model.postprocess(outs)
        self.server.observe(name, len(instances), time.monotonic() - t0)
        preds = outs[0] if isinstance(outs, (list, tuple)) else outs
        self.write_json({"predictions": np.asarray(preds).tolist()})


class V1ExplainHandler(_Base):
    """POST /v1/models/{name}:explain — the reference's v1 explain verb
    (explainer component), served by the model's attached native explainer
    (serve/explain.py). 501 when the model has none configured."""

    @admission_gated
    async def post(self, name: str):
        model = self.repo.get(name)
        deadline = self.request_deadline()
        # Same preprocess as :predict — explanations must be computed on
        # the input the model actually serves.
        body = model.preprocess(self.body_json())
        instances = body.get("instances")
        if instances is None:
            raise tornado.web.HTTPError(
                400, reason='v1 request needs "instances"')
        spec = getattr(model, "input_spec", None)
        t0 = time.monotonic()
        try:
            arr = np.asarray(instances, dtype=spec[0][1] if spec else None)
            out = await self.await_bounded(
                self.submit_blocking(model.explain, arr), deadline)
        except NotImplementedError as e:
            raise tornado.web.HTTPError(501, reason=str(e))
        except (ValueError, TypeError) as e:
            # TypeError is the AOT executable refusing a wrong-shaped
            # instance (per-example shape is static) — a client error.
            raise tornado.web.HTTPError(400, reason=str(e))
        self.server.observe(name, len(out), time.monotonic() - t0)
        self.write_json({"explanations": out})


class GenerateHandler(_Base):
    """POST /v1/models/{name}:generate and /v2/models/{name}/generate —
    the generative data plane (KServe huggingfaceserver's generate surface).
    Body: {"input_ids": [...] | "text": "...", "max_tokens", "temperature",
    "eos_id"}. Bypasses the coalescing batcher: the generation engine does
    its own continuous batching across concurrent requests."""

    @admission_gated
    async def post(self, name: str):
        model = self.repo.get(name)
        gen = getattr(model, "generate", None)
        if gen is None:
            raise tornado.web.HTTPError(
                400, reason=f"model {name!r} is not generative")
        body = self.body_json()
        # "_deadline"/"_trace" are in-process fields only: a wire-supplied
        # value would reach the engine as a non-Deadline / spoofed trace.
        body.pop("_deadline", None)
        body.pop("_trace", None)
        body["_trace"] = self.trace_id
        deadline = self.request_deadline()
        if deadline is not None:
            # In-process deadline propagation: the engine checks the SAME
            # object at admission and every chunk boundary, so an expired
            # request frees its decode slot instead of burning the batch.
            body["_deadline"] = deadline
        t0 = time.monotonic()
        if body.get("stream"):
            await self._stream(name, model, body, t0)
            return
        try:
            out = await self.await_bounded(
                self.submit_blocking(gen, body), deadline)
        except KVCapacityExceeded as e:
            # Paged-KV exhaustion is an overload shed, not a bad request
            # (the spec is valid; THIS replica's pool is too small).
            self.write_capacity_shed(str(e))
            return
        except (ValueError, RuntimeError) as e:
            raise tornado.web.HTTPError(400, reason=str(e)) from None
        self.server.observe(name, out.get("num_output_tokens", 0),
                            time.monotonic() - t0)
        self.write_json({"model_name": name, **out})

    async def _stream(self, name: str, model, body: dict, t0: float):
        """"stream": true → newline-delimited JSON events flushed as the
        engine emits chunks (tornado chunked transfer; the KServe/vLLM
        streaming generate surface), via the shared pump_stream helper."""
        stream_fn = getattr(model, "generate_stream", None)
        if stream_fn is None:
            raise tornado.web.HTTPError(
                400, reason=f"model {name!r} does not stream")
        it = stream_fn(body)
        tokens_out = 0

        def render(ev, first):
            nonlocal tokens_out
            if first:
                self.set_header("Content-Type", "application/x-ndjson")
            tokens_out += len(ev.get("tokens", ()))
            self.write(json.dumps({"model_name": name, **ev}) + "\n")
            return bool(ev.get("done"))

        def render_error(msg):
            return json.dumps({"model_name": name, "error": msg}) + "\n"

        await pump_stream(self, it, render, render_error)
        self.server.observe(name, tokens_out, time.monotonic() - t0)


#: Content type of a KV shipment (serve/kv_transfer.py wire format) —
#: the router relays these bytes opaquely between prefill and decode
#: replicas.
KV_SHIPMENT_CONTENT_TYPE = "application/x-tpk-kv"


class PrefillHandler(_Base):
    """POST /v1/models/{name}:prefill — disaggregation phase 1 (ISSUE
    13): the :generate request body in, a binary KV shipment out. The
    router (or any caller) forwards those bytes to a decode replica's
    :decode; the prefill replica's pool holds nothing for this request
    once the response is on the wire."""

    @admission_gated
    async def post(self, name: str):
        model = self.repo.get(name)
        ship = getattr(model, "prefill_ship", None)
        if ship is None:
            raise tornado.web.HTTPError(
                400, reason=f"model {name!r} cannot prefill-ship "
                            "(not generative, or no paged KV pool)")
        body = self.body_json()
        body.pop("_deadline", None)
        body.pop("_trace", None)
        body["_trace"] = self.trace_id
        deadline = self.request_deadline()
        if deadline is not None:
            body["_deadline"] = deadline
        t0 = time.monotonic()
        try:
            out = await self.await_bounded(
                self.submit_blocking(ship, body), deadline)
        except KVCapacityExceeded as e:
            self.write_capacity_shed(str(e))
            return
        except (ValueError, RuntimeError) as e:
            raise tornado.web.HTTPError(400, reason=str(e)) from None
        self.server.observe(name, out.get("num_input_tokens", 0),
                            time.monotonic() - t0)
        self.set_header("Content-Type", KV_SHIPMENT_CONTENT_TYPE)
        self.finish(out["shipment"])


class DecodeHandler(_Base):
    """POST /v1/models/{name}:decode — disaggregation phase 2: a KV
    shipment in, the :generate response shape out (streaming when the
    original caller asked to stream — the flag rides the shipment
    metadata). The engine admits the shipped blocks straight into
    decode; this replica never runs a prefill chunk."""

    @admission_gated
    async def post(self, name: str):
        from kubeflow_tpu.serve.kv_transfer import (ShipmentError,
                                                    peek_meta)

        model = self.repo.get(name)
        dec = getattr(model, "decode_remote", None)
        if dec is None:
            raise tornado.web.HTTPError(
                400, reason=f"model {name!r} cannot decode a shipment "
                            "(not generative, or no paged KV pool)")
        shipment = self.request.body or b""
        try:
            meta = peek_meta(shipment)
        except ShipmentError as e:
            raise tornado.web.HTTPError(
                400, reason=f"bad KV shipment: {e}") from None
        if meta.get("trace") and \
                REQUEST_ID_HEADER not in self.request.headers:
            # The router stamps the caller's trace id into the shipment
            # meta: a :decode POST without an explicit X-Request-Id
            # (direct tooling, older routers' resumes) still joins the
            # caller's distributed trace. A forwarded header wins — the
            # router already threads the id on its own requests.
            self.trace_id = obs.sanitize_trace_id(str(meta["trace"]))
            self.set_header(REQUEST_ID_HEADER, self.trace_id)
        deadline = self.request_deadline()
        t0 = time.monotonic()
        if (meta.get("extra") or {}).get("stream"):
            it = model.decode_remote_stream(shipment, deadline=deadline,
                                            trace_id=self.trace_id)
            tokens_out = 0

            def render(ev, first):
                nonlocal tokens_out
                if first:
                    self.set_header("Content-Type",
                                    "application/x-ndjson")
                tokens_out += len(ev.get("tokens", ()))
                self.write(json.dumps({"model_name": name, **ev}) + "\n")
                return bool(ev.get("done"))

            def render_error(msg):
                return json.dumps({"model_name": name,
                                   "error": msg}) + "\n"

            await pump_stream(self, it, render, render_error)
            self.server.observe(name, tokens_out, time.monotonic() - t0)
            return
        try:
            out = await self.await_bounded(
                self.submit_blocking(
                    functools.partial(dec, shipment, deadline=deadline,
                                      trace_id=self.trace_id)),
                deadline)
        except KVCapacityExceeded as e:
            self.write_capacity_shed(str(e))
            return
        except (ValueError, RuntimeError) as e:
            raise tornado.web.HTTPError(400, reason=str(e)) from None
        self.server.observe(name, out.get("num_output_tokens", 0),
                            time.monotonic() - t0)
        self.write_json({"model_name": name, **out})


class V2HealthHandler(_Base):
    def get(self, kind: str):
        if kind == "ready":
            ready, why = self.server.readiness()
            if not ready:
                raise tornado.web.HTTPError(503, reason=why)
        self.write_json({"live" if kind == "live" else "ready": True})


class V2ModelHandler(_Base):
    def get(self, name: str, sub: str = ""):
        # A failed background load (load_async) answers here so the
        # controller polling readiness sees the error, not a bare 404 —
        # but never at the expense of a live model: if a previous version
        # is still registered and serving, report ITS state (the failed
        # re-load surfaces via the controller's repost cycle instead).
        if name not in self.repo.names():
            err = self.repo.loading_error(name)
            if err:
                raise tornado.web.HTTPError(
                    503, reason=f"model {name!r} failed to load: {err}")
        model = self.repo.get(name)
        if sub == "/ready":
            if not model.ready:
                raise tornado.web.HTTPError(
                    503, reason=f"model {name!r} not ready")
            # model_dir lets version-aware clients (the TrainedModel
            # controller) distinguish "old version still serving" from
            # "my re-load landed".
            self.write_json({"name": name, "ready": True,
                             "model_dir": self.repo.model_dir(name)})
        else:
            self.write_json(model.metadata())


class V2InferHandler(_Base):
    @admission_gated
    async def post(self, name: str):
        model = self.repo.get(name)
        deadline = self.request_deadline()
        body = model.preprocess(self.body_json())
        tensors = body.get("inputs")
        if not tensors:
            raise tornado.web.HTTPError(400, reason='v2 request needs "inputs"')
        inputs = []
        for t in tensors:
            dtype = v2_to_numpy_dtype(t.get("datatype", "FP32"))
            arr = np.asarray(t["data"], dtype=dtype).reshape(t["shape"])
            inputs.append(arr)
        t0 = time.monotonic()
        if getattr(model, "wants_raw_payload", False):
            # Graph path: first tensor becomes "instances"; v2 request
            # parameters ride along as routing fields.
            payload = dict(body.get("parameters") or {})
            payload["instances"] = inputs[0]
            out = await self.await_bounded(
                self.submit_blocking(model.predict, payload), deadline)
            outs = [out.get("instances") if isinstance(out, dict) else out]
        else:
            fut = self.repo.batcher(name).submit(inputs, deadline=deadline,
                                                 trace_id=self.trace_id)
            outs = await self.await_bounded(fut, deadline)
        outs = model.postprocess(outs)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        self.server.observe(name, int(inputs[0].shape[0]),
                            time.monotonic() - t0)
        self.write_json({
            "model_name": name, "id": body.get("id", ""),
            "outputs": [{
                "name": f"output_{i}", "shape": list(np.shape(o)),
                "datatype": _v2_dtype(str(np.asarray(o).dtype)),
                "data": np.asarray(o).ravel().tolist(),
            } for i, o in enumerate(outs)]})


class RepositoryHandler(_Base):
    def post(self, name: str, verb: str):
        if verb == "load":
            # A body {"model_dir": ...} attaches a NEW model to this
            # running server (the TrainedModel / agent model-puller path:
            # ⟨kserve: pkg/apis/serving/v1alpha1 — TrainedModel⟩). The
            # load runs in the background (AOT compiles take seconds) —
            # 202 now, poll /v2/models/{name}/ready. Bodyless load
            # re-loads a known model synchronously.
            model_dir = self.body_json().get("model_dir")
            if model_dir:
                self.repo.load_async(name, model_dir)
                self.write_json({"name": name, "state": "LOADING"},
                                status=202)
                return
            self.repo.load(name)
        else:
            self.repo.unload(name)
        self.write_json({"name": name, "state":
                         "READY" if verb == "load" else "UNAVAILABLE"})


class RepositoryIndexHandler(_Base):
    def post(self):
        out = []
        for name in self.repo.names():
            m = self.repo.get(name)
            out.append({"name": name,
                        "state": "READY" if m.ready else "UNAVAILABLE"})
        self.write_json(out)


class MetricsHandler(_Base):
    def get(self):
        self.set_header("Content-Type", "text/plain; version=0.0.4")
        self.finish(self.server.prometheus_text())


class DebugTraceHandler(_Base):
    """GET /debug/trace[?trace_id=...] — the process's span ring as
    Chrome trace-event JSON (load in chrome://tracing / Perfetto). One
    slow request is diagnosable by filtering its X-Request-Id: admit →
    batch-gather → prefill → per-chunk decode → fetch spans all carry
    it. Bounded ring, so this is always a small read."""

    def get(self):
        tid = self.get_query_argument("trace_id", default=None)
        self.write_json(obs.get_tracer().chrome_trace(tid))


class RequestLogger:
    """Inference request log — the KServe agent logger equivalent (⟨kserve:
    pkg/agent — request logger⟩, SURVEY.md §2.2). The reference emits
    CloudEvents to a sink URL; here each request appends one JSONL record
    to a local file (ts, path, model, status, latency, sizes; payloads too
    in mode="all"), which the platform's log plumbing ships like any other
    worker log."""

    def __init__(self, path: str, mode: str = "metadata"):
        if mode not in ("metadata", "all"):
            raise ValueError(f"request log mode {mode!r}: metadata | all")
        self.mode = mode
        self._fh = open(path, "a", buffering=1)
        self._lock = threading.Lock()

    def log(self, handler: tornado.web.RequestHandler,
            model: str | None) -> None:
        req = handler.request
        rec = {
            "ts": time.time(),
            "method": req.method,
            "path": req.path,
            "model": model,
            "status": handler.get_status(),
            "latency_ms": round(req.request_time() * 1e3, 3),
            "request_bytes": len(req.body or b""),
        }
        if self.mode == "all":
            try:
                rec["request"] = json.loads(req.body or b"{}")
            except json.JSONDecodeError:
                rec["request"] = None
        with self._lock:
            self._fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._fh.close()


class ModelServer:
    """Hosts a ModelRepository over HTTP; runs inline or on a daemon thread."""

    def __init__(self, repo: ModelRepository | None = None,
                 request_logger: RequestLogger | None = None,
                 admission: AdmissionController | None = None,
                 max_inflight: int = 256,
                 executor_workers: int | None = None):
        self.repo = repo or ModelRepository()
        self.request_logger = request_logger
        # max_inflight=0 disables admission control entirely (None);
        # an explicit controller wins over the convenience knob.
        if max_inflight < 0:
            raise ValueError(f"max_inflight must be >= 0, got "
                             f"{max_inflight}")
        self.admission = admission
        if admission is None and max_inflight > 0:
            self.admission = AdmissionController(max_inflight)
        # Handler-submitted blocking work runs here (not the asyncio
        # default executor) so expired requests hand back a CONCURRENT
        # future: the admission slot can ride it to true completion
        # instead of freeing while the abandoned call still runs.
        # `executor_workers` overrides the CPU-derived default: a
        # worker is held for each admitted blocking call's full
        # duration (mostly device/engine waits, not CPU), so small-CPU
        # hosts serving concurrency-heavy traffic size it by admission
        # depth instead.
        self.executor = ThreadPoolExecutor(
            max_workers=(int(executor_workers) if executor_workers
                         else min(32, (os.cpu_count() or 1) + 4)),
            thread_name_prefix="tpk-serve-work")
        self._counters: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._loop: tornado.ioloop.IOLoop | None = None
        self._thread: threading.Thread | None = None
        self.port: int | None = None
        self._grpc = None
        self.grpc_port: int | None = None
        # Connection-draining state (scale-in, ISSUE 9): a plain bool —
        # single writer (the drain trigger), GIL-atomic reads from
        # request threads and probes.
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Enter draining: BOTH readiness surfaces (HTTP /v2/health/ready
        and gRPC ServerReady share readiness()) go not-ready, new
        inference requests are rejected 503 + DRAINING_HEADER (HTTP) /
        UNAVAILABLE "replica draining" (gRPC), and in-flight requests
        run to completion — the router/controller retires the process
        once the in-flight gauges reach zero."""
        self._draining = True

    def end_drain(self) -> None:
        """Abort a drain (scale-in cancelled): the replica resumes
        admitting and both readiness surfaces recover together."""
        self._draining = False

    def start_grpc(self, port: int = 0) -> int:
        """Open Inference Protocol v2 over gRPC (grpc_server.py), sharing
        this server's repository/batchers. Returns the bound port."""
        from kubeflow_tpu.serve.grpc_server import build_grpc_server

        self._grpc, self.grpc_port = build_grpc_server(self, port)
        self._grpc.start()
        return self.grpc_port

    def readiness(self) -> tuple[bool, str]:
        """THE readiness rule, shared by the HTTP probe and gRPC
        ServerReady so the two surfaces cannot drift: not ready while
        draining (scale-in in progress — pollers on EITHER surface must
        see the same degradation, or a gRPC-only client keeps sending
        to a replica the HTTP plane already retired), while any model is
        still loading, or while the replica is actively shedding
        (admission rejections within the last retry_after_s — KServe
        probe semantics: route around a saturated replica instead of
        feeding more traffic into 503s; a full-but-quiet replica stays
        ready)."""
        if self._draining:
            return False, "draining"
        for name in self.repo.names():
            try:
                model = self.repo.get(name)
            except Exception:
                continue  # unloaded between names() and get(): not loading
            if not model.ready:
                return False, "models loading"
        if self.admission is not None and self.admission.shedding:
            return False, "shedding: admission queue full"
        return True, ""

    def observe(self, model: str, examples: int, seconds: float) -> None:
        with self._lock:
            c = self._counters.setdefault(
                model, {"requests": 0, "examples": 0, "seconds": 0.0})
            c["requests"] += 1
            c["examples"] += examples
            c["seconds"] += seconds
        # Latency distribution, not just the running sum: the counter
        # pair gives average latency only — p50/p99 need buckets
        # (SURVEY.md §5.1 rebuild item).
        res_metrics.observe("tpk_serve_request_latency_seconds", seconds,
                            model=model)

    def prometheus_text(self) -> str:
        lines = [
            "# TYPE tpk_serve_requests_total counter",
            "# TYPE tpk_serve_examples_total counter",
            "# TYPE tpk_serve_request_seconds_total counter",
        ]
        with self._lock:
            for model, c in sorted(self._counters.items()):
                tag = f'{{model="{model}"}}'
                lines += [
                    f"tpk_serve_requests_total{tag} {c['requests']}",
                    f"tpk_serve_examples_total{tag} {c['examples']}",
                    f"tpk_serve_request_seconds_total{tag} {c['seconds']:.6f}",
                ]
        if self.admission is not None:
            lines += [
                "# TYPE tpk_serve_inflight gauge",
                f"tpk_serve_inflight {self.admission.inflight}",
            ]
        lines += self._engine_metric_lines()
        out = "\n".join(lines) + "\n"
        # The shared resilience counters (retries, deadline expiries,
        # sheds) render on the same scrape — one metrics surface for the
        # whole failure story.
        return out + res_metrics.prometheus_text()

    def _engine_metric_lines(self) -> list[str]:
        """Per-model generation-engine counters (see _ENGINE_METRICS)."""
        rows = []
        for name in self.repo.names():
            try:
                model = self.repo.get(name)
            except Exception:
                continue  # unloaded between names() and get()
            engine = getattr(model, "engine", None)
            snap = getattr(engine, "stats_snapshot", None)
            # Locked shallow snapshot (the engine worker mutates its
            # dict); plain-dict fallback for engines without the lock
            # (text2text's single-threaded stats).
            stats = snap() if callable(snap) else getattr(engine, "stats",
                                                          None)
            if not stats:
                continue
            rows.append((name, engine, dict(stats)))
        lines: list[str] = []
        for stat_key, metric, kind in _ENGINE_METRICS:
            typed = False
            for name, engine, stats in rows:
                if stat_key == "__depth__":
                    val = getattr(engine, "pipeline_depth", 1)
                elif stat_key == "__inflight__":
                    val = getattr(engine, "inflight_depth", 0)
                elif stat_key == "__spec_accept_rate__":
                    proposed = stats.get("spec_proposed") or 0
                    if not proposed:
                        continue
                    val = stats.get("spec_accepted", 0) / proposed
                elif stat_key in ("__kv_free__", "__kv_used__",
                                  "__kv_spill__"):
                    # None on flat engines — the pool gauges only exist
                    # where a pool does (and the spill gauge only where
                    # a host tier does).
                    attr = {"__kv_free__": "kv_blocks_free",
                            "__kv_used__": "kv_blocks_used",
                            "__kv_spill__": "kv_spill_blocks"}[stat_key]
                    val = getattr(engine, attr, None)
                    if val is None:
                        continue
                else:
                    val = stats.get(stat_key)
                    if val is None:
                        continue
                if not typed:
                    lines.append(f"# TYPE {metric} {kind}")
                    typed = True
                v = (int(val) if float(val).is_integer()
                     else round(float(val), 6))
                lines.append(f'{metric}{{model="{name}"}} {v}')
        # Engine role as a labeled presence gauge (the fleet poller and
        # operators read which phase of disaggregated serving a replica
        # runs): one series per model, value always 1.
        typed = False
        for name, engine, _stats in rows:
            role = getattr(engine, "role", None)
            if not role:
                continue
            if not typed:
                lines.append("# TYPE tpk_engine_role gauge")
                typed = True
            lines.append(
                f'tpk_engine_role{{model="{name}",role="{role}"}} 1')
        # KV quantization mode as a labeled info gauge (ISSUE 19):
        # which encode a replica's pool blocks use — operators pair
        # disagg fleets by this series (mismatched modes refuse at
        # submit_remote), and "none" is rendered too so the escape
        # hatch is as observable as the quantized modes.
        typed = False
        for name, engine, _stats in rows:
            mode = getattr(engine, "kv_quant", None)
            if not mode:
                continue
            if not typed:
                lines.append("# TYPE tpk_kv_quant_mode gauge")
                typed = True
            lines.append(
                f'tpk_kv_quant_mode{{model="{name}",mode="{mode}"}} 1')
        return lines

    def app(self) -> tornado.web.Application:
        from kubeflow_tpu.serve import openai_api

        kw = {"server": self}
        return tornado.web.Application(openai_api.routes(self) + [
            (r"/v1/models", V1ListHandler, kw),
            (r"/v1/models/([^/:]+)", V1ModelHandler, kw),
            (r"/v1/models/([^/:]+):predict", V1PredictHandler, kw),
            (r"/v1/models/([^/:]+):explain", V1ExplainHandler, kw),
            (r"/v1/models/([^/:]+):generate", GenerateHandler, kw),
            (r"/v1/models/([^/:]+):prefill", PrefillHandler, kw),
            (r"/v1/models/([^/:]+):decode", DecodeHandler, kw),
            (r"/v2/models/([^/]+)/generate", GenerateHandler, kw),
            (r"/v2/health/(live|ready)", V2HealthHandler, kw),
            (r"/v2/models/([^/]+)/infer", V2InferHandler, kw),
            (r"/v2/repository/models/([^/]+)/(load|unload)",
             RepositoryHandler, kw),
            (r"/v2/repository/index", RepositoryIndexHandler, kw),
            (r"/v2/models/([^/]+)(/ready)?", V2ModelHandler, kw),
            (r"/metrics", MetricsHandler, kw),
            (r"/debug/trace", DebugTraceHandler, kw),
        ])

    def _serve(self, port: int, ready: threading.Event) -> None:
        asyncio.set_event_loop(asyncio.new_event_loop())
        self._loop = tornado.ioloop.IOLoop.current()
        sockets = tornado.netutil.bind_sockets(port, address="127.0.0.1")
        server = tornado.httpserver.HTTPServer(self.app())
        server.add_sockets(sockets)
        self.port = sockets[0].getsockname()[1]
        ready.set()
        self._loop.start()

    def start_background(self, port: int = 0) -> int:
        """Starts on a daemon thread; returns the bound port (tests, local)."""
        ready = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, args=(port, ready), daemon=True,
            name="tpk-model-server")
        self._thread.start()
        if not ready.wait(10.0):
            raise TimeoutError("model server failed to bind")
        assert self.port is not None
        return self.port

    def stop(self) -> None:
        grpc_drained = None
        if self._grpc is not None:
            grpc_drained = self._grpc.stop(grace=1.0)
        if self._loop is not None:
            self._loop.add_callback(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # Executor last, after the gRPC grace window actually drains:
        # in-flight handlers may still submit_blocking(), and shutting
        # down first would 500 them with 'cannot schedule new futures'.
        if grpc_drained is not None:
            grpc_drained.wait(1.5)
        self.executor.shutdown(wait=False)
        self.repo.close()

    def run(self, port: int) -> None:
        """Blocking serve — the in-pod entrypoint."""
        self._serve(port, threading.Event())


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpk-model-server")
    p.add_argument("--model-dir", action="append", default=[],
                   help="model bundle dir (repeatable; see runtimes.py)")
    p.add_argument("--storage-uri", action="append", default=[],
                   help="uri to materialize then serve (file://, pvc://)")
    p.add_argument("--name", action="append", default=[],
                   help="override name for the i-th model")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--max-batch-size", type=int, default=32)
    p.add_argument("--max-latency-ms", type=float, default=5.0)
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="force N virtual CPU devices (test mode)")
    p.add_argument("--request-log", default=None,
                   help="JSONL inference request log path (agent logger)")
    p.add_argument("--request-log-mode", default="metadata",
                   choices=["metadata", "all"])
    p.add_argument("--grpc-port", type=int, default=None,
                   help="also serve the v2 open-inference gRPC protocol")
    p.add_argument("--max-inflight", type=int, default=256,
                   help="admitted-request cap before 503 shedding "
                        "(0 disables admission control)")
    p.add_argument("--mesh", default=None,
                   help="device mesh for tensor-parallel generative "
                        "serving, e.g. 'tensor=8' or 'tensor=4,data=2' "
                        "(the ISVC model.mesh field)")
    args = p.parse_args(argv)

    mesh_spec = None
    if args.mesh:
        mesh_spec = {}
        for part in args.mesh.split(","):
            axis, _, n = part.partition("=")
            try:
                mesh_spec[axis.strip()] = int(n)
            except ValueError:
                p.error(f"--mesh parts must be axis=N, got {part!r}")

    if args.cpu_devices:
        # Shared helper: covers jax >= 0.5 (jax_num_cpu_devices) AND
        # older jax (XLA_FLAGS) — a raw config update crash-loops every
        # controller-launched replica on old-jax environments.
        from kubeflow_tpu.utils.devices import force_cpu_device_count

        force_cpu_device_count(args.cpu_devices)

    from kubeflow_tpu.serve import runtimes, storage

    dirs = list(args.model_dir)
    for i, uri in enumerate(args.storage_uri):
        dirs.append(storage.download(uri, f"/tmp/tpk-models/{i}"))

    logger = (RequestLogger(args.request_log, args.request_log_mode)
              if args.request_log else None)
    server = ModelServer(request_logger=logger,
                         max_inflight=args.max_inflight)
    for i, d in enumerate(dirs):
        name = args.name[i] if i < len(args.name) else None
        model = runtimes.load_model(d, name=name, mesh=mesh_spec)
        server.repo.register(model, model_dir=d, mesh=mesh_spec,
                             max_batch_size=args.max_batch_size,
                             max_latency_ms=args.max_latency_ms)
        print(json.dumps({"event": "model_loaded", "name": model.name,
                          "load_time_s": model.load_time_s}), flush=True)
    if args.grpc_port is not None:
        bound = server.start_grpc(args.grpc_port)
        print(json.dumps({"event": "grpc_serving", "port": bound}),
              flush=True)
    print(json.dumps({"event": "serving", "port": args.port}), flush=True)
    server.run(args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
