"""Open-loop load harness + router benchmark (ISSUE 9).

The r4 batcher-tail episode (PROFILE.md §5) is the standing lesson: a
harness bug can fabricate a 13x tail. This harness is therefore built
around the two disciplines that episode taught:

  * **Open loop.** Arrival times are drawn from a seeded Poisson process
    and requests FIRE AT THEIR SCHEDULED TIME regardless of completions
    — a closed loop (next request after the last reply) hides queueing
    collapse, because a saturated server slows the offered load down to
    exactly what it can serve.
  * **Mechanism arms, honest labels.** The replicas are FAKE engines
    (slot-limited timed service, a real prefix-seen cache) behind REAL
    ModelServers: the numbers measure the ROUTER — placement, proxy
    overhead, horizontal scaling, affinity — not model decode. The
    artifact says so.

`run_routerbench()` (→ ROUTERBENCH.json via `python bench.py
--routerbench`) records:

  * routed-1-replica vs direct-1-replica: the router's p50 overhead
    bound (acceptance: <= 10%);
  * routed-4 vs routed-1 at the SAME per-replica offered load: the
    horizontal-scaling claim (acceptance: >= 3x goodput at equal p99
    deadline-miss rate);
  * affinity on vs hash-off control at identical traffic: the
    prefix-cache hit-rate delta (acceptance: strictly above).

Latency percentiles are reported both from the per-request records and
from the replicas' EXISTING `tpk_serve_request_latency_seconds`
histograms (scraped and merged), so the two views cross-check each
other.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from kubeflow_tpu.serve.model import Model
from kubeflow_tpu.serve.server import (DEADLINE_HEADER, ModelServer)

#: Tokens of prompt prefix the fake prefix cache keys on — matches the
#: router's affinity_key prefix window so an affinity hit IS a cache
#: hit after first touch.
PREFIX_TOKENS = 32


class FakeEngine:
    """The engine-shaped stats surface a fake replica exports, so the
    REAL /metrics rendering (ModelServer._engine_metric_lines) and the
    fleet scrape see live gauges: tpk_decode_inflight_depth, request
    and prefix-cache counters."""

    pipeline_depth = 1

    def __init__(self):
        self.stats = {  # guarded-by: _lock
            "requests": 0, "decode_tokens": 0,
            "prefix_hits": 0, "prefix_misses": 0,
            "prefill_chunks": 0, "remote_admits": 0,
            "kv_blocks_shipped": 0, "kv_blocks_received": 0,
        }
        self.inflight_depth = 0  # single int store, GIL-atomic reads
        self._lock = threading.Lock()

    def bump(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                self.stats[k] = self.stats.get(k, 0) + v

    def enter(self) -> None:
        with self._lock:
            self.inflight_depth += 1

    def exit(self) -> None:
        with self._lock:
            self.inflight_depth -= 1

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


class FakeGenerativeModel(Model):
    """A timed stand-in for the generation engine: `slots` concurrent
    decodes, service time = prefill + max_tokens x per-token, with a
    prefix-seen cache (keyed like the engine's (adapter, prefix) family)
    that discounts the prefill on a hit. Deterministic, CPU-only, and
    honest about concurrency — queueing happens in a real semaphore, so
    open-loop overload produces real latency growth."""

    def __init__(self, name: str, *, slots: int = 4,
                 per_token_s: float = 0.0012, prefill_s: float = 0.012,
                 hit_prefill_s: float = 0.002):
        super().__init__(name)
        self.ready = True
        self.engine = FakeEngine()
        self.slots = int(slots)
        self.per_token_s = float(per_token_s)
        self.prefill_s = float(prefill_s)
        self.hit_prefill_s = float(hit_prefill_s)
        self._slots_sem = threading.Semaphore(self.slots)
        self._seen: set = set()  # guarded-by: _seen_lock
        self._seen_lock = threading.Lock()

    def _prefix_probe(self, payload: dict) -> bool:
        ids = payload.get("input_ids") or []
        key = (payload.get("adapter") or "",
               tuple(int(t) for t in ids[:PREFIX_TOKENS]))
        with self._seen_lock:
            hit = key in self._seen
            self._seen.add(key)
        self.engine.bump(prefix_hits=int(hit), prefix_misses=int(not hit))
        return hit

    def generate_stream(self, payload: dict):
        """Genuinely incremental: each chunk event yields AFTER its
        share of the timed service, while the slot is held — so a drain
        that begins mid-stream really does race an open stream, and the
        zero-mid-stream-errors pin means something."""
        hit = self._prefix_probe(payload)
        max_tokens = int(payload.get("max_tokens", 16))
        with self._slots_sem:
            self.engine.enter()
            try:
                time.sleep(self.hit_prefill_s if hit else self.prefill_s)
                emitted = 0
                while emitted < max_tokens:
                    n = min(8, max_tokens - emitted)
                    time.sleep(n * self.per_token_s)
                    toks = list(range(emitted, emitted + n))
                    emitted += n
                    yield {"tokens": toks}
            finally:
                self.engine.exit()
        self.engine.bump(requests=1, decode_tokens=max_tokens)
        yield {"done": True, "output_ids": list(range(max_tokens)),
               "num_output_tokens": max_tokens, "prefix_hit": hit}

    def generate(self, payload: dict) -> dict:
        out: dict = {}
        for ev in self.generate_stream(payload):
            if ev.get("done"):
                out = {k: v for k, v in ev.items() if k != "done"}
        return out

    # -- disaggregation fakes (ISSUE 13): timed stand-ins for the real
    # prefill_ship / decode_remote engine surface, through the REAL wire
    # format, so router handoff tests measure the router. ---------------

    def prefill_ship(self, payload: dict) -> dict:
        from kubeflow_tpu.serve.kv_transfer import pack_shipment

        hit = self._prefix_probe(payload)
        ids = [int(t) for t in (payload.get("input_ids") or [0])]
        with self._slots_sem:
            self.engine.enter()
            try:
                time.sleep(self.hit_prefill_s if hit else self.prefill_s)
            finally:
                self.engine.exit()
        nb = max(1, -(-len(ids) // 8))
        self.engine.bump(requests=1, prefill_chunks=1,
                         kv_blocks_shipped=nb)
        meta = {"fmt": 1, "block_size": 8, "tokens": ids,
                "first_token": 0, "first_logprob": 0.0,
                "max_tokens": int(payload.get("max_tokens", 16)),
                "prefix_hit": hit,
                "extra": {"stream": bool(payload.get("stream"))}}
        shipment = pack_shipment(
            meta, {"k": np.zeros((1, nb, 8, 1, 2), np.float32),
                   "v": np.zeros((1, nb, 8, 1, 2), np.float32)})
        return {"shipment": shipment, "num_input_tokens": len(ids),
                "first_token": 0, "kv_blocks": nb}

    def decode_remote_stream(self, shipment, *, deadline=None,
                             trace_id: str = ""):
        from kubeflow_tpu.serve.kv_transfer import peek_meta
        from kubeflow_tpu.utils import obs

        t_decode0 = time.perf_counter()
        meta = peek_meta(shipment)
        max_tokens = int(meta.get("max_tokens", 16))
        # Resume cursor (ISSUE 14): same contract as the real engine —
        # the deterministic stream replays, the first `resume_skip`
        # tokens are dropped from chunk events, the done summary stays
        # full.
        skip = int(meta.get("resume_skip", 0))
        nb = max(1, -(-len(meta.get("tokens", [0])) // 8))
        with self._slots_sem:
            self.engine.enter()
            try:
                emitted = 0
                while emitted < max_tokens:
                    n = min(8, max_tokens - emitted)
                    time.sleep(n * self.per_token_s)
                    toks = list(range(emitted, emitted + n))
                    emitted += n
                    if skip:
                        dropped = min(skip, len(toks))
                        skip -= dropped
                        toks = toks[dropped:]
                    if toks:
                        yield {"tokens": toks}
            finally:
                self.engine.exit()
        self.engine.bump(requests=1, remote_admits=1,
                         kv_blocks_received=nb,
                         decode_tokens=max_tokens)
        # Span parity with the real engine surface (ISSUE 20): the
        # caller's trace id (header-forwarded OR adopted from the
        # shipment meta by the decode handler) tags the decode work, so
        # assembled distributed traces see the remote-decode leg even
        # against fake replicas.
        obs.record("serve.decode_remote", t_decode0,
                   time.perf_counter(), trace_id,
                   tokens=max_tokens, resume_skip=int(
                       meta.get("resume_skip", 0)))
        yield {"done": True, "output_ids": list(range(max_tokens)),
               "num_output_tokens": max_tokens,
               "prefix_hit": bool(meta.get("prefix_hit"))}

    def decode_remote(self, shipment, *, deadline=None,
                      trace_id: str = "") -> dict:
        out: dict = {}
        for ev in self.decode_remote_stream(shipment, deadline=deadline,
                                            trace_id=trace_id):
            if ev.get("done"):
                out = {k: v for k, v in ev.items() if k != "done"}
        return out

    def predict(self, inputs):
        return [np.asarray(inputs[0])]


def make_fake_replica(name: str = "m", *, slots: int = 4,
                      max_inflight: int = 64, grpc: bool = False,
                      **model_kw):
    """One in-process fake replica: (ModelServer, base_url, model).
    Registered under model name `name` with a REAL admission gate, so
    overload sheds and readiness degradation behave exactly like a
    production replica's. The worker pool is sized by admission depth,
    not CPU count — fake service time is sleeps, and a 2-CPU test host
    must not serialize the concurrency the bench exists to measure."""
    model = FakeGenerativeModel(name, slots=slots, **model_kw)
    server = ModelServer(max_inflight=max_inflight,
                        executor_workers=max_inflight)
    server.repo.register(model, load=False)
    port = server.start_background()
    if grpc:
        server.start_grpc()
    return server, f"http://127.0.0.1:{port}", model


# -- open-loop generator ----------------------------------------------------


def _post_generate(base_url: str, model: str, payload: dict,
                   deadline_ms: float | None,
                   timeout_s: float = 30.0
                   ) -> tuple[int, dict, dict, float | None]:
    """Returns (status, body, response_headers, ttft_s) — the headers
    carry the router's per-request provenance (X-Tpk-Replica /
    X-Tpk-Attempts); `ttft_s` is the CLIENT-side time to first body
    byte (None on failures), the ground truth the router's
    tpk_router_ttft_seconds histogram is cross-checked against."""
    req = urllib.request.Request(
        f"{base_url}/v1/models/{model}:generate",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    if deadline_ms is not None:
        req.add_header(DEADLINE_HEADER, str(int(deadline_ms)))
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            first = r.read(1)
            ttft_s = time.monotonic() - t0
            body = first + r.read()
            return (r.status, json.loads(body or b"{}"),
                    dict(r.headers), ttft_s)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except json.JSONDecodeError:
            body = {}
        return e.code, body, dict(e.headers or {}), None
    except Exception as e:
        return -1, {"error": f"{type(e).__name__}: {e}"}, {}, None


def open_loop(base_url: str, model: str, prompts: list[list[int]], *,
              rate_rps: float, duration_s: float, max_tokens: int = 24,
              deadline_ms: float | None = 2000.0,
              seed: int = 0) -> list[dict]:
    """Fire POST :generate requests at seeded Poisson arrival times for
    `duration_s`, cycling through `prompts`. Every request fires at its
    schedule (open loop); returns one record per request."""
    rng = np.random.default_rng(seed)
    arrivals = []
    t = 0.0
    while t < duration_s:
        t += float(rng.exponential(1.0 / rate_rps))
        if t < duration_s:
            arrivals.append(t)
    records: list[dict] = []
    rec_lock = threading.Lock()
    threads: list[threading.Thread] = []

    def fire(i: int, sched: float):
        payload = {"input_ids": prompts[i % len(prompts)],
                   "max_tokens": max_tokens}
        t0 = time.monotonic()
        status, body, hdrs, ttft_s = _post_generate(
            base_url, model, payload, deadline_ms)
        t1 = time.monotonic()
        try:
            attempts = int(hdrs.get("X-Tpk-Attempts", 1))
        except (TypeError, ValueError):
            attempts = 1
        with rec_lock:
            records.append({
                "sched_s": sched, "status": status,
                "latency_ms": (t1 - t0) * 1e3,
                "ttft_ms": (None if ttft_s is None
                            else ttft_s * 1e3),
                "prefix_hit": bool(body.get("prefix_hit")),
                # Per-request provenance (ISSUE 14): which replica
                # served it, how many placement attempts it took, and
                # its actual wall window — so fault-overlap claims are
                # computed from per-request truth, not aggregates.
                "replica": hdrs.get("X-Tpk-Replica"),
                "retries": max(attempts - 1, 0),
                "t_start_s": t0 - start, "t_end_s": t1 - start,
            })

    start = time.monotonic()
    for i, sched in enumerate(arrivals):
        delay = start + sched - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=fire, args=(i, sched), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=60.0)
    return records


def summarize(records: list[dict], duration_s: float,
              deadline_ms: float | None) -> dict:
    """Per-arm report: offered/goodput rps, p50/p99 over successful
    requests, shed rate, deadline-miss rate (504s + replies that landed
    past the client's budget)."""
    n = len(records)
    ok = [r for r in records if r["status"] == 200]
    sheds = sum(1 for r in records if r["status"] == 503)
    late = (sum(1 for r in ok if deadline_ms is not None
                and r["latency_ms"] > deadline_ms))
    misses = sum(1 for r in records if r["status"] == 504) + late
    lat = sorted(r["latency_ms"] for r in ok)

    def pct(p):
        if not lat:
            return None
        return round(lat[min(int(len(lat) * p), len(lat) - 1)], 2)

    hits = sum(1 for r in ok if r["prefix_hit"])
    return {
        "requests": n,
        "offered_rps": round(n / duration_s, 1),
        "completed_ok": len(ok),
        "goodput_rps": round((len(ok) - late) / duration_s, 1),
        "p50_ms": pct(0.50), "p99_ms": pct(0.99),
        "shed_rate": round(sheds / max(n, 1), 4),
        "deadline_miss_rate": round(misses / max(n, 1), 4),
        "prefix_hit_rate": round(hits / max(len(ok), 1), 4),
        "errors": sum(1 for r in records
                      if r["status"] not in (200, 503, 504)),
    }


def _quantiles_from_cum(buckets: dict[float, float], total: float,
                        quantiles=(0.5, 0.99)) -> dict:
    """Interpolate quantiles from cumulative `le` buckets (+Inf folds
    to the last finite bound — a histogram can't say more)."""
    if not buckets or total <= 0:
        return {}
    out = {}
    bounds = sorted(buckets)
    for q in quantiles:
        target = q * total
        lo_bound, lo_cum = 0.0, 0.0
        for ub in bounds:
            cum = buckets[ub]
            if cum >= target:
                if ub == float("inf"):
                    out[f"p{int(q * 100)}_ms"] = round(lo_bound * 1e3, 2)
                    break
                frac = ((target - lo_cum) / max(cum - lo_cum, 1e-12))
                val = lo_bound + frac * (ub - lo_bound)
                out[f"p{int(q * 100)}_ms"] = round(val * 1e3, 2)
                break
            lo_bound, lo_cum = ub, cum
    out["count"] = int(total)
    return out


def histogram_quantiles(prom_texts: list[str], name: str,
                        quantiles=(0.5, 0.99)) -> dict:
    """Merge one histogram family across replica scrapes (all label
    sets summed) and interpolate quantiles from the cumulative buckets
    — the 'p50/p99 from the existing histograms' view."""
    buckets: dict[float, float] = {}
    total = 0.0
    for text in prom_texts:
        for line in text.splitlines():
            if not line.startswith(name):
                continue
            metric, _, value = line.rpartition(" ")
            if metric.startswith(f"{name}_bucket"):
                le = metric.rsplit('le="', 1)[-1].rstrip('"}')
                ub = float("inf") if le == "+Inf" else float(le)
                buckets[ub] = buckets.get(ub, 0.0) + float(value)
            elif metric.startswith(f"{name}_count"):
                total += float(value)
    return _quantiles_from_cum(buckets, total, quantiles)


def _hist_snapshot(model: str) -> dict:
    from kubeflow_tpu.utils.resilience import metrics as res_metrics

    return res_metrics.get_histogram("tpk_serve_request_latency_seconds",
                                     model=model)


def _router_ttft_snapshot() -> dict:
    from kubeflow_tpu.utils.resilience import metrics as res_metrics

    return res_metrics.get_histogram("tpk_router_ttft_seconds",
                                     intent="generate")


def _ttft_crosscheck(records: list[dict], before: dict,
                     after: dict) -> dict:
    """Client-recorded TTFT vs the router's tpk_router_ttft_seconds
    histogram (section delta — the registry is process-global across
    arms): the same request population counted on both sides, and the
    means must agree. Client TTFT sits ABOVE the router's flush-
    boundary sample by client loop/socket overhead, never structurally
    below; tests/test_router.py pins the agreement bound."""
    client = [r["ttft_ms"] for r in records
              if r["status"] == 200 and r.get("ttft_ms") is not None]
    count = after.get("count", 0) - before.get("count", 0)
    total_s = after.get("sum", 0.0) - before.get("sum", 0.0)
    router_mean_ms = (total_s / count * 1e3) if count else None
    client_mean_ms = (sum(client) / len(client)) if client else None
    out = {
        "client_count": len(client), "router_count": count,
        "client_mean_ms": (round(client_mean_ms, 2)
                           if client_mean_ms is not None else None),
        "router_mean_ms": (round(router_mean_ms, 2)
                           if router_mean_ms is not None else None),
    }
    if client_mean_ms is not None and router_mean_ms is not None:
        out["agreement_ms"] = round(client_mean_ms - router_mean_ms, 2)
    return out


def _hist_delta(before: dict, after: dict) -> dict:
    """SECTION DELTA of the serve-latency histogram (the CTRLBENCH.json
    precedent): the registry is process-global, so an arm's view must
    subtract everything earlier arms observed."""
    buckets = {}
    for le, cum in after.get("buckets", {}).items():
        ub = float("inf") if le == "+Inf" else float(le)
        buckets[ub] = cum - before.get("buckets", {}).get(le, 0)
    total = after.get("count", 0) - before.get("count", 0)
    return _quantiles_from_cum(buckets, total)


# -- the router benchmark ---------------------------------------------------


def _prompt_mix(rng: np.random.Generator, *, prefixes: int,
                repeats: int, vocab: int = 30000) -> list[list[int]]:
    """`prefixes` distinct PREFIX_TOKENS-token prefixes, each appearing
    `repeats` times with a different short suffix — the shape that
    rewards prefix affinity (same prefix -> same replica -> cache hit)
    and punishes scattering. Shuffled so arrival order interleaves
    prefixes."""
    heads = [list(map(int, rng.integers(2, vocab, PREFIX_TOKENS)))
             for _ in range(prefixes)]
    prompts = []
    for head in heads:
        for _ in range(repeats):
            tail = list(map(int, rng.integers(2, vocab,
                                              int(rng.integers(2, 8)))))
            prompts.append(head + tail)
    rng.shuffle(prompts)
    return prompts


def run_routerbench(quick: bool = False, seed: int = 0) -> dict:
    """The ROUTERBENCH.json payload. Pure host-side (fake CPU replicas
    behind real ModelServers + the real router) — no chip, no binary."""
    from kubeflow_tpu.serve.router import RouterServer

    # Sized for the HARNESS HOST, not the model: the whole fleet + the
    # router + the open-loop client run in one Python process on a
    # small-CPU container, so per-request interpreter cost (two HTTP
    # hops of tornado/http.client) caps total request rate long before
    # any router mechanism does. Service times are slow enough that the
    # offered load at 0.7x capacity stays well inside the interpreter's
    # envelope — the arms then measure PLACEMENT AND SCALING, not GIL
    # contention (the §5 lesson, applied in advance).
    slots = 2
    per_token_s = 0.01
    prefill_s, hit_prefill_s = 0.03, 0.005
    max_tokens = 24
    duration = 6.0 if quick else 15.0
    deadline_ms = 2000.0
    # Per-replica service time ~= prefill + tokens*per_token; offered
    # load is 70% of nominal capacity per replica, scaled by N for the
    # routed-N arm — same per-replica pressure in every arm.
    svc_s = prefill_s + max_tokens * per_token_s
    cap_rps = slots / svc_s
    rate_1 = 0.7 * cap_rps
    rng = np.random.default_rng(seed)
    result: dict = {
        "metric": "routerbench",
        "mode": "fake-cpu-replicas",
        "note": ("replicas are slot-limited timed FAKE engines behind "
                 "real ModelServers: these numbers measure the router "
                 "(placement, proxy overhead, horizontal scaling, "
                 "affinity), NOT model decode throughput"),
        "params": {"slots": slots, "per_token_s": per_token_s,
                   "prefill_s": prefill_s,
                   "hit_prefill_s": hit_prefill_s,
                   "max_tokens": max_tokens, "duration_s": duration,
                   "deadline_ms": deadline_ms,
                   "offered_frac_of_capacity": 0.7,
                   "capacity_rps_per_replica": round(cap_rps, 1),
                   "quick": bool(quick), "seed": seed},
        "arms": {},
    }

    def one_arm(n_replicas: int, *, routed: bool, affinity: bool = True,
                rate: float | None = None, prompts=None,
                label: str = "") -> dict:
        servers = []
        router = None
        try:
            replicas = [make_fake_replica("m", slots=slots,
                                          per_token_s=per_token_s,
                                          prefill_s=prefill_s,
                                          hit_prefill_s=hit_prefill_s)
                        for _ in range(n_replicas)]
            servers = [s for s, _, _ in replicas]
            if routed:
                router = RouterServer(affinity=affinity)
                router.fleet.poll_interval_s = 0.15
                for i, (_, url, _) in enumerate(replicas):
                    router.fleet.add(f"r{i}", url)
                base = f"http://127.0.0.1:{router.start_background()}"
                time.sleep(0.4)  # let the poller take a first scrape
            else:
                base = replicas[0][1]
            rate = rate or rate_1 * n_replicas
            prompts = prompts or _prompt_mix(
                rng, prefixes=16, repeats=12)
            hist0 = _hist_snapshot("m")
            ttft0 = _router_ttft_snapshot()
            records = open_loop(base, "m", prompts, rate_rps=rate,
                                duration_s=duration,
                                max_tokens=max_tokens,
                                deadline_ms=deadline_ms, seed=seed)
            arm = summarize(records, duration, deadline_ms)
            arm["replicas"] = n_replicas
            # Server-side view from the EXISTING latency histogram
            # (tpk_serve_request_latency_seconds), as a section delta —
            # the registry is process-global across arms.
            arm["histogram"] = _hist_delta(hist0, _hist_snapshot("m"))
            if router is not None:
                arm["router_stats"] = router.router.stats_snapshot()
                arm["ttft"] = _ttft_crosscheck(records, ttft0,
                                               _router_ttft_snapshot())
            return arm
        finally:
            if router is not None:
                router.stop()
            for s in servers:
                s.stop()

    # One shared prompt mix for the three scaling arms: every arm's
    # replicas start cold, so direct-vs-routed p50 compares like with
    # like (same hit pattern) and the routed-4 arm just cycles the mix
    # at 4x the arrival rate.
    base_prompts = _prompt_mix(rng, prefixes=12, repeats=16)
    result["arms"]["direct_1"] = one_arm(1, routed=False,
                                         prompts=base_prompts)
    result["arms"]["routed_1"] = one_arm(1, routed=True,
                                         prompts=base_prompts)
    result["arms"]["routed_4"] = one_arm(4, routed=True,
                                         prompts=base_prompts)
    d1 = result["arms"]["direct_1"]
    r1 = result["arms"]["routed_1"]
    r4 = result["arms"]["routed_4"]
    if d1["p50_ms"] and r1["p50_ms"]:
        result["routed_overhead_p50"] = round(
            r1["p50_ms"] / d1["p50_ms"] - 1.0, 4)
    result["scaling_x"] = round(
        r4["goodput_rps"] / max(r1["goodput_rps"], 1e-9), 2)
    result["scaling_miss_rate_delta"] = round(
        r4["deadline_miss_rate"] - r1["deadline_miss_rate"], 4)

    # Affinity A/B: IDENTICAL traffic (same seed, same prompt mix) over
    # 4 replicas, consistent-hash affinity vs the hash-off control.
    # Many prefixes with few repeats each — the regime where scattering
    # hurts: without affinity every replica pays its own cold miss per
    # prefix, and there aren't enough repeats to warm all four anyway.
    ab_prompts = _prompt_mix(np.random.default_rng(seed + 1),
                             prefixes=48, repeats=4)
    on = one_arm(4, routed=True, affinity=True, prompts=ab_prompts,
                 rate=rate_1 * 4)
    off = one_arm(4, routed=True, affinity=False, prompts=ab_prompts,
                  rate=rate_1 * 4)
    result["affinity"] = {
        "on": on, "off": off,
        "hit_rate_on": on["prefix_hit_rate"],
        "hit_rate_off": off["prefix_hit_rate"],
        "hit_rate_delta": round(on["prefix_hit_rate"]
                                - off["prefix_hit_rate"], 4),
    }
    return result
