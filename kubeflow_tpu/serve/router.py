"""Front-door router: one address over N engine replicas (ISSUE 9).

One engine's ceiling behind one tunnel is a few hundred tok/s
(SERVEBENCH.json); the ROADMAP's "millions of users" direction is
horizontal. This module is the front door: it proxies the native
`:generate`, the OpenAI facade, the v1/v2 predict surfaces, and the gRPC
open-inference plane over a `Fleet` of model-server replicas
(serve/fleet.py), placing each request by:

  * **Prefix/adapter affinity.** Requests whose prompts share a prefix
    land on the same replica — the engine's prefix cache is keyed on the
    `(adapter, len, hash)` family, so cache warmth is per replica and
    scattering a hot prefix across the fleet wastes it. Placement
    consistent-hashes the request's affinity key (adapter + prompt
    prefix) onto a ring of virtual nodes per replica, so membership
    changes only remap the keys of the replicas that changed.
  * **Load-based spill-over.** Affinity yields when the cache-warm
    replica is more than `spill_margin` requests deeper than the
    least-loaded one — a hot prefix must not melt one replica while the
    rest idle. Load is router-outstanding + the replica's scraped
    `tpk_decode_inflight_depth` + admission occupancy; the scrape runs
    on the fleet's background poller, NEVER on the placement path.
  * **Least-loaded fallback.** No affinity signal (tensor inference,
    metadata GETs) → lowest load, ties broken by name (deterministic).

Composition with the existing layers (not a bypass):

  * `X-Request-Id` is honored/assigned and forwarded; the router's
    place/forward spans join the same trace the replica's admit →
    prefill → decode spans carry.
  * `X-Request-Timeout-Ms` is re-issued to the replica as the REMAINING
    budget at forward time — deadline propagation, not per-hop resets.
  * A replica's 503 overload shed is FORWARDED (Retry-After intact),
    never retried: backpressure must reach the caller or the router
    converts overload into a retry storm.
  * Connect-level failures and draining-replica rejections ARE retried,
    on a different replica, under the caller's remaining deadline —
    these are placement mistakes, not capacity signals. A POST-CONNECT
    timeout is neither: the replica accepted the request and may still
    be decoding it, so the caller gets a 504 and no replay (a replay
    would duplicate the work on a second replica).

Scale events come from serve/fleet.py: `drain()` stops placement while
in-flight requests finish; `FleetAutoscaler` turns router-observed shed
rate/occupancy into scale-out and drain-then-retire scale-in.
"""

from __future__ import annotations

import asyncio
import bisect
import dataclasses
import hashlib
import http.client
import json
import math
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import urlsplit

import tornado.httpserver
import tornado.ioloop
import tornado.iostream
import tornado.netutil
import tornado.web

from kubeflow_tpu.serve.fleet import Fleet
from kubeflow_tpu.serve.headers import (ATTEMPTS_HEADER, DEADLINE_HEADER,
                                        DRAINING_HEADER, REPLICA_HEADER,
                                        REQUEST_ID_HEADER)
from kubeflow_tpu.utils import obs
from kubeflow_tpu.utils.resilience import (Deadline, MetricsMergeError,
                                           merge_prometheus_texts,
                                           metrics as res_metrics)

#: Headers copied replica → caller. Everything else is router-owned
#: (the router echoes ITS X-Request-Id; hop-by-hop headers must not
#: leak through a proxy).
_FORWARD_RESP_HEADERS = ("Content-Type", "Retry-After")

#: Request paths that are inference traffic (placement + retry + body
#: parse for affinity); everything else is metadata/control and just
#: takes the least-loaded forward.
_GENERATIVE_SUFFIXES = (":generate", "/generate")
_OPENAI_PATHS = ("/openai/v1/completions", "/openai/v1/chat/completions")
_INFER_SUFFIXES = (":predict", ":explain", "/infer")

#: Bodies above this size skip the affinity parse (see _proxy).
_AFFINITY_PARSE_CAP = 512 * 1024


def _hash64(s: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")


def affinity_key(path: str, body: dict | None) -> str | None:
    """The placement-affinity key of one request, or None when the
    request carries no prefix signal (→ least-loaded).

    Built to follow the engine prefix cache's key family (adapter, len,
    hash): the ADAPTER (either the payload field or the OpenAI
    "<base>:<adapter>" model id) plus a bounded PREFIX of the prompt —
    leading token ids when the caller sends `input_ids`, leading text
    otherwise. Two requests that would hit the same cached prefix
    produce the same key; max_tokens/temperature/suffix differences
    don't perturb it."""
    if not isinstance(body, dict):
        return None
    scope = path.rsplit("/", 1)[-1] if path else ""
    adapter = body.get("adapter") or ""
    model = body.get("model") or scope
    ids = body.get("input_ids")
    if isinstance(ids, (list, tuple)) and ids:
        head = ",".join(str(t) for t in ids[:32])
        return f"{model}|{adapter}|ids:{head}"
    for field in ("text", "prompt"):
        v = body.get(field)
        if isinstance(v, str) and v:
            return f"{model}|{adapter}|txt:{v[:128]}"
    msgs = body.get("messages")
    if isinstance(msgs, list) and msgs:
        try:
            head = json.dumps(msgs[0], sort_keys=True)[:128]
        except (TypeError, ValueError):
            return None
        return f"{model}|{adapter}|msg:{head}"
    return None


class Router:
    """Placement policy over a Fleet: consistent-hash affinity with
    load-based spill-over, least-loaded otherwise. Pure table math —
    every signal it reads was cached by the fleet poller."""

    def __init__(self, fleet: Fleet, *, affinity: bool = True,
                 spill_margin: float = 4.0, vnodes: int = 48):
        self.fleet = fleet
        self.affinity = bool(affinity)
        self.spill_margin = float(spill_margin)
        self.vnodes = int(vnodes)
        #: intent -> (version, ring) — the prefill/unified rings differ
        #: in a role-split fleet, so each intent caches its own.
        self._rings: dict = {}  # guarded-by: _ring_lock
        self._ring_lock = threading.Lock()
        self.stats = {  # guarded-by: _stats_lock
            "placed": 0, "affinity_hits": 0, "spills": 0,
            "least_loaded": 0, "decode_pool": 0, "retries": 0, "ok": 0,
            "handoffs": 0, "handoff_retries": 0,
            "resumes": 0, "resume_failures": 0,
            "sheds_forwarded": 0, "no_replica": 0, "errors": 0,
        }
        self._stats_lock = threading.Lock()

    def stats_snapshot(self) -> dict:
        with self._stats_lock:
            return dict(self.stats)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + n

    def _ring_for(self, names: list[str], version: int,
                  intent: str | None = None) -> list[tuple[int, str]]:
        """The consistent-hash ring over `names`, rebuilt only when fleet
        membership/state changed (cheap version check otherwise); cached
        PER INTENT, since a role-split fleet's prefill ring covers a
        different replica set than the unified one. `version` must have
        been read BEFORE `names` was snapshotted: a membership change
        between the two then stamps the fresher set with the older
        version — over-invalidation (one spare rebuild), never a stale
        ring cached under the newest version."""
        with self._ring_lock:
            cached = self._rings.get(intent)
            if cached is not None and cached[0] == version:
                return cached[1]
        ring = sorted((_hash64(f"{name}#{i}"), name)
                      for name in names for i in range(self.vnodes))
        with self._ring_lock:
            self._rings[intent] = (version, ring)
        return ring

    def _ring_lookup(self, ring, point: int) -> str | None:
        if not ring:
            return None
        # (point,) sorts below every (point, name), so bisect_left gives
        # the first vnode at-or-after the point; wrap closes the ring.
        return ring[bisect.bisect_left(ring, (point,)) % len(ring)][1]

    # Placement is on every request's critical path: table reads and
    # hash math only — the fleet poller already cached every load
    # signal, so nothing here blocks on a scrape, a device, or I/O.
    # tpk-hot: router-placement
    def place(self, key: str | None, exclude: frozenset = frozenset(),
              intent: str | None = None) -> tuple[str | None, str]:
        """Choose a replica for a request with affinity key `key`
        (None = no prefix signal). Returns (replica_name, reason);
        (None, "no_replica") when nothing is placeable. `exclude` drops
        replicas that already failed this request (retry path).

        `intent` selects the disaggregation phase (ISSUE 13): "prefill"
        placements keep the prefix-affinity logic over prefill-capable
        replicas (cache warmth lives where prefills run); "decode"
        placements are load/pool-driven — least loaded, ties broken by
        the LARGEST free-block pool (the admission currency), then name
        — affinity would be meaningless there, the KV arrives on the
        wire. None is the unified full-request intent."""
        version = self.fleet.version()  # before loads() — see _ring_for
        if intent == "decode":
            sig = self.fleet.signals("decode")
            candidates = {n: v for n, v in sig.items()
                          if n not in exclude}
            if not candidates:
                self._bump("no_replica")
                return None, "no_replica"
            chosen = min(candidates,
                         key=lambda n: (candidates[n][0],
                                        -candidates[n][1], n))
            res_metrics.inc("tpk_router_placement_total",
                            reason="decode-pool")
            self._bump("placed")
            self._bump("decode_pool")
            return chosen, "decode-pool"
        loads = self.fleet.loads(intent=intent)
        candidates = loads if not exclude else \
            {n: v for n, v in loads.items() if n not in exclude}
        if not candidates:
            self._bump("no_replica")
            return None, "no_replica"
        floor = min(candidates.values())
        reason = "least-loaded"
        chosen = None
        if self.affinity and key is not None and len(loads) > 1:
            # The ring covers the FULL placeable set — it is cached
            # against the fleet version, so a retry's per-request
            # exclusions must apply at lookup time, never to the ring
            # itself (a poisoned cache would silently drop a healthy
            # replica from affinity until the next membership change).
            ring = self._ring_for(sorted(loads), version, intent)
            target = self._ring_lookup(ring, _hash64(key))
            if target in candidates:
                if candidates[target] - floor < self.spill_margin:
                    chosen, reason = target, "affinity-hit"
                else:
                    reason = "spill"
        elif self.affinity and key is not None:
            # Single candidate: the hash could only name it anyway.
            reason = "affinity-hit"
        if chosen is None:
            chosen = min(candidates, key=lambda n: (candidates[n], n))
        res_metrics.inc("tpk_router_placement_total", reason=reason)
        self._bump("placed")
        self._bump({"affinity-hit": "affinity_hits", "spill": "spills",
                    "least-loaded": "least_loaded"}[reason])
        return chosen, reason


class _ForwardResult:
    """One upstream attempt's outcome: a live response to stream, or a
    complete small response (sheds, errors) already read."""

    __slots__ = ("status", "headers", "conn", "resp", "body")

    def __init__(self, status, headers, conn=None, resp=None, body=None):
        self.status = status
        self.headers = headers
        self.conn = conn
        self.resp = resp
        self.body = body


class RetryableForwardError(Exception):
    """Connect-level failure or a draining replica — retry elsewhere."""


class ForwardTimeoutError(Exception):
    """The upstream ran past its time budget AFTER the connection was
    established. The replica accepted the request and may still be
    executing it, so replaying elsewhere would duplicate decode work —
    the caller gets a 504 instead."""


def _forward_once(url: str, method: str, path: str, body: bytes | None,
                  headers: dict, timeout_s: float,
                  read_body: bool = True) -> _ForwardResult:
    """One blocking proxy attempt against `url`. Raises
    RetryableForwardError on connect-level failures and drain
    rejections, ForwardTimeoutError on a post-connect timeout. With
    `read_body` (every non-streaming request) the WHOLE response is
    read here — one executor hop per request instead of one per chunk;
    streams keep the live (conn, resp) to relay chunk-by-chunk."""
    parts = urlsplit(url)
    conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                      timeout=timeout_s)
    try:
        conn.connect()
    except OSError as e:
        # Pre-request failure (refused, reset, connect timeout):
        # nothing reached the replica, replaying elsewhere is safe.
        conn.close()
        raise RetryableForwardError(f"{type(e).__name__}: {e}") from e
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        draining = (resp.status == 503
                    and resp.getheader(DRAINING_HEADER) is not None)
        whole = draining or read_body or resp.status == 503
        data = resp.read() if whole else None
    except TimeoutError as e:
        # socket.timeout past the established connection: the request
        # is in the replica's hands — slow is not retry fodder.
        conn.close()
        raise ForwardTimeoutError(
            f"no response within {timeout_s:.1f}s") from e
    except (ConnectionError, OSError, http.client.HTTPException) as e:
        # HTTPException covers a replica dying mid-response
        # (BadStatusLine / IncompleteRead on a closed socket) — same
        # retry class as a straight connect reset: nothing reached the
        # caller yet, so replaying elsewhere is safe.
        conn.close()
        raise RetryableForwardError(f"{type(e).__name__}: {e}") from e
    if draining:
        conn.close()
        raise RetryableForwardError(
            f"replica draining: {data[:120]!r}")
    if whole:
        conn.close()
        return _ForwardResult(resp.status, resp.getheaders(), body=data)
    return _ForwardResult(resp.status, resp.getheaders(), conn=conn,
                          resp=resp)


@dataclasses.dataclass
class _ForwardState:
    """Mutable retry bookkeeping threaded through `_forward_attempt`.
    Lives OUTSIDE the helper so a caller that re-enters the loop (the
    decode resume path) keeps its exclusions and its attempt budget
    across entries — a resumed stream must not get a fresh budget."""

    exclude: set = dataclasses.field(default_factory=set)
    attempts: int = 0


@dataclasses.dataclass
class _Attempt:
    """Terminal outcome of one `_forward_attempt` run.

    kind:
      * ``no_replica`` — placement found nothing live.
      * ``deadline``   — the caller's deadline expired before a forward
        (already counted + metered by the helper).
      * ``exhausted``  — a connect-class failure and no retry budget /
        deadline left; `expired`/`draining` say which terminal flavor.
      * ``timeout``    — post-connect timeout (replica may still be
        working: no replay; already counted + metered by the helper).
      * ``ok``         — `result` is live and `name` is STILL CHECKED
        OUT: the caller owns the matching `fleet.checkin`.
    """

    kind: str
    name: str | None = None
    result: _ForwardResult | None = None
    t0: float = 0.0
    error: Exception | None = None
    expired: bool = False
    draining: bool = False


class _RouterBase(tornado.web.RequestHandler):
    def initialize(self, server: "RouterServer"):
        self.server = server
        self.router = server.router
        self.fleet = server.fleet

    def write_json(self, obj, status: int = 200) -> None:
        self.set_status(status)
        self.set_header("Content-Type", "application/json")
        self.finish(json.dumps(obj))

    def write_error(self, status_code: int, **kwargs) -> None:
        reason = self._reason
        if "exc_info" in kwargs:
            exc = kwargs["exc_info"][1]
            if not isinstance(exc, tornado.web.HTTPError):
                reason = f"{type(exc).__name__}: {exc}"
        self.write_json({"error": reason}, status=status_code)


class ProxyHandler(_RouterBase):
    """The catch-all data-plane proxy: place, forward, stream back."""

    async def get(self, path):
        await self._proxy(path)

    async def post(self, path):
        await self._proxy(path)

    async def put(self, path):
        await self._proxy(path)

    async def delete(self, path):
        await self._proxy(path)

    def _count(self, replica: str | None, outcome: str) -> None:
        res_metrics.inc("tpk_router_requests_total",
                        replica=replica or "-", outcome=outcome)
        # Every terminal count doubles as SLO/flight-recorder evidence:
        # the latest outcome wins (a resumed stream's mid-loop
        # upstream_error is overwritten by the final ok) and the replica
        # joins the request's trail.
        slo = getattr(self, "_slo", None)
        if slo is not None:
            slo["outcome"] = outcome
            if replica:
                self._slo_replica(replica)

    def _slo_replica(self, name: str) -> None:
        """Append `name` to the request's replica trail (consecutive
        duplicates collapsed — retries against the same replica are an
        attempt count, not a trail hop)."""
        slo = getattr(self, "_slo", None)
        if slo is not None and name and (not slo["replicas"]
                                         or slo["replicas"][-1] != name):
            slo["replicas"].append(name)

    def _observe_flush(self) -> None:
        """SLO accounting at the byte-flush boundary: the FIRST flushed
        content frame is TTFT (what the caller experienced — placement,
        queueing, prefill, handoff all included); subsequent flushes on
        a stream are inter-token-latency gaps."""
        slo = getattr(self, "_slo", None)
        if slo is None:
            return
        now = time.perf_counter()
        if slo["ttft_s"] is None:
            slo["ttft_s"] = now - slo["t0"]
            # tpk-slo: router-ttft-observe — THE TTFT observe site
            # (tpklint's red-switch test pins this marker: deleting the
            # observation silently is a finding).
            res_metrics.observe("tpk_router_ttft_seconds",
                                slo["ttft_s"], intent=slo["intent"])
        elif slo["stream"] and slo["last_flush"] is not None:
            res_metrics.observe("tpk_router_itl_seconds",
                                now - slo["last_flush"])
        slo["last_flush"] = now

    def _finalize_slo(self) -> None:
        """Conclude one proxied request: e2e/deadline-miss observations
        plus the flight-recorder record — the one place every request
        (ok, shed, resumed, died) reports what actually happened.
        Idempotent: the relay paths can conclude through several exits."""
        slo = getattr(self, "_slo", None)
        if slo is None or slo["final"]:
            return
        slo["final"] = True
        e2e = time.perf_counter() - slo["t0"]
        outcome = slo["outcome"]
        if outcome is None:
            status = self.get_status()
            outcome = ("ok" if status < 400 else
                       "shed" if status == 503 else
                       "deadline" if status == 504 else
                       "client_error" if status < 500
                       else "upstream_error")
        missed = (slo["deadline"] is not None
                  and slo["deadline"].expired())
        res_metrics.observe("tpk_router_e2e_seconds", e2e,
                            outcome=outcome)
        if missed:
            res_metrics.inc("tpk_router_deadline_miss_total",
                            intent=slo["intent"])
        self.server.flight_recorder.record(
            trace_id=slo["trace_id"], path=slo["path"],
            intent=slo["intent"], stream=slo["stream"],
            t_start_unix=slo["t_start_unix"], ttft_s=slo["ttft_s"],
            e2e_s=e2e, outcome=outcome, reason=slo["reason"],
            replicas=list(slo["replicas"]), resumes=slo["resumes"],
            attempts=slo["attempts"], deadline_miss=missed)

    def _deadline(self) -> Deadline | None:
        raw = self.request.headers.get(DEADLINE_HEADER)
        if raw is None:
            return None
        try:
            ms = float(raw)
            # Mirrors server.py request_deadline: NaN/inf would defeat
            # every expiry comparison (and overflow the remaining-ms
            # re-issue) downstream.
            if not math.isfinite(ms) or ms <= 0:
                raise ValueError
        except ValueError:
            raise tornado.web.HTTPError(
                400, reason=f"{DEADLINE_HEADER} must be a positive "
                            f"number of milliseconds, got {raw!r}") \
                from None
        return Deadline(ms / 1e3)

    async def _proxy(self, path: str) -> None:
        trace_id = obs.sanitize_trace_id(
            self.request.headers.get(REQUEST_ID_HEADER))
        self.set_header(REQUEST_ID_HEADER, trace_id)
        route = "/" + path
        # Classify (and key affinity) on the bare ROUTE: a query string
        # must not reclassify inference traffic as metadata — that would
        # drop both the affinity key and the drain-retry contract.
        is_generative = (route.endswith(_GENERATIVE_SUFFIXES)
                         or route in _OPENAI_PATHS)
        is_inference = is_generative or route.endswith(_INFER_SUFFIXES)
        self._slo = {
            "t0": time.perf_counter(), "t_start_unix": time.time(),
            "trace_id": trace_id, "path": route,
            "intent": ("generate" if is_generative else
                       "infer" if is_inference else "meta"),
            "deadline": None, "stream": False, "ttft_s": None,
            "last_flush": None, "replicas": [], "resumes": 0,
            "attempts": 0, "outcome": None, "reason": None,
            "final": False,
        }
        try:
            await self._proxy_impl(route, trace_id, is_generative,
                                   is_inference)
        except tornado.web.HTTPError as e:
            slo = self._slo
            if slo["outcome"] is None:
                slo["outcome"] = ("shed" if e.status_code == 503 else
                                  "deadline" if e.status_code == 504 else
                                  "upstream_error" if e.status_code >= 500
                                  else "client_error")
            if not slo["reason"]:
                slo["reason"] = e.reason or ""
            raise
        finally:
            self._finalize_slo()

    async def _proxy_impl(self, route: str, trace_id: str,
                          is_generative: bool,
                          is_inference: bool) -> None:
        deadline = self._deadline()
        self._slo["deadline"] = deadline
        full_path = route
        if self.request.query:
            full_path += "?" + self.request.query
        key = None
        wants_stream = False
        if is_generative and self.request.body:
            raw = self.request.body
            if len(raw) <= _AFFINITY_PARSE_CAP:
                try:
                    parsed = json.loads(raw)
                    key = affinity_key(route, parsed)
                    wants_stream = bool(isinstance(parsed, dict)
                                        and parsed.get("stream"))
                except (ValueError, TypeError):
                    key = None  # malformed body: the replica renders the 400
            else:
                # json.loads holds the GIL for the whole parse — a
                # multi-MB longctx payload parsed on the ioloop would
                # stall every other request for placement sugar worth a
                # 32-token prefix. Forego affinity; a substring test
                # picks the relay mode (a false positive only costs
                # chunk-wise relay of a non-streamed reply).
                wants_stream = b'"stream"' in raw
        self._slo["stream"] = wants_stream
        if (is_generative and self.request.method == "POST"
                and self.fleet.role_split()):
            # Disaggregated fleet (ISSUE 13): two-phase handoff —
            # prefill replica ships KV blocks, decode replica streams
            # the tokens. Falls through to the unified path when no
            # prefill-capable replica is placeable (or the surface has
            # no :prefill mapping, e.g. the OpenAI facade).
            if await self._proxy_disagg(route, trace_id, deadline, key,
                                        wants_stream):
                return
        # A full generate needs a replica serving BOTH phases (a
        # decode-role replica would refuse the prefill); metadata and
        # tensor-infer traffic places over every role.
        intent = "generate" if is_generative else None
        state = _ForwardState()
        a = await self._forward_attempt(
            state=state, key=key, intent=intent,
            method=self.request.method, path=full_path,
            body=self.request.body or None,
            content_type=self.request.headers.get("Content-Type"),
            trace_id=trace_id, deadline=deadline,
            read_body=not wants_stream,
            retryable=(is_inference or self.request.method == "GET"),
            drain_rejects=True)
        if a.kind == "no_replica":
            self._count(None, "no_replica")
            self.router._bump("errors")
            self.set_header("Retry-After", "1")
            self.write_json({"error": "no live replica"}, status=503)
            return
        if a.kind == "deadline":
            raise tornado.web.HTTPError(
                504, reason="request deadline exceeded (router)")
        if a.kind == "exhausted":
            if a.expired:
                raise tornado.web.HTTPError(
                    504, reason="request deadline exceeded "
                                "(router retries)") from a.error
            if a.draining:
                # The replica answered cleanly — reflect its drain
                # rejection as the 503 it was, not a 502. NOT counted
                # as a shed: sheds feed the autoscaler's scale-out
                # signal, and a drain rejection is the opposite of
                # overload evidence.
                self.router._bump("draining_rejects")
                self.set_header("Retry-After", "1")
                self.set_header(DRAINING_HEADER, "1")
                self.write_json(
                    {"error": f"replica {a.name} draining"}, status=503)
                return
            raise tornado.web.HTTPError(
                502, reason=f"replica {a.name} unreachable: {a.error}") \
                from a.error
        if a.kind == "timeout":
            raise tornado.web.HTTPError(
                504, reason=f"replica {a.name} timed out: {a.error}") \
                from a.error
        self.set_header(REPLICA_HEADER, a.name)
        self.set_header(ATTEMPTS_HEADER, str(state.attempts))
        try:
            await self._relay(a.result, a.name, trace_id, a.t0)
        finally:
            self.fleet.checkin(a.name)

    def _remaining_headers(self, trace_id: str,
                           deadline: Deadline | None,
                           content_type: str | None = None) -> dict:
        headers = {REQUEST_ID_HEADER: trace_id}
        if content_type:
            headers["Content-Type"] = content_type
        if deadline is not None:
            rem = deadline.remaining()
            headers[DEADLINE_HEADER] = str(max(int((rem or 0.0) * 1e3), 1))
        return headers

    async def _forward_attempt(
            self, *, state: _ForwardState, key: str | None,
            intent: str | None, method: str, path: str,
            body: bytes | None, content_type: str | None,
            trace_id: str, deadline: Deadline | None, read_body: bool,
            retryable: bool = True, retry_reason: str | None = None,
            drain_rejects: bool = False,
            count_handoff: bool = False) -> _Attempt:
        """ONE place → checkout → forward → classify pass, shared by
        the unified proxy loop and both disaggregation phases (prefill,
        decode/resume). Owns the retry loop for connect-class failures
        and drain rejections: nothing reached the caller on those, so
        re-placing elsewhere is safe — `state` carries the exclusions
        and attempt budget so a re-entrant caller (decode resume) keeps
        both across calls. All counting the three callers share lives
        here (pre-forward deadline, retry/exhausted/timeout metrics);
        terminal outcomes come back as an `_Attempt` for the caller to
        render, because the renders legitimately differ (the unified
        path raises HTTPErrors, a started decode stream must close with
        an error frame instead). On ``ok`` the replica is STILL checked
        out — the caller owns the checkin after relaying.

        `retryable=False` (non-inference non-GET traffic) turns the
        first connect failure terminal. `retry_reason` overrides the
        draining/connect retry label (decode passes "prefill_handoff");
        `count_handoff` adds the handoff_retries bump. `drain_rejects`
        (unified path only) keeps a drain-exhausted terminal out of the
        error count — a drain rejection is the opposite of overload
        evidence — so the caller can render it as the 503 it was."""
        loop = asyncio.get_event_loop()
        max_attempts = max(len(self.fleet.names()), 1)
        while True:
            with obs.span("router.place", trace_id=trace_id,
                          path=path) as sp:
                name, reason = self.router.place(
                    key, exclude=frozenset(state.exclude), intent=intent)
                sp.set(replica=name or "-", reason=reason)
            if name is None:
                return _Attempt("no_replica")
            url = self.fleet.url_of(name)
            if url is None:
                state.exclude.add(name)
                continue
            if deadline is not None and deadline.expired():
                self._count(name, "deadline")
                res_metrics.inc("tpk_deadline_expired_total",
                                component="router")
                return _Attempt("deadline", name=name)
            headers = self._remaining_headers(trace_id, deadline,
                                              content_type)
            timeout_s = (deadline.bound(self.server.forward_timeout_s)
                         if deadline is not None
                         else self.server.forward_timeout_s)
            self.fleet.checkout(name)
            state.attempts += 1
            slo = getattr(self, "_slo", None)
            if slo is not None:
                slo["attempts"] += 1
            t0 = time.perf_counter()
            try:
                result = await loop.run_in_executor(
                    self.server.executor, _forward_once, url, method,
                    path, body, headers, timeout_s, read_body)
            except RetryableForwardError as e:
                draining = "draining" in str(e)
                self.fleet.checkin(name, failed=not draining)
                obs.record("router.forward", t0, time.perf_counter(),
                           trace_id=trace_id, replica=name,
                           error=str(e)[:120])
                expired = deadline is not None and deadline.expired()
                if (retryable and state.attempts <= max_attempts
                        and not expired):
                    state.exclude.add(name)
                    res_metrics.inc(
                        "tpk_router_retry_total",
                        reason=(retry_reason if retry_reason
                                else "draining" if draining
                                else "connect"))
                    self.router._bump("retries")
                    if count_handoff:
                        self.router._bump("handoff_retries")
                    continue
                self._count(name, "deadline" if expired
                            else "draining" if draining and drain_rejects
                            else "retry_exhausted")
                if expired or not (draining and drain_rejects):
                    self.router._bump("errors")
                if expired:
                    res_metrics.inc("tpk_deadline_expired_total",
                                    component="router")
                return _Attempt("exhausted", name=name, error=e,
                                expired=expired, draining=draining)
            except ForwardTimeoutError as e:
                # The replica may still be executing the request: no
                # replay (that would duplicate decode work) and no
                # failure mark (slow is not dead) — the caller renders
                # a 504. The gray-ejection EWMA still gets the latency
                # evidence.
                self.fleet.checkin(name)
                self.fleet.observe_forward(name, timeout_s)
                obs.record("router.forward", t0, time.perf_counter(),
                           trace_id=trace_id, replica=name,
                           error=str(e)[:120])
                self._count(name, "upstream_error")
                self.router._bump("errors")
                return _Attempt("timeout", name=name, error=e)
            except Exception:
                # Anything non-retryable still releases the outstanding
                # count, or a drain on this replica would wait forever.
                self.fleet.checkin(name)
                raise
            return _Attempt("ok", name=name, result=result, t0=t0)

    async def _proxy_disagg(self, route: str, trace_id: str,
                            deadline: Deadline | None, key: str | None,
                            wants_stream: bool) -> bool:
        """The prefill→decode handoff (ISSUE 13). Phase 1 places by
        PREFIX AFFINITY over prefill-capable replicas (cache warmth
        lives where prefills run) and receives the KV shipment; phase 2
        places by load/pool over decode-capable replicas and relays the
        token stream. THE ROUTER HOLDS THE SHIPMENT between phases:
        once phase 1 returns, the prefill replica owes this request
        nothing — its death cannot force a re-prefill, and a decode
        replica failing at connect retries on ANOTHER decode replica
        with the same bytes (`tpk_router_retry_total{reason=
        "prefill_handoff"}`), never replaying prefill work. Returns
        False to fall through to the unified single-phase path (no
        prefill replica placeable / unmapped surface). Both phases ride
        `_forward_attempt` — the same place → checkout → forward →
        classify machinery as the unified loop."""
        if route.endswith(":generate"):
            model = route.rsplit("/", 1)[-1][:-len(":generate")]
        elif route.endswith("/generate"):
            parts = route.split("/")
            model = parts[-2] if len(parts) >= 2 else ""
        else:
            return False  # no :prefill mapping for this surface
        if not model:
            return False
        prefill_path = f"/v1/models/{model}:prefill"
        decode_path = f"/v1/models/{model}:decode"
        t_handoff0 = time.perf_counter()

        # -- phase 1: chunked prefill → KV shipment ----------------------
        # A pre-ship failure computed nothing for this request yet, so
        # re-placing the prefill is the plain connect/draining retry
        # class, not a handoff.
        pstate = _ForwardState()
        a = await self._forward_attempt(
            state=pstate, key=key, intent="prefill", method="POST",
            path=prefill_path, body=self.request.body or None,
            content_type="application/json", trace_id=trace_id,
            deadline=deadline, read_body=True)
        if a.kind == "no_replica":
            if pstate.attempts == 0:
                return False  # no prefill capacity: unified path
            self._count(None, "no_replica")
            self.router._bump("errors")
            self.set_header("Retry-After", "1")
            self.write_json({"error": "no live prefill replica"},
                            status=503)
            return True
        if a.kind == "deadline":
            raise tornado.web.HTTPError(
                504, reason="request deadline exceeded (router)")
        if a.kind == "exhausted":
            if a.expired:
                raise tornado.web.HTTPError(
                    504, reason="request deadline exceeded "
                                "(router retries)") from a.error
            raise tornado.web.HTTPError(
                502, reason=f"prefill replica {a.name} unreachable: "
                            f"{a.error}") from a.error
        if a.kind == "timeout":
            raise tornado.web.HTTPError(
                504, reason=f"prefill replica {a.name} timed out: "
                            f"{a.error}") from a.error
        name, result, t0 = a.name, a.result, a.t0
        self.fleet.checkin(name)
        if result.status != 200:
            # Sheds forward as backpressure, errors relay as-is —
            # exactly the unified path's contract. (_relay observes
            # the forward latency itself — observing here too would
            # double-count the sample into the gray EWMA.)
            self.set_header(REPLICA_HEADER, name)
            self.set_header(ATTEMPTS_HEADER, str(pstate.attempts))
            await self._relay(result, name, trace_id, t0)
            return True
        self.fleet.observe_forward(name, time.perf_counter() - t0)
        obs.record("router.forward", t0, time.perf_counter(),
                   trace_id=trace_id, replica=name, status=200,
                   phase="prefill")
        self._slo_replica(name)
        # Stamp the caller's trace id into the held shipment meta
        # (header splice via rewrite_meta — array bytes untouched, fmt
        # unchanged, older replicas ignore the key): the decode
        # replica's spans join the caller's trace even though the
        # :decode POST body is opaque TPKV1, and every resume
        # re-submission restates the stamp along with its cursor.
        from kubeflow_tpu.serve.kv_transfer import rewrite_meta

        try:
            shipment = rewrite_meta(result.body, trace=trace_id)
        except Exception:
            shipment = result.body  # unparseable meta: ship verbatim
        res_metrics.observe("tpk_prefill_handoff_seconds",
                            time.perf_counter() - t_handoff0)
        self.router._bump("handoffs")

        # -- phase 2: shipment → decode replica → caller -----------------
        # The resume loop (ISSUE 14): because the router HOLDS the
        # shipment, a decode replica dying MID-STREAM is recoverable —
        # the same bytes are re-submitted to a surviving decode replica
        # with a `resume_skip` cursor stamped into the shipment meta
        # (the count of tokens already relayed to the caller), and the
        # replica's deterministic replay continues the stream exactly
        # where it stopped: zero re-prefill, zero duplicated or lost
        # tokens, no caller-visible error. Bounded by `max_resumes` and
        # the caller's riding deadline; once those run out the stream
        # ends with a terminal error frame + honest abrupt close.
        dstate = _ForwardState()
        resumes = 0
        delivered = 0           # whole-frame tokens already at the caller
        stream_started = False  # status+headers already on the wire
        served: list[str] = []
        active_shipment = shipment
        while True:
            # THE handoff-resume path: the prefill work is safe in the
            # router-held shipment, so a dead/draining decode target
            # costs one re-placement and ZERO re-prefill. One `dstate`
            # across resume iterations: a resumed stream keeps its
            # exclusions and does NOT get a fresh attempt budget.
            a = await self._forward_attempt(
                state=dstate, key=None, intent="decode", method="POST",
                path=decode_path, body=active_shipment,
                content_type="application/x-tpk-kv", trace_id=trace_id,
                deadline=deadline, read_body=not wants_stream,
                retry_reason="prefill_handoff", count_handoff=True)
            if a.kind == "no_replica":
                self._count(None, "no_replica")
                self.router._bump("errors")
                if stream_started:
                    self.router._bump("resume_failures")
                    await self._stream_error_close(
                        "no live decode replica to resume on")
                    return True
                self.set_header("Retry-After", "1")
                self.write_json({"error": "no live decode replica"},
                                status=503)
                return True
            if a.kind == "deadline":
                if stream_started:
                    self.router._bump("errors")
                    self.router._bump("resume_failures")
                    await self._stream_error_close(
                        "request deadline exceeded (router resume)")
                    return True
                raise tornado.web.HTTPError(
                    504, reason="request deadline exceeded (router)")
            if a.kind == "exhausted":
                if a.expired:
                    if stream_started:
                        self.router._bump("resume_failures")
                        await self._stream_error_close(
                            "request deadline exceeded (router resume)")
                        return True
                    raise tornado.web.HTTPError(
                        504, reason="request deadline exceeded "
                                    "(router retries)") from a.error
                if stream_started:
                    self.router._bump("resume_failures")
                    await self._stream_error_close(
                        f"decode replica {a.name} unreachable during "
                        f"resume: {a.error}")
                    return True
                raise tornado.web.HTTPError(
                    502, reason=f"decode replica {a.name} unreachable: "
                                f"{a.error}") from a.error
            if a.kind == "timeout":
                # The decode replica may still be generating: 504, no
                # replay (a replay would duplicate decode work).
                if stream_started:
                    self.router._bump("resume_failures")
                    await self._stream_error_close(
                        f"decode replica {a.name} timed out: {a.error}")
                    return True
                raise tornado.web.HTTPError(
                    504, reason=f"decode replica {a.name} timed out: "
                                f"{a.error}") from a.error
            dname, result, t0 = a.name, a.result, a.t0
            if not wants_stream:
                self.set_header(REPLICA_HEADER, dname)
                self.set_header(ATTEMPTS_HEADER,
                                str(pstate.attempts + dstate.attempts))
                try:
                    await self._relay(result, dname, trace_id, t0)
                finally:
                    self.fleet.checkin(dname)
                return True
            if stream_started and result.status != 200:
                # A resume attempt answered an error/shed AFTER the 200
                # status already went out — nothing left to forward it
                # as; terminal error frame.
                if result.conn is not None:
                    result.conn.close()
                self.fleet.checkin(dname)
                self._count(dname, "upstream_error")
                self.router._bump("errors")
                self.router._bump("resume_failures")
                await self._stream_error_close(
                    f"decode resume on {dname} answered "
                    f"{result.status}")
                return True
            if result.body is not None or result.status != 200:
                # Pre-stream shed/error from the FIRST attempt: relay it
                # verbatim (sheds forward as backpressure, errors as-is
                # — exactly the unified path's contract).
                self.set_header(REPLICA_HEADER, dname)
                self.set_header(ATTEMPTS_HEADER,
                                str(pstate.attempts + dstate.attempts))
                try:
                    await self._relay(result, dname, trace_id, t0)
                finally:
                    self.fleet.checkin(dname)
                return True
            if not stream_started:
                self.set_header(REPLICA_HEADER, dname)
                self.set_header(ATTEMPTS_HEADER,
                                str(pstate.attempts + dstate.attempts))
            prov = {"replicas": served + [dname], "resumes": resumes}
            try:
                status, delta, err, flushed = await self._relay_ndjson(
                    result, dname, trace_id, t0,
                    started=stream_started, prov=prov)
            except Exception:
                # Unexpected relay failure (executor shutdown, handler
                # teardown): the outstanding count must still release,
                # or this replica's load stays inflated and a drain on
                # it never completes.
                self.fleet.checkin(dname)
                raise
            # Committed only once bytes actually reached the caller: an
            # attempt that died pre-flush leaves the status line free,
            # so terminal failures can still answer a real 5xx.
            stream_started = stream_started or flushed
            delivered += delta
            served.append(dname)
            dt = time.perf_counter() - t0
            if status in ("done", "caller_gone"):
                self.fleet.checkin(dname)
                self.fleet.observe_forward(dname, dt)
                return True
            # Died mid-stream. A read timeout means the replica is
            # STALLED, not dead — no failure nudge (the gray-ejection
            # EWMA gets the latency evidence instead); anything else is
            # a death and counts toward the probe-failure trip.
            stalled = isinstance(err, TimeoutError)
            self.fleet.checkin(dname, failed=not stalled)
            self.fleet.observe_forward(dname, dt)
            self._count(dname, "upstream_error")
            expired = deadline is not None and deadline.expired()
            if resumes >= self.server.max_resumes or expired:
                self.router._bump("errors")
                self.router._bump("resume_failures")
                if expired:
                    res_metrics.inc("tpk_deadline_expired_total",
                                    component="router")
                msg = (f"decode replica {dname} died mid-stream and "
                       f"the resume budget is exhausted "
                       f"({resumes}/{self.server.max_resumes}): {err}")
                if stream_started:
                    await self._stream_error_close(msg)
                    return True
                # Nothing reached the caller yet: a real status beats
                # a 200 + error frame.
                raise tornado.web.HTTPError(504 if expired else 502,
                                            reason=msg)
            resumes += 1
            res_metrics.inc("tpk_router_resume_total",
                            reason="stall" if stalled else "death")
            self.router._bump("resumes")
            self._slo["resumes"] = resumes
            # The resume SEAM is a first-class trace event: a zero-
            # duration span on the router timeline marking where the
            # stream crossed replicas — the assembled distributed trace
            # shows the kill and the continuation either side of it.
            t_seam = time.perf_counter()
            obs.record("router.resume", t_seam, t_seam,
                       trace_id=trace_id, from_replica=dname,
                       delivered=delivered,
                       reason="stall" if stalled else "death")
            self.server.flight_recorder.snapshot(
                f"resume:{dname}", trace_id=trace_id,
                cause="stall" if stalled else "death",
                delivered=delivered, resumes=resumes)
            dstate.exclude.add(dname)
            # Stamp the cursor on the ORIGINAL held bytes (idempotent —
            # each resume restates the full delivered count; the trace
            # stamp above rides along, rewrite_meta splices into the
            # already-stamped shipment).
            active_shipment = rewrite_meta(shipment,
                                           resume_skip=delivered,
                                           trace=trace_id)

    async def _stream_error_close(self, msg: str) -> None:
        """Terminal error envelope for an already-started ndjson stream,
        followed by an honest ABRUPT close: the envelope names the
        failure for clients that parse frames, the missing terminator
        keeps the truncation visible to clients that don't."""
        slo = getattr(self, "_slo", None)
        if slo is not None and not slo["reason"]:
            slo["reason"] = msg
        try:
            self.write(json.dumps({"error": msg}) + "\n")
            await self.flush()
        except Exception:
            pass
        try:
            self.request.connection.stream.close()
        except Exception:
            pass

    async def _relay_ndjson(
            self, result: _ForwardResult, name: str, trace_id: str,
            t0: float, *, started: bool,
            prov: dict) -> tuple[str, int, Exception | None, bool]:
        """Relay one decode replica's x-ndjson token stream LINE
        BUFFERED: only COMPLETE frames reach the caller (a death
        mid-frame must not deliver a torn line — the resume cursor
        counts tokens from whole frames, so router-delivered and
        replica-skipped counts always agree), tokens are tallied as
        frames pass, and the terminal done frame is enriched with the
        router's provenance (`_router`: serving replicas + resume
        count) so load harnesses get per-request truth. Returns
        (status, delivered_tokens, err, flushed) with status one of
        "done" (terminal frame relayed), "caller_gone" (client
        disconnected), "died" (upstream ended without a done frame);
        `flushed` reports whether any bytes actually reached the
        caller's socket — an attempt that died before flushing leaves
        the response UNCOMMITTED, so a later terminal failure can still
        answer a proper 5xx instead of a 200 + error frame."""
        loop = asyncio.get_event_loop()
        if not started:
            self.set_status(result.status)
            hdrs = dict(result.headers or ())
            for h in _FORWARD_RESP_HEADERS:
                if h in hdrs:
                    self.set_header(h, hdrs[h])
        conn, resp = result.conn, result.resp
        delivered = 0
        done = False
        flushed = False
        err: Exception | None = None
        buf = b""
        try:
            while not done:
                try:
                    chunk = await loop.run_in_executor(
                        self.server.executor, resp.read1, 65536)
                except (OSError, http.client.HTTPException) as e:
                    err = e
                    break
                if not chunk:
                    break
                buf += chunk
                out: list[bytes] = []
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        out.append(line + b"\n")
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        out.append(line + b"\n")
                        continue
                    if isinstance(ev, dict):
                        delivered += len(ev.get("tokens") or ())
                        if ev.get("done"):
                            done = True
                            ev["_router"] = dict(prov)
                            out.append(json.dumps(ev).encode() + b"\n")
                            break
                    out.append(line + b"\n")
                if out:
                    self.write(b"".join(out))
                    try:
                        await self.flush()
                        flushed = True
                        self._observe_flush()
                    except tornado.iostream.StreamClosedError:
                        self._count(name, "ok")
                        self.router._bump("ok")
                        return ("caller_gone", delivered, None, flushed)
        finally:
            conn.close()
        if done:
            self._count(name, "ok")
            self.router._bump("ok")
            obs.record("router.forward", t0, time.perf_counter(),
                       trace_id=trace_id, replica=name,
                       status=result.status)
            try:
                self.finish()
            except tornado.iostream.StreamClosedError:
                pass
            return ("done", delivered, None, True)
        if err is None:
            err = RuntimeError("upstream closed before the done frame")
        obs.record("router.forward", t0, time.perf_counter(),
                   trace_id=trace_id, replica=name,
                   error=str(err)[:120])
        return ("died", delivered, err, flushed)

    async def _relay(self, result: _ForwardResult, name: str,
                     trace_id: str, t0: float) -> None:
        """Stream one upstream response back to the caller."""
        loop = asyncio.get_event_loop()
        self.set_status(result.status)
        hdrs = dict(result.headers or ())
        for h in _FORWARD_RESP_HEADERS:
            if h in hdrs:
                self.set_header(h, hdrs[h])
        if result.body is not None:  # fully-read (non-stream) response
            if result.status == 503:
                outcome, stat = "shed", "sheds_forwarded"
            elif result.status >= 500:
                outcome, stat = "upstream_error", "errors"
            else:
                outcome, stat = "ok", "ok"
            self._count(name, outcome)
            self.router._bump(stat)
            self.fleet.observe_forward(name,
                                       time.perf_counter() - t0)
            obs.record("router.forward", t0, time.perf_counter(),
                       trace_id=trace_id, replica=name,
                       status=result.status)
            self.finish(result.body)
            if result.status < 400:
                # Non-streamed content: the one body flush IS the first
                # content frame (sheds/errors are accounted by the e2e
                # outcome histogram, not TTFT).
                self._observe_flush()
            return
        conn, resp = result.conn, result.resp
        outcome = "ok" if result.status < 500 else "upstream_error"
        upstream_err = None
        try:
            while True:
                try:
                    # read1: at most ONE chunk per hop. read(amt) on a
                    # chunked response accumulates until `amt` bytes or
                    # end-of-stream — it would buffer a whole token
                    # stream and deliver it at EOF.
                    chunk = await loop.run_in_executor(
                        self.server.executor, resp.read1, 65536)
                except (OSError, http.client.HTTPException) as e:
                    # Replica died mid-stream — exactly the fleet event
                    # the counters exist to surface.
                    upstream_err = e
                    outcome = "upstream_error"
                    break
                if not chunk:
                    break
                self.write(chunk)
                try:
                    await self.flush()
                    if result.status < 400:
                        self._observe_flush()
                except tornado.iostream.StreamClosedError:
                    break  # caller went away; stop pulling
            self._count(name, outcome)
            self.router._bump("ok" if outcome == "ok" else "errors")
            self.fleet.observe_forward(name,
                                       time.perf_counter() - t0)
            obs.record("router.forward", t0, time.perf_counter(),
                       trace_id=trace_id, replica=name,
                       status=result.status,
                       **({"error": str(upstream_err)[:120]}
                          if upstream_err is not None else {}))
            if upstream_err is not None:
                # Headers (and chunks) are already on the wire: the
                # abrupt close below stays the honest truncation signal
                # — but where the surface has an in-band error envelope
                # (ndjson frames, SSE events), write one terminal error
                # frame first so parsing clients see the failure named
                # instead of a bare connection reset (ISSUE 14).
                ct = hdrs.get("Content-Type") or ""
                msg = (f"upstream replica {name} died mid-stream: "
                       f"{type(upstream_err).__name__}")
                frame = None
                # Leading newline: this relay forwards RAW chunks, so
                # the upstream may have died mid-line — appending the
                # envelope straight after a torn partial line would
                # make it unparseable to exactly the line-parsing
                # clients it exists for (blank lines are skipped by
                # both surfaces' parsers).
                if ct.startswith("application/x-ndjson"):
                    frame = "\n" + json.dumps({"error": msg}) + "\n"
                elif ct.startswith("text/event-stream"):
                    frame = "\n\ndata: " + json.dumps(
                        {"error": {"message": msg}}) + "\n\n"
                if frame is not None:
                    try:
                        self.write(frame)
                        await self.flush()
                    except Exception:
                        pass
                try:
                    self.request.connection.stream.close()
                except Exception:
                    pass
            else:
                try:
                    self.finish()
                except tornado.iostream.StreamClosedError:
                    pass
        finally:
            conn.close()


class AdminReplicasHandler(_RouterBase):
    def get(self):
        self.write_json({
            "replicas": self.fleet.snapshot(),
            "router": self.router.stats_snapshot(),
        })

    def post(self):
        try:
            body = json.loads(self.request.body or b"{}")
        except json.JSONDecodeError as e:
            raise tornado.web.HTTPError(400, reason=f"bad JSON: {e}") \
                from None
        name, url = body.get("name"), body.get("url")
        if not name or not url:
            raise tornado.web.HTTPError(
                400, reason="replica registration needs name and url")
        try:
            self.fleet.add(name, url, grpc=body.get("grpc"),
                           role=body.get("role", "any"))
        except ValueError as e:
            raise tornado.web.HTTPError(400, reason=str(e)) from None
        self.write_json({"added": name})


class AdminReplicaHandler(_RouterBase):
    def delete(self, name):
        self.fleet.remove(name)
        self.write_json({"removed": name})


class AdminDrainHandler(_RouterBase):
    def post(self, name):
        if not self.fleet.drain(name):
            raise tornado.web.HTTPError(
                404, reason=f"replica {name!r} not found")
        self.write_json({"draining": name})


class RouterMetricsHandler(_RouterBase):
    def get(self):
        self.set_header("Content-Type", "text/plain; version=0.0.4")
        self.finish(res_metrics.prometheus_text())


class FleetMetricsHandler(_RouterBase):
    """GET /fleet/metrics — ONE exposition for the whole fleet, merged
    from the poller's already-scraped per-replica documents (zero extra
    scrape traffic: aggregation rides the poll the fleet already pays
    for). Counters sum, gauges keep a `replica` label, same-bucket
    histograms sum bucket-wise; incompatible families answer 500 —
    refusal is the contract, silent merging never happens."""

    def get(self):
        texts = self.fleet.metrics_texts()
        try:
            merged = merge_prometheus_texts(texts)
        except MetricsMergeError as e:
            self.write_json(
                {"error": f"fleet metrics merge refused: {e}"},
                status=500)
            return
        self.set_header("Content-Type", "text/plain; version=0.0.4")
        self.finish(merged)


class FlightRecorderHandler(_RouterBase):
    """GET /admin/flightrecorder[?n=K] — the per-request outcome ring
    (most recent last) plus the chaos snapshots frozen at resume/eject
    events. Bounded by construction: `capacity` records, ever."""

    def get(self):
        raw = self.get_query_argument("n", default=None)
        n = None
        if raw is not None:
            try:
                n = int(raw)
            except ValueError:
                raise tornado.web.HTTPError(
                    400, reason=f"n must be an integer, got {raw!r}") \
                    from None
        fr = self.server.flight_recorder
        self.write_json({"records": fr.tail(n),
                         "snapshots": fr.snapshots(),
                         "capacity": fr.capacity})


class RouterTraceHandler(_RouterBase):
    """GET /debug/trace[?trace_id=] — without a trace id, this process's
    own span ring (the ISSUE-5 behavior, unchanged). WITH one:
    distributed assembly — fan out to the replicas on that request's
    flight-recorder trail (the whole fleet when the trail is unknown),
    pull each ring over the same per-replica /debug/trace surface,
    estimate each replica's clock offset from the fetch RTT midpoint,
    and serve ONE merged Chrome trace: router place/forward spans,
    prefill chunks, the shipment hop, decode chunks, and the resume
    seam on a single timeline, with the alignment error bars stated."""

    async def get(self):
        tid = self.get_query_argument("trace_id", default=None)
        if tid is None:
            self.write_json(obs.get_tracer().chrome_trace(None))
            return
        tid = obs.sanitize_trace_id(tid)
        rec = self.server.flight_recorder.lookup(tid)
        names = list((rec or {}).get("replicas") or self.fleet.names())
        loop = asyncio.get_event_loop()
        fetches = []
        for name in dict.fromkeys(names):
            url = self.fleet.url_of(name)
            if url is not None:
                fetches.append(loop.run_in_executor(
                    self.server.executor, self._fetch_replica_trace,
                    name, url, tid))
        results = await asyncio.gather(*fetches) if fetches else []
        parts = [{"process": "router",
                  "doc": obs.get_tracer().chrome_trace(tid),
                  "offset_us": 0.0, "err_us": 0.0}]
        unreachable = []
        for name, doc, offset_us, err_us, err in results:
            if err is not None:
                # A dead replica's ring died with it — say so instead
                # of silently serving a partial trace as complete.
                unreachable.append({"replica": name, "error": err})
                continue
            parts.append({"process": name, "doc": doc,
                          "offset_us": offset_us, "err_us": err_us})
        merged = obs.merge_chrome_traces(parts)
        merged["trace_id"] = tid
        if rec is not None:
            merged["flight_record"] = rec
        if unreachable:
            merged["unreachable"] = unreachable
        self.write_json(merged)

    def _fetch_replica_trace(self, name: str, url: str, tid: str):
        """One blocking per-replica ring fetch (executor only) + the
        RTT-midpoint clock-offset estimate: the replica stamps its own
        `now_us` while serving the fetch, which on OUR timeline happened
        ~at the fetch midpoint — so offset = our_midpoint - its_now,
        with half the RTT as the honest error bar. Returns
        (name, doc, offset_us, err_us, error)."""
        t0 = time.perf_counter()
        try:
            # tid came through sanitize_trace_id: URL-safe charset.
            with urllib.request.urlopen(
                    f"{url}/debug/trace?trace_id={tid}",
                    timeout=self.server.trace_timeout_s) as r:
                doc = json.loads(r.read().decode())
        except Exception as e:
            return name, None, 0.0, None, f"{type(e).__name__}: {e}"
        t1 = time.perf_counter()
        now_us = doc.get("now_us") if isinstance(doc, dict) else None
        if now_us is None:
            # Older replica without the export stamp: spans ride
            # un-shifted, marked unaligned in clock_alignment.
            return name, doc, 0.0, None, None
        mid_us = obs.perf_to_us((t0 + t1) / 2.0)
        return (name, doc, mid_us - float(now_us),
                (t1 - t0) / 2.0 * 1e6, None)


class RouterServer:
    """Hosts the proxy + admin plane; same lifecycle shape as
    ModelServer (daemon-thread ioloop, worker executor for blocking
    upstream I/O)."""

    def __init__(self, fleet: Fleet | None = None, *,
                 affinity: bool = True, spill_margin: float = 4.0,
                 forward_timeout_s: float = 300.0,
                 max_resumes: int = 3,
                 max_workers: int = 128,
                 trace_timeout_s: float = 5.0):
        self.fleet = fleet or Fleet()
        self.router = Router(self.fleet, affinity=affinity,
                             spill_margin=spill_margin)
        self.forward_timeout_s = float(forward_timeout_s)
        #: Per-replica budget for the distributed-trace fan-out fetch
        #: (a dead replica must not wedge assembly of everyone else's
        #: spans — it lands in the `unreachable` list instead).
        self.trace_timeout_s = float(trace_timeout_s)
        #: Per-request outcome ring (+ chaos snapshots). The fleet's
        #: eject transitions freeze a snapshot so postmortems keep the
        #: requests surrounding an ejection.
        self.flight_recorder = obs.FlightRecorder()
        self.fleet.on_transition = self._on_fleet_transition
        #: Mid-stream decode-failover cap (ISSUE 14): how many times one
        #: disaggregated stream may be resumed on a fresh decode replica
        #: before the router gives up with a terminal error frame.
        self.max_resumes = int(max_resumes)
        # One worker is HELD for the whole upstream round trip of one
        # in-flight request (blocking http.client forward), so the pool
        # must cover peak CONCURRENT requests, not CPU count — the
        # workers spend their lives in network waits. Threads are lazy;
        # an idle router allocates none of them.
        self.executor = ThreadPoolExecutor(
            max_workers=int(max_workers),
            thread_name_prefix="tpk-router-fwd")
        self._loop: tornado.ioloop.IOLoop | None = None
        self._thread: threading.Thread | None = None
        self.port: int | None = None
        self._grpc = None
        self.grpc_port: int | None = None

    def _on_fleet_transition(self, name: str, kind: str) -> None:
        if kind == "eject":
            self.flight_recorder.snapshot(f"eject:{name}", replica=name)

    def app(self) -> tornado.web.Application:
        kw = {"server": self}
        return tornado.web.Application([
            (r"/admin/replicas", AdminReplicasHandler, kw),
            (r"/admin/replicas/([^/]+)", AdminReplicaHandler, kw),
            (r"/admin/drain/([^/]+)", AdminDrainHandler, kw),
            (r"/admin/flightrecorder", FlightRecorderHandler, kw),
            (r"/metrics", RouterMetricsHandler, kw),
            (r"/fleet/metrics", FleetMetricsHandler, kw),
            (r"/debug/trace", RouterTraceHandler, kw),
            (r"/(.*)", ProxyHandler, kw),
        ])

    def start_grpc(self, port: int = 0) -> int:
        from kubeflow_tpu.serve.grpc_router import build_grpc_router

        self._grpc, self.grpc_port = build_grpc_router(self, port)
        self._grpc.start()
        return self.grpc_port

    def _serve(self, port: int, ready: threading.Event) -> None:
        asyncio.set_event_loop(asyncio.new_event_loop())
        self._loop = tornado.ioloop.IOLoop.current()
        sockets = tornado.netutil.bind_sockets(port, address="127.0.0.1")
        server = tornado.httpserver.HTTPServer(self.app())
        server.add_sockets(sockets)
        self.port = sockets[0].getsockname()[1]
        ready.set()
        self._loop.start()

    def start_background(self, port: int = 0) -> int:
        ready = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, args=(port, ready), daemon=True,
            name="tpk-router")
        self._thread.start()
        if not ready.wait(10.0):
            raise TimeoutError("router failed to bind")
        assert self.port is not None
        return self.port

    def run(self, port: int) -> None:
        self._serve(port, threading.Event())

    def stop(self) -> None:
        if self._grpc is not None:
            self._grpc.stop(grace=1.0).wait(1.5)
        if self._loop is not None:
            self._loop.add_callback(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.executor.shutdown(wait=False)
        self.fleet.close()


def main(argv: list[str] | None = None) -> int:
    """`tpk-router` entrypoint: front a static replica list (grow/shrink
    later through the admin endpoint or the autoscaler)."""
    import argparse

    p = argparse.ArgumentParser(prog="tpk-router")
    p.add_argument("--port", type=int, default=8090)
    p.add_argument("--grpc-port", type=int, default=None)
    p.add_argument("--replica", action="append", default=[],
                   metavar="NAME=URL[,GRPC][,role=ROLE]",
                   help="replica registration (repeatable); role is "
                        "any|prefill|decode (disaggregated fleets)")
    p.add_argument("--no-affinity", action="store_true",
                   help="disable prefix/adapter affinity (least-loaded "
                        "only; the A/B control)")
    p.add_argument("--spill-margin", type=float, default=4.0)
    args = p.parse_args(argv)

    server = RouterServer(affinity=not args.no_affinity,
                          spill_margin=args.spill_margin)
    for spec in args.replica:
        name, _, rest = spec.partition("=")
        if not rest:
            p.error(f"--replica must be NAME=URL[,GRPC][,role=ROLE], "
                    f"got {spec!r}")
        url, _, tail = rest.partition(",")
        grpc, role = None, "any"
        for part in (tail.split(",") if tail else []):
            if part.startswith("role="):
                role = part[len("role="):]
            elif part:
                grpc = part
        server.fleet.add(name, url, grpc=grpc, role=role)
    if args.grpc_port is not None:
        bound = server.start_grpc(args.grpc_port)
        print(json.dumps({"event": "router_grpc", "port": bound}),
              flush=True)
    print(json.dumps({"event": "router_serving", "port": args.port,
                      "replicas": server.fleet.names()}), flush=True)
    server.run(args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
