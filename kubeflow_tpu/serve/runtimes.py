"""Serving runtimes — the ServingRuntime/ClusterServingRuntime equivalent.

The reference resolves `modelFormat` → container recipe through ServingRuntime
CRs (⟨kserve: pkg/apis/serving/v1alpha1 — ServingRuntime⟩, SURVEY.md §2.2,
§5.6). Here a runtime is a Python builder `fn(model_dir, spec) -> Model`,
registered by format name; an exported model directory carries a `model.json`
naming its format, so `load_model(dir)` is the whole resolution path.

Model directory layout (produced by `export_for_serving`):
    model.json   {"format": "jax-registry", "model": "...", "model_kwargs": {},
                  "batch_buckets": [...], "seed": 0}
    params/      orbax params-only checkpoint (optional; init from seed if absent)
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

import jax
import numpy as np

from kubeflow_tpu.serve.model import JAXModel, Model

_RUNTIMES: dict[str, Callable[[str, dict], Model]] = {}


def register_runtime(fmt: str):
    def deco(fn):
        _RUNTIMES[fmt] = fn
        return fn
    return deco


def list_runtimes() -> list[str]:
    return sorted(_RUNTIMES)


def load_model(model_dir: str, name: str | None = None,
               mesh: dict | None = None) -> Model:
    """Resolve model.json's format to a runtime and build the Model.

    `mesh` ({"tensor": N, ...}) overrides the bundle's device-mesh spec —
    the ISVC `model.mesh` field lands here via the server's --mesh flag,
    turning a single-device generative bundle into tensor-parallel
    serving without touching the bundle."""
    spec_path = os.path.join(model_dir, "model.json")
    with open(spec_path) as f:
        spec = json.load(f)
    if mesh:
        gen = spec.get("generative")
        if not gen:
            raise ValueError(
                "a mesh override requires a generative bundle (fixed-"
                "forward models replicate per replica instead)")
        spec = {**spec, "generative": {**gen, "mesh": dict(mesh)}}
    fmt = spec.get("format", "jax-registry")
    try:
        builder = _RUNTIMES[fmt]
    except KeyError:
        raise ValueError(
            f"no serving runtime for format {fmt!r}; have {list_runtimes()}"
        ) from None
    model = builder(model_dir, spec)
    if name:
        model.name = name
    if spec.get("explainer"):
        from kubeflow_tpu.serve.explain import build_explainer

        attach = getattr(model, "attach_explainer", None)
        if attach is None:
            raise ValueError(
                f"runtime {fmt!r} model does not support explainers")
        attach(build_explainer(spec["explainer"]))
    return model


def export_for_serving(model_dir: str, *, model: str, params: Any = None,
                       model_kwargs: dict | None = None,
                       batch_buckets=(1, 2, 4, 8, 16, 32),
                       seed: int = 0, extra: dict | None = None) -> str:
    """Writes the serving bundle: model.json + optional orbax params.

    The training side calls this after fine-tuning (the analog of pushing a
    trained model to the KServe storage bucket)."""
    import flax.linen as nn
    import orbax.checkpoint as ocp

    os.makedirs(model_dir, exist_ok=True)
    spec = {"format": "jax-registry", "model": model,
            "model_kwargs": model_kwargs or {},
            "batch_buckets": list(batch_buckets), "seed": seed}
    spec.update(extra or {})
    with open(os.path.join(model_dir, "model.json"), "w") as f:
        json.dump(spec, f, indent=1)
    if params is not None:
        path = os.path.join(os.path.abspath(model_dir), "params")
        with ocp.StandardCheckpointer() as ckptr:
            # Strip flax logical-partitioning boxes: the bundle stores plain
            # arrays; serving re-shards (or replicates) at load time.
            ckptr.save(path, nn.meta.unbox(params))
    return model_dir


@register_runtime("jax-registry")
def _jax_registry_runtime(model_dir: str, spec: dict) -> Model:
    """Builds a JAXModel from the model zoo + optional orbax params."""
    from kubeflow_tpu.utils import registry

    module, info = registry.build_model(spec["model"],
                                        **spec.get("model_kwargs", {}))
    example_shape = tuple(info["example_shape"][1:])
    dtype = info.get("example_dtype", "float32")

    params_dir = os.path.join(os.path.abspath(model_dir), "params")
    if os.path.isdir(params_dir):
        import orbax.checkpoint as ocp
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore(params_dir)
    else:  # no trained weights: init from the recorded seed (tests, smoke)
        import flax.linen as nn
        rng = jax.random.key(spec.get("seed", 0))
        example = np.zeros((1, *example_shape), dtype=dtype)
        params = nn.meta.unbox(module.init(rng, example)["params"])

    module, params = _maybe_quantize(module, params, spec)

    if spec.get("generative"):
        # LLM bundle: KV-cache decode engine instead of a fixed forward
        # (⟨kserve: python/huggingfaceserver⟩ equivalent; generation.py).
        from kubeflow_tpu.serve.generation import GenerativeJAXModel

        return GenerativeJAXModel(
            spec.get("name") or spec["model"], module, params,
            info.get("config"), generation=dict(spec["generative"]))

    def apply_fn(params, x):
        out = module.apply({"params": params}, x)
        return out[-1] if isinstance(out, tuple) else out

    return JAXModel(
        spec.get("name") or spec["model"], apply_fn, params,
        input_spec=[(example_shape, dtype)],
        batch_buckets=spec.get("batch_buckets", (1, 2, 4, 8, 16, 32)),
        warm_buckets=spec.get("warm_buckets", (1, 8)))


def _maybe_quantize(module, params, spec: dict):
    """spec.quantize == "int8" → weight-only int8 storage (serve/quant.py),
    transparent to the model via QuantizedModule."""
    mode = spec.get("quantize")
    if not mode:
        return module, params
    if mode != "int8":
        raise ValueError(f"unsupported quantize mode {mode!r} (have: int8)")
    from kubeflow_tpu.serve.quant import QuantizedModule, quantize_tree

    return QuantizedModule(module), quantize_tree(params)


@register_runtime("huggingface")
def _huggingface_runtime(model_dir: str, spec: dict) -> Model:
    """HF safetensors checkpoint → native JAX model (the huggingfaceserver
    equivalent; models/hf_import.py). The bundle is the HF directory itself
    plus a model.json {"format": "huggingface"}; `checkpoint` may point at a
    subdirectory or an absolute path, default the bundle dir.

    Llama-family checkpoints serve generatively when the spec carries a
    `generative` block (KV-cache engine), else as a full-forward logits
    model; BERT checkpoints serve as classifiers (pooled logits).
    """
    from kubeflow_tpu.models.bert import Bert
    from kubeflow_tpu.models.hf_import import build_from_hf, read_hf_config
    from kubeflow_tpu.models.t5 import T5

    ckpt = spec.get("checkpoint") or "."
    if not os.path.isabs(ckpt):
        ckpt = os.path.join(os.path.abspath(model_dir), ckpt)
    overrides = dict(spec.get("model_overrides") or {})
    module, cfg, params = build_from_hf(ckpt, **overrides)
    adapter = spec.get("peft_adapter")
    if adapter:
        # PEFT LoRA adapter dir (tuned here via spec.lora or elsewhere
        # via HF peft): overlay onto the base and FOLD FLAT — the engine
        # serves a plain base tree, zero changes downstream
        # (models/peft_import.py; exactness tested vs the peft-wrapped
        # torch model).
        if not os.path.isabs(adapter):
            adapter = os.path.join(os.path.abspath(model_dir), adapter)
        from kubeflow_tpu.models.peft_import import attach_peft_adapter
        from kubeflow_tpu.train.lora import merge

        acfg, aparams = attach_peft_adapter(adapter, cfg, params)
        params = merge(aparams, acfg)
    is_bert = isinstance(module, Bert)  # before the quantize wrapper
    is_t5 = isinstance(module, T5)
    module, params = _maybe_quantize(module, params, spec)
    name = spec.get("name") or os.path.basename(os.path.abspath(model_dir))

    if is_t5:
        # Encoder-decoder → the text2text task (whole-decode-as-one-
        # program greedy generation; serve/text2text.py).
        from kubeflow_tpu.serve.text2text import Text2TextJAXModel

        gen = dict(spec.get("generative") or {})
        if "tokenizer" not in gen:
            from kubeflow_tpu.serve.tokenizer_util import \
                load_bundled_tokenizer

            tok = load_bundled_tokenizer(ckpt, name)
            if tok is not None:
                gen["tokenizer"] = tok
        return Text2TextJAXModel(name, module, params, cfg,
                                 generation=gen)

    if is_bert:
        # Pad tokens must not enter attention: the mask is derived from the
        # checkpoint's pad_token_id (HF tokenizers right-pad with it), so a
        # single-input v1/v2 request with padded rows scores identically to
        # the reference server.
        pad_id = int(read_hf_config(ckpt).get("pad_token_id") or 0)

        def apply_fn(params, input_ids):
            _, logits = module.apply({"params": params}, input_ids,
                                     attention_mask=input_ids != pad_id)
            return logits

        seq = int(spec.get("seq_len", min(cfg.max_seq_len, 128)))
        return JAXModel(
            name, apply_fn, params, input_spec=[((seq,), "int32")],
            batch_buckets=spec.get("batch_buckets", (1, 2, 4, 8, 16, 32)),
            warm_buckets=spec.get("warm_buckets", (1, 8)))

    if spec.get("generative"):
        from kubeflow_tpu.serve.generation import GenerativeJAXModel

        gen = dict(spec["generative"])
        if gen.get("adapters"):
            # Multi-LoRA: {name: PEFT adapter dir}, relative to the
            # bundle like `checkpoint`.
            gen["adapters"] = {
                k: (v if os.path.isabs(v)
                    else os.path.join(os.path.abspath(model_dir), v))
                for k, v in dict(gen["adapters"]).items()}
        # Bundle the checkpoint's own tokenizer when present (vLLM-parity
        # text in/out + streaming text deltas): generation then accepts
        # "text" and returns decoded "text"; eos defaults to the
        # tokenizer's unless the spec pins one.
        if "tokenizer" not in gen:
            from kubeflow_tpu.serve.tokenizer_util import \
                load_bundled_tokenizer

            tok = load_bundled_tokenizer(ckpt, name)
            if tok is not None:
                gen["tokenizer"] = tok
                if tok.eos_token_id is not None:
                    gen.setdefault("eos_id", int(tok.eos_token_id))
        return GenerativeJAXModel(name, module, params, cfg,
                                  generation=gen)

    def apply_fn(params, tokens):
        return module.apply({"params": params}, tokens)

    seq = int(spec.get("seq_len", 128))
    return JAXModel(
        name, apply_fn, params, input_spec=[((seq,), "int32")],
        batch_buckets=spec.get("batch_buckets", (1, 2, 4, 8)),
        warm_buckets=spec.get("warm_buckets", (1,)))
