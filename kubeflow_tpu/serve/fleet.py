"""Replica fleet: the router's model of N engine replicas (ISSUE 9).

The front-door router (serve/router.py) places requests over a set of
model-server replicas. This module owns everything about those replicas
EXCEPT placement itself:

  * **Replica table.** One record per replica — address, ready/draining
    state, the live load signals placement reads, last-scrape age,
    consecutive probe failures. All mutation is lock-guarded; the router
    reads immutable snapshots.
  * **Background scrape poller.** Load signals come from the replicas'
    EXISTING metrics surface (no new replica API): the gRPC
    `/tpk.Metrics/Prometheus` method when a replica registers a gRPC
    address, HTTP `GET /metrics` otherwise — parsed for
    `tpk_decode_inflight_depth`, `tpk_kv_blocks_free`, and
    `tpk_serve_inflight` (admission occupancy). Readiness rides the
    same poll (`/v2/health/ready`, which the ISSUE-1 degradation states
    already feed). Scraping happens HERE, fanned out on the poller's
    scrape pool, never on the placement path — placement reads cached
    numbers.
  * **Draining.** `drain(name)` removes a replica from placement
    immediately; the poller watches the replica's router-tracked
    outstanding count AND its scraped in-flight gauges reach zero, then
    fires the drain callback exactly once (scale-in retires the process
    there). In-flight requests are never cut.
  * **Autoscaling.** `FleetAutoscaler` closes the control loop: router
    shed rate and fleet occupancy high/low-water drive scale-out
    callbacks and drain-then-retire scale-in. The controlplane flavor
    (`ControlPlaneScaler`) reconciles by patching the InferenceService
    `spec.replicas` through the C++ store — complementing the existing
    scale-to-zero ISVC (examples/inference_service_scale_to_zero.yaml).
"""

from __future__ import annotations

import json
import random
import statistics
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import wait as futures_wait

from kubeflow_tpu.utils.resilience import metrics as res_metrics

#: Replica states. `starting` = registered, not yet probed; optimistic —
#: placement may try it (a connect failure retries elsewhere and the
#: poller downgrades it). `down` = N consecutive probe failures.
#: `slow` = GRAY-FAILURE ejection (ISSUE 14): the replica answers
#: probes — it is ALIVE — but its forward-latency / probe-RTT EWMA is a
#: statistical outlier against the rest of the fleet, so placement
#: routes around it while its in-flight work drains normally; it
#: rejoins after the half-open probes show it recovered. `draining` =
#: no new placements; `drained` = drain completed (nothing in flight
#: anywhere), safe to retire.
STATES = ("starting", "ready", "slow", "draining", "drained", "down")

#: Consecutive probe failures before a replica is marked down.
DOWN_AFTER_FAILURES = 3

#: Replica roles (disaggregated prefill/decode, ISSUE 13). "any" is the
#: unified default; role-split fleets register prefill-heavy and
#: decode-heavy replicas and the router runs the two-phase handoff.
REPLICA_ROLES = ("any", "prefill", "decode")

#: Placement intent → replica roles that can serve it. `None`
#: (metadata/control traffic, and every load accessor's default) spans
#: every role; a FULL generate needs a replica that runs both phases
#: ("generate"); the split intents take their phase's specialists plus
#: unified replicas.
INTENT_ROLES = {None: REPLICA_ROLES,
                "generate": ("any",),
                "prefill": ("any", "prefill"),
                "decode": ("any", "decode")}

#: Drain-completion grace for replicas that expose NO in-flight gauge
#: (admission disabled / non-generative): their own traffic is
#: unobservable, so the drain holds this long past drain start instead
#: of completing on the first poll (see _quiesced_locked).
DRAIN_UNOBSERVED_GRACE_S = 5.0


class Replica:
    """One replica's record. Instances are internal to the Fleet (mutated
    under its lock); the router sees `snapshot()` copies."""

    __slots__ = ("name", "url", "grpc", "role", "state", "outstanding",
                 "decode_inflight", "admission_inflight", "kv_blocks_free",
                 "last_scrape", "scrape_failures", "on_drained",
                 "draining_since", "probe_ready",
                 "fwd_ewma", "fwd_last", "probe_rtt_ewma",
                 "probe_rtt_last", "slow_strikes", "slow_since",
                 "scrape_seq", "metrics_text")

    def __init__(self, name: str, url: str, grpc: str | None = None,
                 role: str = "any"):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"replica role {role!r}: must be one of {REPLICA_ROLES}")
        self.name = name
        self.url = url.rstrip("/")
        self.grpc = grpc
        #: Disaggregation role (ISSUE 13): "any" serves every surface
        #: (the unified default); "prefill"/"decode" replicas only take
        #: their phase's placements — the router keys placement intents
        #: against this.
        self.role = role
        self.state = "starting"
        #: Router-owned live count of requests this process has in
        #: flight against the replica — fresher than any scrape.
        self.outstanding = 0
        # Scraped load signals (None until the first successful scrape).
        self.decode_inflight: float | None = None
        self.admission_inflight: float | None = None
        self.kv_blocks_free: float | None = None
        self.last_scrape: float | None = None
        self.scrape_failures = 0
        #: Raw exposition text of the last successful scrape — the
        #: router's /fleet/metrics merges these cached documents, so
        #: fleet aggregation piggybacks on the poll it already pays for
        #: (no second scrape storm).
        self.metrics_text: str | None = None
        self.on_drained = None
        self.draining_since: float | None = None
        #: Gray-failure signals (ISSUE 14): EWMA of router-observed
        #: forward latency and of probe round-trips, plus the ejection
        #: hysteresis bookkeeping (consecutive outlier passes before
        #: `slow`, and when the ejection happened).
        self.fwd_ewma: float | None = None
        self.fwd_last: float | None = None
        self.probe_rtt_ewma: float | None = None
        self.probe_rtt_last: float | None = None
        self.slow_strikes = 0
        self.slow_since: float | None = None
        #: Highest poll-pass sequence whose scrape has applied — a
        #: straggler from an OLDER pass landing late must not overwrite
        #: fresher state (see poll_once's bounded wait).
        self.scrape_seq = 0
        #: Last readiness-probe answer (None until first probe). False
        #: = the replica itself degraded (ISSUE-1 shedding window, an
        #: out-of-band drain): placement routes around it until the
        #: probe recovers — the KServe "route around a saturated
        #: replica" semantics, fleet-side.
        self.probe_ready: bool | None = None

    def load(self) -> float:
        """The placement load score: requests this router has riding on
        the replica plus the replica's own reported concurrency. The
        admission gauge already counts every request that is decoding,
        so the two scraped signals combine with max() — summing them
        double-counted each generative request, which made spill_margin
        and capacity_per_replica operate in ~3x-inflated units.
        `outstanding` IS still added on top: it is fresher than any
        scrape and covers requests the last scrape predates, at the
        cost of briefly double-counting this router's already-admitted
        traffic. Unscraped signals count 0 — a brand-new replica looks
        idle, which is what drains traffic toward it."""
        return self.outstanding + max(self.decode_inflight or 0,
                                      self.admission_inflight or 0)

    def placeable(self) -> bool:
        return (self.state in ("starting", "ready")
                and self.probe_ready is not False)

    def serves(self, intent: str | None) -> bool:
        return self.role in INTENT_ROLES[intent]

    def view(self) -> dict:
        """JSON-safe copy for admin/CLI surfaces."""
        return {
            "name": self.name, "url": self.url, "grpc": self.grpc,
            "role": self.role,
            "state": self.state, "ready": self.probe_ready,
            "outstanding": self.outstanding,
            "decode_inflight": self.decode_inflight,
            "admission_inflight": self.admission_inflight,
            "kv_blocks_free": self.kv_blocks_free,
            "scrape_age_s": (None if self.last_scrape is None
                             else round(time.monotonic() - self.last_scrape,
                                        3)),
            "scrape_failures": self.scrape_failures,
            "fwd_ewma_ms": (None if self.fwd_ewma is None
                            else round(self.fwd_ewma * 1e3, 2)),
            "probe_rtt_ms": (None if self.probe_rtt_ewma is None
                             else round(self.probe_rtt_ewma * 1e3, 2)),
            "load": self.load(),
        }


def parse_scrape(text: str) -> dict:
    """Pull the placement signals out of one replica's Prometheus text.

    Sums `tpk_decode_inflight_depth` over the replica's models (a replica
    may serve several engines), keeps the SCARCEST `tpk_kv_blocks_free`
    (admission blocks on the tightest pool), and reads the unlabeled
    `tpk_serve_inflight` admission gauge. Missing series stay None —
    flat engines have no pool gauges, non-generative replicas no decode
    depth."""
    decode = None
    kv_free = None
    admission = None
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, rest = line.partition(" ")
        base = name.partition("{")[0]
        try:
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        if base == "tpk_decode_inflight_depth":
            decode = (decode or 0.0) + value
        elif base == "tpk_kv_blocks_free":
            kv_free = value if kv_free is None else min(kv_free, value)
        elif base == "tpk_serve_inflight":
            admission = value
    return {"decode_inflight": decode, "kv_blocks_free": kv_free,
            "admission_inflight": admission}


class Fleet:
    """The replica table + its background poller.

    Thread model: request threads call checkout/checkin/snapshot; the
    poller thread scrapes and writes load signals; admin calls mutate
    membership. Everything meets under `_lock`; network I/O (scrapes,
    probes) happens strictly OUTSIDE it.
    """

    def __init__(self, poll_interval_s: float = 0.25,
                 scrape_timeout_s: float = 2.0,
                 start_poller: bool = True,
                 gray_ejection: bool = True,
                 eject_ratio: float = 3.0, eject_min_s: float = 0.2,
                 eject_strikes: int = 3, rejoin_ratio: float = 1.5,
                 slow_min_s: float = 1.0, ewma_alpha: float = 0.3,
                 min_remaining: int = 2):
        self._replicas: dict[str, Replica] = {}  # guarded-by: _lock
        #: Membership generation — bumped on add/remove/state change so
        #: the router knows to rebuild its hash ring.
        self._version = 0  # guarded-by: _lock
        self._grpc_clients: dict = {}  # guarded-by: _lock
        #: Poll-pass sequence clock for stale-straggler filtering.
        self._poll_seq = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self.poll_interval_s = float(poll_interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        # Gray-failure ejection knobs (ISSUE 14). A replica is an
        # OUTLIER when its latency score exceeds BOTH `eject_min_s` (an
        # absolute floor so microsecond-scale noise on an idle fleet
        # can't eject anything) and `eject_ratio` x the median of the
        # other candidates; it must stay an outlier for `eject_strikes`
        # consecutive poll passes before it ejects (one GC pause must
        # not flap the ring), never ejects when fewer than
        # `min_remaining` placeable replicas would remain, and rejoins
        # only after `slow_min_s` in the slow state with a succeeding
        # half-open probe whose RTT is back inside `rejoin_ratio` x the
        # fleet baseline.
        self.gray_ejection = bool(gray_ejection)
        self.eject_ratio = float(eject_ratio)
        self.eject_min_s = float(eject_min_s)
        self.eject_strikes = int(eject_strikes)
        self.rejoin_ratio = float(rejoin_ratio)
        self.slow_min_s = float(slow_min_s)
        self.ewma_alpha = float(ewma_alpha)
        self.min_remaining = int(min_remaining)
        #: Optional callback `(name, kind)` fired (outside the lock)
        #: for every eject/rejoin transition — the router hooks its
        #: flight-recorder snapshot here so chaos postmortems capture
        #: the requests surrounding an ejection.
        self.on_transition = None
        self._closed = threading.Event()
        # Scrapes fan out on this pool (threads are lazy): one stalled
        # replica must not serialize the pass and stale every OTHER
        # replica's load signals behind its timeout.
        self._scrape_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="tpk-fleet-scrape")
        self._thread: threading.Thread | None = None
        if start_poller:
            self._thread = threading.Thread(
                target=self._poll_loop, daemon=True, name="tpk-fleet-poll")
            self._thread.start()

    # -- membership ---------------------------------------------------------

    def add(self, name: str, url: str, grpc: str | None = None,
            role: str = "any") -> None:
        """Register a replica (idempotent on the same address; a new
        address or role replaces the record — the controller relaunched
        it)."""
        with self._lock:
            cur = self._replicas.get(name)
            if cur is not None and cur.url == url.rstrip("/") \
                    and cur.grpc == grpc and cur.role == role:
                return
            self._replicas[name] = Replica(name, url, grpc, role=role)
            client = self._grpc_clients.pop(name, None)
            self._version += 1
            n = len(self._replicas)
        if client is not None:
            # Only the scrape pool uses these clients; a scrape racing
            # the close fails once and self-heals on the next pass.
            try:
                client.close()
            except Exception:
                pass
        res_metrics.set_gauge("tpk_router_replicas", n)

    def remove(self, name: str) -> None:
        """Drop a replica immediately — no drain, in-flight requests to
        it will fail and retry elsewhere. Use `drain()` for graceful
        scale-in."""
        with self._lock:
            self._replicas.pop(name, None)
            client = self._grpc_clients.pop(name, None)
            self._version += 1
            n = len(self._replicas)
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        res_metrics.set_gauge("tpk_router_replicas", n)

    def drain(self, name: str, on_drained=None) -> bool:
        """Stop placing new requests on `name`; in-flight requests (both
        this router's outstanding and the replica's own gauges) finish.
        `on_drained(name)` fires exactly once when everything lands.
        Returns False for an unknown replica."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return False
            if r.state not in ("draining", "drained"):
                r.state = "draining"
                r.draining_since = time.monotonic()
                r.on_drained = on_drained
                self._version += 1
            return True

    # -- placement-side accessors ------------------------------------------

    def version(self) -> int:
        with self._lock:
            return self._version

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    def placeable_names(self) -> list[str]:
        with self._lock:
            return sorted(n for n, r in self._replicas.items()
                          if r.placeable())

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [r.view() for _, r in sorted(self._replicas.items())]

    def loads(self, names=None, intent: str | None = None) -> dict[str, float]:
        """name -> load score for the given (default: placeable)
        replicas, optionally filtered by placement `intent` ("prefill" /
        "decode" / None = full-request — see INTENT_ROLES). One lock
        hop, no I/O — safe on the placement path."""
        with self._lock:
            if names is None:
                return {n: r.load() for n, r in self._replicas.items()
                        if r.placeable() and r.serves(intent)}
            return {n: self._replicas[n].load() for n in names
                    if n in self._replicas}

    def signals(self, intent: str | None = None) -> dict[str, tuple]:
        """name -> (load, kv_blocks_free) for placeable replicas
        serving `intent` — the decode-phase placement reads pool
        headroom alongside load (ISSUE 13: decode placement is
        load/pool-driven). One lock hop."""
        with self._lock:
            return {n: (r.load(), r.kv_blocks_free or 0.0)
                    for n, r in self._replicas.items()
                    if r.placeable() and r.serves(intent)}

    def role_split(self) -> bool:
        """True when the fleet contains a placeable SPLIT replica
        (prefill or decode role) whose complementary phase is also
        covered (by the other split role, or by an "any" replica) —
        the router runs the two-phase handoff for generative traffic
        iff this holds. Symmetric on purpose: an "any"+"decode" fleet
        disaggregates (the "any" replica prefills, the decode
        specialists decode) just like "any"+"prefill" — otherwise the
        decode-role replicas, whose engines refuse local :generate,
        would sit silently stranded."""
        with self._lock:
            roles = {r.role for r in self._replicas.values()
                     if r.placeable()}
        prefill_capable = "prefill" in roles or "any" in roles
        decode_capable = "decode" in roles or "any" in roles
        has_split = "prefill" in roles or "decode" in roles
        return has_split and prefill_capable and decode_capable

    def get(self, name: str) -> dict | None:
        with self._lock:
            r = self._replicas.get(name)
            return r.view() if r is not None else None

    def url_of(self, name: str) -> str | None:
        with self._lock:
            r = self._replicas.get(name)
            return r.url if r is not None else None

    def checkout(self, name: str) -> bool:
        """Claim one outstanding slot on the replica (the router calls
        this around every forward so drain can see true quiescence)."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return False
            r.outstanding += 1
            return True

    def checkin(self, name: str, *, failed: bool = False) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            r.outstanding = max(r.outstanding - 1, 0)
            if failed:
                # A connect-level failure is evidence ahead of the next
                # poll: nudge the failure count so repeated resets take
                # the replica out of placement quickly. A `slow` replica
                # that starts refusing connections is dead, not gray.
                r.scrape_failures += 1
                if (r.scrape_failures >= DOWN_AFTER_FAILURES
                        and r.state in ("starting", "ready", "slow")):
                    r.state = "down"
                    self._version += 1

    def observe_forward(self, name: str, seconds: float) -> None:
        """Fold one router-observed forward latency into the replica's
        gray-failure EWMA. The router calls this on every completed
        forward (including timeouts and mid-stream deaths — a stalled
        replica's inflated wall time IS the gray signal)."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            a = self.ewma_alpha
            r.fwd_last = float(seconds)
            r.fwd_ewma = (seconds if r.fwd_ewma is None
                          else (1 - a) * r.fwd_ewma + a * seconds)

    # -- polling ------------------------------------------------------------

    def _scrape_one(self, name: str, url: str, grpc: str | None) -> dict:
        """One replica's load signals + readiness, via the existing
        surfaces. Runs on the scrape pool only (network I/O). The
        probe's wall time rides along as `rtt_s` — it feeds the
        gray-failure EWMA, and keeps observing a replica placement
        already routes around (the half-open side of ejection)."""
        t0 = time.perf_counter()
        if grpc:
            client = self._grpc_client(name, grpc)
            text = client.metrics(timeout=self.scrape_timeout_s)
        else:
            with urllib.request.urlopen(f"{url}/metrics",
                                        timeout=self.scrape_timeout_s) as r:
                text = r.read().decode()
        out = parse_scrape(text)
        out["metrics_text"] = text
        out["ready"] = self._probe_ready(url)
        out["rtt_s"] = time.perf_counter() - t0
        return out

    def _grpc_client(self, name: str, grpc_addr: str):
        with self._lock:
            client = self._grpc_clients.get(name)
        if client is None:
            from kubeflow_tpu.serve.grpc_server import InferenceClient

            client = InferenceClient(grpc_addr)
            with self._lock:
                self._grpc_clients[name] = client
        return client

    def _probe_ready(self, url: str) -> bool:
        try:
            with urllib.request.urlopen(f"{url}/v2/health/ready",
                                        timeout=self.scrape_timeout_s) as r:
                return r.status == 200
        except urllib.error.HTTPError:
            return False  # 503 = degraded/draining, the probe answered

    def _poll_loop(self) -> None:
        # Jittered interval (ISSUE 14): a fixed period phase-locks every
        # pass to the same replicas' slow windows and to other pollers
        # on the host; +-25% keeps the probes decorrelated.
        while not self._closed.wait(
                self.poll_interval_s * (0.75 + 0.5 * random.random())):
            self.poll_once()

    def poll_once(self) -> None:
        """One scrape pass over the fleet — the poller's body, public so
        tests (and CLI one-shots) can drive it synchronously. Replicas
        scrape in parallel on the pool; the pass waits for results only
        up to a BOUND (2x the per-probe timeout + slack) per future —
        N stalled replicas whose probes serialize behind the 8-worker
        pool must not wedge the pass (their results still apply
        whenever the worker finishes, via scrape_and_apply itself).
        Ends with the gray-failure ejection pass over whatever
        signals landed."""
        with self._lock:
            self._poll_seq += 1
            seq = self._poll_seq
            targets = [(r.name, r.url, r.grpc)
                       for r in self._replicas.values()
                       if r.state != "drained"]
        if not targets:
            return

        def scrape_and_apply(target):
            name, url, grpc = target
            try:
                sig = self._scrape_one(name, url, grpc)
            except Exception:
                sig = None
            # Apply HERE, as each scrape lands — an in-order gather
            # would hold every fast replica's fresh signals hostage to
            # the slowest scrape's timeout. The pass seq rides along:
            # stragglers outlive the bounded wait below, and a STALE
            # pass's result landing after a fresher one must be
            # dropped (three queued stale failures draining after a
            # recovery probe would mark a healthy replica down).
            self.update_load(name, sig, seq=seq)

        # ONE shared deadline for the whole set (a per-future wait
        # would re-pay its floor for every straggler); leftovers apply
        # themselves whenever their worker finishes.
        futures_wait([self._scrape_pool.submit(scrape_and_apply, t)
                      for t in targets],
                     timeout=2.0 * self.scrape_timeout_s + 1.0)
        self.eject_pass()

    def update_load(self, name: str, sig: dict | None,
                    seq: int | None = None) -> None:
        """Apply one scrape result (None = probe failed) to the table.
        The poller's write path — and the unit-test hook for driving
        placement scenarios without live replicas. `seq` is the poll
        pass that produced the result: older-pass stragglers are
        dropped (None = direct caller, always applies)."""
        fire_drained = None
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            if seq is not None:
                if seq < r.scrape_seq:
                    return  # stale straggler from an earlier pass
                r.scrape_seq = seq
            if sig is None:
                r.scrape_failures += 1
                if (r.scrape_failures >= DOWN_AFTER_FAILURES
                        and r.state in ("starting", "ready", "slow")):
                    r.state = "down"
                    self._version += 1
            else:
                r.scrape_failures = 0
                r.last_scrape = time.monotonic()
                for k in ("decode_inflight", "admission_inflight",
                          "kv_blocks_free"):
                    if k in sig:
                        setattr(r, k, sig[k])
                if "metrics_text" in sig:
                    r.metrics_text = sig["metrics_text"]
                if sig.get("rtt_s") is not None:
                    a = self.ewma_alpha
                    rtt = float(sig["rtt_s"])
                    r.probe_rtt_last = rtt
                    r.probe_rtt_ewma = (
                        rtt if r.probe_rtt_ewma is None
                        else (1 - a) * r.probe_rtt_ewma + a * rtt)
                if "ready" in sig and sig["ready"] != r.probe_ready:
                    # A degradation flip changes placeability — bump the
                    # version so the router rebuilds its ring.
                    r.probe_ready = sig["ready"]
                    self._version += 1
                if r.state in ("starting", "down"):
                    # Readiness may be degraded (shedding) — the replica
                    # still answers, so it is back in the table; a
                    # not-ready-but-alive replica keeps its state until
                    # ready flips true.
                    if sig.get("ready", True):
                        r.state = "ready"
                        self._version += 1
            if r.state == "draining" and self._quiesced_locked(r, sig):
                r.state = "drained"
                self._version += 1
                fire_drained, r.on_drained = r.on_drained, None
        if fire_drained is not None:
            try:
                fire_drained(name)
            except Exception:
                pass  # a retire hook must never kill the poller

    def eject_pass(self) -> list[tuple[str, str]]:
        """The gray-failure evaluation (ISSUE 14): runs after every poll
        pass (and synchronously from tests). Compares each candidate's
        latency score (worse of forward-EWMA and probe-RTT-EWMA)
        against the MEDIAN of the other candidates':

          * a `ready` replica that has been an outlier (> eject_min_s
            AND > eject_ratio x median) for `eject_strikes` consecutive
            passes EJECTS to `slow` — out of placement, still draining
            its in-flight, still probed (the binary `down` path is
            untouched: a replica whose probes FAIL outright still trips
            DOWN_AFTER_FAILURES);
          * a `slow` replica rejoins (`ready`) once it has served its
            `slow_min_s` hysteresis, its half-open probe succeeds, and
            its probe RTT is back inside rejoin_ratio x the baseline —
            the forward EWMA resets on rejoin (it is stale by
            construction: placement sent the replica nothing while
            slow), so re-ejection needs fresh evidence.

        Returns the transitions taken, as (name, "eject"|"rejoin") —
        telemetry and tests."""
        if not self.gray_ejection:
            return []
        transitions: list[tuple[str, str]] = []
        now = time.monotonic()
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.state in ("starting", "ready", "slow")]
            placeable_by_role: dict[str, int] = {}
            for r in candidates:
                if r.state in ("starting", "ready"):
                    placeable_by_role[r.role] = \
                        placeable_by_role.get(r.role, 0) + 1
            # Each signal is compared only WITHIN ITS OWN population:
            # forward latency (whole-request wall, streams included)
            # and probe RTT live on different scales, so judging one
            # replica's stream wall time against its idle peers' probe
            # RTTs would eject the fleet's only ACTIVE replica — found
            # the hard way by the seeded decode-kill test, where the
            # lone serving decode replica got ejected and the resume
            # had nowhere to land. Forward latency is ALSO partitioned
            # by ROLE: in a disaggregated fleet, prefill forwards
            # finish in milliseconds while decode forwards stream for
            # seconds BY DESIGN — pooled, every healthy decode replica
            # is a structural outlier against its prefill peers and
            # the whole decode side would flap out of placement. Probe
            # RTT stays fleet-wide (the scrape is role-independent).
            fwd_pop: dict[str, dict] = {}
            for r in candidates:
                if r.fwd_ewma is not None:
                    fwd_pop.setdefault(r.role, {})[r.name] = r.fwd_ewma
            rtt_pop = {r.name: r.probe_rtt_ewma for r in candidates
                       if r.probe_rtt_ewma is not None}

            def outlier(pop: dict, me: str, ewma, last) -> bool:
                # A strike needs the SMOOTHED and the INSTANTANEOUS
                # sample to both be outliers: the EWMA keeps one GC
                # pause's spike alive for several polls, and counting
                # strikes off its decay tail alone would turn a single
                # pause into an ejection.
                if ewma is None:
                    return False
                others = [v for n, v in pop.items() if n != me]
                if len(others) < 2:
                    return False  # no population, no statistics
                med = max(statistics.median(others), 1e-9)
                val = ewma if last is None else min(ewma, last)
                return (val > self.eject_min_s
                        and val > self.eject_ratio * med)

            def rtt_baseline(me: str) -> float:
                others = [v for n, v in rtt_pop.items() if n != me]
                return max(statistics.median(others), 1e-9) if others \
                    else 1e-9

            for r in candidates:
                if r.state == "ready":
                    is_out = (outlier(fwd_pop.get(r.role, {}), r.name,
                                      r.fwd_ewma, r.fwd_last)
                              or outlier(rtt_pop, r.name,
                                         r.probe_rtt_ewma,
                                         r.probe_rtt_last))
                    r.slow_strikes = r.slow_strikes + 1 if is_out else 0
                    if (r.slow_strikes >= self.eject_strikes
                            and placeable_by_role.get(r.role, 0) - 1
                            >= self.min_remaining):
                        r.state = "slow"
                        r.slow_since = now
                        r.slow_strikes = 0
                        placeable_by_role[r.role] -= 1
                        self._version += 1
                        transitions.append((r.name, "eject"))
                elif r.state == "slow":
                    probe_ok = (r.scrape_failures == 0
                                and r.probe_rtt_ewma is not None
                                and r.probe_ready is not False)
                    recovered = (probe_ok
                                 and r.probe_rtt_ewma
                                 <= max(self.eject_min_s,
                                        self.rejoin_ratio
                                        * rtt_baseline(r.name)))
                    if (recovered and r.slow_since is not None
                            and now - r.slow_since >= self.slow_min_s):
                        r.state = "ready"
                        r.slow_since = None
                        r.fwd_ewma = None
                        placeable_by_role[r.role] = \
                            placeable_by_role.get(r.role, 0) + 1
                        self._version += 1
                        transitions.append((r.name, "rejoin"))
        for name, kind in transitions:
            if kind == "eject":
                res_metrics.inc("tpk_fleet_ejections_total",
                                replica=name)
            else:
                res_metrics.inc("tpk_fleet_rejoins_total",
                                replica=name)
            if self.on_transition is not None:
                try:
                    self.on_transition(name, kind)
                except Exception:
                    pass  # an observer hook must never kill the poller
        return transitions

    def metrics_texts(self) -> dict[str, str]:
        """Replica name -> raw exposition text of its last successful
        scrape (replicas never scraped are absent) — the cached inputs
        for the router's /fleet/metrics merge."""
        with self._lock:
            return {r.name: r.metrics_text
                    for r in self._replicas.values()
                    if r.metrics_text is not None}

    @staticmethod
    def _quiesced_locked(r: Replica, sig: dict | None) -> bool:
        """Drain completion: nothing outstanding from this router AND the
        replica's own gauges read idle (or the replica is gone — nothing
        left to preserve)."""
        if r.outstanding > 0:
            return False
        if sig is None:
            return r.scrape_failures >= DOWN_AFTER_FAILURES
        decode = sig.get("decode_inflight")
        admission = sig.get("admission_inflight")
        if decode is None and admission is None:
            # The replica exposes NO in-flight gauge: absence is not
            # evidence of idleness (other routers' / direct clients'
            # traffic is unobservable), so hold the drain for a grace
            # window rather than completing on the first poll. Best
            # effort only — work longer than the grace can still be
            # cut; replicas with an admission gate are fully observed.
            since = r.draining_since or 0.0
            return time.monotonic() - since >= DRAIN_UNOBSERVED_GRACE_S
        return not decode and not admission

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._scrape_pool.shutdown(wait=False)
        with self._lock:
            clients = list(self._grpc_clients.values())
            self._grpc_clients.clear()
        for c in clients:
            try:
                c.close()
            except Exception:
                pass


class FleetAutoscaler:
    """Closes the load → replica-count loop (ISSUE 9 tentpole).

    Inputs are ROUTER-observed: the shed rate (503s forwarded to
    callers since the last evaluation) and fleet occupancy (mean load
    per replica against `capacity_per_replica`). Policy:

      * sheds observed OR occupancy >= high_water  → scale OUT (+1).
      * occupancy <= low_water for `low_water_evals` consecutive
        evaluations and more than `min_replicas` remain → scale IN:
        pick the least-loaded replica, DRAIN it (placement stops,
        in-flight finishes), and only when the fleet reports it
        quiesced does `retire(name)` run.

    The scaler is deliberately callback-shaped: `scale_up()` adds a
    replica however the deployment does (spawn a process, patch an ISVC
    through `ControlPlaneScaler`, …) and `retire(name)` tears one down.
    `evaluate()` is the whole policy — the background thread just calls
    it on an interval, so tests drive it synchronously."""

    def __init__(self, fleet: Fleet, router, *, scale_up, retire,
                 capacity_per_replica: float = 8.0,
                 high_water: float = 0.8, low_water: float = 0.2,
                 low_water_evals: int = 3,
                 min_replicas: int = 1, max_replicas: int = 8,
                 interval_s: float = 1.0):
        self.fleet = fleet
        self.router = router
        self.scale_up = scale_up
        self.retire = retire
        self.capacity_per_replica = float(capacity_per_replica)
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.low_water_evals = int(low_water_evals)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self._last_sheds = 0.0
        self._low_streak = 0
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "FleetAutoscaler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpk-autoscaler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._closed.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:
                pass  # a flaky scale hook must not kill the loop

    def occupancy(self) -> float:
        loads = self.fleet.loads()
        if not loads:
            return 0.0
        cap = self.capacity_per_replica * len(loads)
        return sum(loads.values()) / max(cap, 1e-9)

    def evaluate(self) -> str | None:
        """One policy step; returns the action taken (telemetry/tests)."""
        stats = self.router.stats_snapshot()
        # no_replica counts too: a fleet whose every replica degraded
        # into its shedding window is the loudest possible scale signal.
        sheds = (float(stats.get("sheds_forwarded", 0))
                 + float(stats.get("no_replica", 0)))
        shed_delta, self._last_sheds = sheds - self._last_sheds, sheds
        occ = self.occupancy()
        # Draining replicas still count toward the total (their retire
        # is already committed) so a slow drain can't double-scale —
        # but drained/down ones are no longer capacity and must not
        # consume max_replicas headroom: past scale-ins (whose retired
        # table entries a count-based ControlPlaneScaler never removes)
        # would otherwise permanently block future scale-outs. A gray
        # `slow` replica counts too: it is ALIVE and expected back — a
        # GC pause must not buy a whole new replica, and it must never
        # be picked as a drain victim (placeable_names excludes it).
        total = len([r for r in self.fleet.snapshot()
                     if r["state"] in ("starting", "ready", "slow",
                                       "draining")])
        if (shed_delta > 0 or occ >= self.high_water) \
                and total < self.max_replicas:
            self._low_streak = 0
            self.scale_up()
            return "scale_up"
        placeable = self.fleet.placeable_names()
        if occ <= self.low_water and len(placeable) > self.min_replicas:
            self._low_streak += 1
            if self._low_streak >= self.low_water_evals:
                self._low_streak = 0
                loads = self.fleet.loads(placeable)
                victim = min(placeable,
                             key=lambda n: (loads.get(n, 0.0), n))
                self.fleet.drain(victim, on_drained=self._retire_and_remove)
                return f"drain:{victim}"
        else:
            self._low_streak = 0
        return None

    def _retire_and_remove(self, name: str) -> None:
        """Drain-completion hook: tear the replica down AND drop its
        table entry — a retired 'drained' record kept forever would
        inflate tpk_router_replicas and eat max_replicas headroom."""
        try:
            self.retire(name)
        finally:
            self.fleet.remove(name)

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


class ControlPlaneScaler:
    """Autoscaler callbacks that reconcile through the C++ store: patch
    the InferenceService's `spec.replicas`, and let the serving
    controller (cpp/serve.cc) do the actual process launch/teardown —
    the same path `tpukit submit` and the scale-to-zero example use.
    The fixed-replica ISVC reconcile already follows `spec.replicas`
    updates, so the router's autoscaler composes with it without any
    new control-plane verb.

    LIMITATION — count-based, not victim-targeted: `retire(name)` only
    decrements `spec.replicas`; the serving controller picks which
    process to tear down when reconciling the count, and that may NOT
    be the replica the fleet just drained (k8s has pod-deletion-cost
    for this; the store schema has no per-replica selector yet). Safe
    only where the controller's victim choice matches the drain (e.g.
    it retires the highest index and the autoscaler drains the same),
    or where a second drain cycle on the survivor is acceptable.
    Deployments that need exact victim identity should pass a custom
    `retire` callback that kills the drained process directly."""

    def __init__(self, client, isvc_name: str):
        self.client = client
        self.isvc = isvc_name

    # `update_spec` is a FULL-SPEC replace on the control plane (the
    # server re-validates the whole document) — so the patch must be
    # read-modify-write. Sending a bare {"replicas": N} looked fine
    # against test fakes but the REAL binary rejects it ("model is
    # required") — found by the ISSUE 14 combined-plane failover test,
    # which runs the scaler's reconcile against a live promoted
    # follower. The replace rides the store's CAS (`expected_version`
    # = the read's resourceVersion, the wake_service precedent) so a
    # concurrent spec writer's change is never clobbered by our stale
    # copy — a version conflict re-reads and retries.

    def _patch_replicas(self, delta: int) -> None:
        for _ in range(4):
            res = self.client.get("InferenceService", self.isvc)
            spec = dict(res.get("spec", {}))
            spec["replicas"] = max(
                int(spec.get("replicas", 1)) + delta, 0)
            try:
                self.client.update_spec(
                    "InferenceService", self.isvc, spec,
                    expected_version=res.get("resourceVersion"))
                return
            except Exception as e:
                if "conflict" not in str(e):
                    raise
        raise RuntimeError(
            f"spec.replicas patch on {self.isvc!r} kept losing the "
            "CAS race")

    def scale_up(self) -> None:
        self._patch_replicas(+1)

    def retire(self, name: str) -> None:
        self._patch_replicas(-1)


def fetch_replicas(router_url: str, timeout_s: float = 5.0) -> dict:
    """GET the router's admin replica table (the `tpukit replicas`
    backend)."""
    with urllib.request.urlopen(
            f"{router_url.rstrip('/')}/admin/replicas",
            timeout=timeout_s) as r:
        return json.loads(r.read().decode())
