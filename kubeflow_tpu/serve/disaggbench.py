"""Disaggregated-vs-unified serving benchmark (ISSUE 13) → DISAGGBENCH.json.

The claim under test (ROADMAP item 3 / PROFILE §8): under heavy MIXED
traffic, long-prompt chunked prefill steals decode dispatches from
in-flight streams because both share one engine loop — so splitting the
fleet into prefill-only and decode-only replicas (KV blocks shipped
through the router) isolates TTFT and the decode tail at EQUAL total
engines.

Harness discipline (PROFILE §11, the ROUTERBENCH rules):

  * **Open loop.** Seeded Poisson arrivals FIRE AT SCHEDULE — a closed
    loop would slow offered load to whatever the server survives and
    hide exactly the queueing this bench exists to expose.
  * **Real engines, honest labels.** Replicas run the REAL
    GenerationEngine on the tiny CPU model behind real ModelServers and
    the real router — the mechanism counters (prefill chunks, shipped/
    received blocks) are the engine's own, not simulated. Absolute
    tok/s numbers are CPU-tiny-model numbers and say nothing about
    chips; the ARM DELTAS (TTFT/tail isolation at equal engines) are
    the artifact. The chip row records skipped-with-reason while the
    tunnel is down, per the SERVEBENCH convention.
  * **Equal resources.** Both arms run exactly two engines with
    identical pools/slots; the disagg arm splits them by role, the
    unified arm load-balances mixed traffic across both.

Per request the harness records TTFT (first streamed token frame) and
total latency; the summary reports goodput, p50/p99 TTFT (overall and
for the short-decode class the interference claim is about), and the
decode-tail p99 (total − TTFT over short requests).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from kubeflow_tpu.serve.loadgen import summarize  # noqa: F401 (doc link)


def _build_tiny():
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.llama import Llama, llama_tiny

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                              num_layers=2)
    model = Llama(cfg)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(jax.random.key(0))
    return model, params, cfg


def _make_replica(model, params, cfg, *, role: str, gen_kw: dict,
                  name: str = "m"):
    from kubeflow_tpu.serve.generation import GenerativeJAXModel
    from kubeflow_tpu.serve.server import ModelServer

    m = GenerativeJAXModel(name, model, params, cfg,
                           generation=dict(gen_kw, role=role))
    srv = ModelServer(max_inflight=128, executor_workers=128)
    srv.repo.register(m)
    port = srv.start_background()
    return srv, f"http://127.0.0.1:{port}", m


def _stream_generate(base_url: str, model: str, payload: dict,
                     timeout_s: float = 60.0) -> dict:
    """POST a streaming :generate and record TTFT (first token frame)
    + total wall. Returns {status, ttft_ms, total_ms, tokens}."""
    req = urllib.request.Request(
        f"{base_url}/v1/models/{model}:generate",
        data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    t0 = time.monotonic()
    ttft = None
    tokens = 0
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            buf = b""
            while True:
                chunk = r.read1(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    ev = json.loads(line)
                    if ev.get("tokens") and ttft is None:
                        ttft = (time.monotonic() - t0) * 1e3
                    tokens += len(ev.get("tokens", ()))
        return {"status": 200, "ttft_ms": ttft,
                "total_ms": (time.monotonic() - t0) * 1e3,
                "tokens": tokens}
    except urllib.error.HTTPError as e:
        return {"status": e.code, "ttft_ms": None,
                "total_ms": (time.monotonic() - t0) * 1e3, "tokens": 0}
    except Exception as e:
        return {"status": -1, "ttft_ms": None,
                "total_ms": (time.monotonic() - t0) * 1e3, "tokens": 0,
                "error": f"{type(e).__name__}: {e}"}


def _mixed_open_loop(base: str, *, rate_rps: float, duration_s: float,
                     long_frac: float, cfg, long_prompt: int,
                     short_prompt: int, long_max_tokens: int,
                     short_max_tokens: int, seed: int) -> list[dict]:
    """Seeded Poisson mixed long-prompt/short-decode arrivals, fired at
    schedule (open loop); one record per request."""
    rng = np.random.default_rng(seed)
    arrivals = []
    t = 0.0
    while t < duration_s:
        t += float(rng.exponential(1.0 / rate_rps))
        if t < duration_s:
            arrivals.append((t, bool(rng.random() < long_frac)))
    records: list[dict] = []
    lock = threading.Lock()
    threads = []

    def fire(i: int, is_long: bool):
        g = np.random.default_rng(seed * 100003 + i)
        n = long_prompt if is_long else short_prompt
        payload = {
            "input_ids": [int(x) for x in
                          g.integers(1, cfg.vocab_size, n)],
            "max_tokens": (long_max_tokens if is_long
                           else short_max_tokens),
        }
        rec = _stream_generate(base, "m", payload)
        rec["kind"] = "long" if is_long else "short"
        with lock:
            records.append(rec)

    start = time.monotonic()
    for i, (sched, is_long) in enumerate(arrivals):
        delay = start + sched - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=fire, args=(i, is_long),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120.0)
    return records


def _pct(vals, p):
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    return round(vals[min(int(len(vals) * p), len(vals) - 1)], 2)


def _summarize_mixed(records: list[dict], duration_s: float) -> dict:
    ok = [r for r in records if r["status"] == 200]
    shorts = [r for r in ok if r["kind"] == "short"]
    longs = [r for r in ok if r["kind"] == "long"]
    tails = [r["total_ms"] - r["ttft_ms"] for r in shorts
             if r["ttft_ms"] is not None]
    return {
        "requests": len(records),
        "completed_ok": len(ok),
        "goodput_rps": round(len(ok) / duration_s, 2),
        "shed_rate": round(sum(1 for r in records
                               if r["status"] == 503)
                           / max(len(records), 1), 4),
        "errors": sum(1 for r in records
                      if r["status"] not in (200, 503)),
        "ttft_p50_ms": _pct([r["ttft_ms"] for r in ok], 0.50),
        "ttft_p99_ms": _pct([r["ttft_ms"] for r in ok], 0.99),
        "short_ttft_p50_ms": _pct([r["ttft_ms"] for r in shorts], 0.50),
        "short_ttft_p99_ms": _pct([r["ttft_ms"] for r in shorts], 0.99),
        "long_ttft_p99_ms": _pct([r["ttft_ms"] for r in longs], 0.99),
        "decode_tail_p99_ms": _pct(tails, 0.99),
        "tokens": sum(r["tokens"] for r in ok),
    }


def run_disaggbench(quick: bool = False, seed: int = 0) -> dict:
    """The DISAGGBENCH.json payload: unified vs disaggregated fleets at
    equal engines under identical seeded mixed traffic."""
    import jax

    from kubeflow_tpu.serve.router import RouterServer

    model, params, cfg = _build_tiny()
    gen_kw = dict(slots=4, max_len=120, chunk=8,
                  prefill_buckets=(16, 32), kv_block_size=8,
                  kv_blocks=0, pipeline_depth=2, seed=seed)
    duration = 6.0 if quick else 16.0
    # Mixed traffic: long prompts chunk-prefill (4 chunks of 32) with a
    # short decode; short prompts decode long enough to have a tail.
    traffic = dict(long_frac=0.35, long_prompt=96, short_prompt=12,
                   long_max_tokens=8, short_max_tokens=32)
    rate = 10.0 if quick else 14.0

    result: dict = {
        "metric": "disaggbench",
        "mode": "real-tiny-engines-cpu",
        "note": ("both arms run the REAL GenerationEngine (tiny model, "
                 "CPU) behind real ModelServers and the real router at "
                 "EQUAL total engines; absolute latencies are CPU-tiny "
                 "numbers — the artifact is the arm DELTA (TTFT/tail "
                 "isolation) and the mechanism counters"),
        "device_kind": jax.devices()[0].device_kind,
        "params": {"gen_kw": {k: v for k, v in gen_kw.items()},
                   "traffic": traffic, "rate_rps": rate,
                   "duration_s": duration, "seed": seed,
                   "quick": bool(quick)},
        "chip_row": {"skipped": "axon tunnel down — recorded on CPU "
                                "with the tiny model; chip re-run "
                                "queued for the next window"},
        "arms": {},
    }

    def one_arm(disagg: bool) -> dict:
        servers = []
        router = None
        try:
            if disagg:
                roles = (("pre", "prefill"), ("dec", "decode"))
            else:
                roles = (("u0", "unified"), ("u1", "unified"))
            reps = []
            for name, role in roles:
                srv, url, m = _make_replica(model, params, cfg,
                                            role=role, gen_kw=gen_kw)
                servers.append(srv)
                reps.append((name, url, m, role))
            router = RouterServer()
            router.fleet.poll_interval_s = 0.15
            for name, url, _m, role in reps:
                router.fleet.add(name, url,
                                 role=("any" if role == "unified"
                                       else role))
            base = f"http://127.0.0.1:{router.start_background()}"
            time.sleep(0.4)  # first scrape
            records = _mixed_open_loop(
                base, rate_rps=rate, duration_s=duration, cfg=cfg,
                seed=seed, **traffic)
            arm = _summarize_mixed(records, duration)
            arm["replicas"] = {}
            for name, _url, m, role in reps:
                s = m.engine.stats_snapshot()
                arm["replicas"][name] = {
                    "role": m.engine.role,
                    "prefill_chunks": s["prefill_chunks"],
                    "decode_dispatches": s["decode_dispatches"],
                    "kv_blocks_shipped": s["kv_blocks_shipped"],
                    "kv_blocks_received": s["kv_blocks_received"],
                    "kv_spilled_blocks": s["kv_spilled_blocks"],
                    "kv_restored_blocks": s["kv_restored_blocks"],
                    "remote_admits": s["remote_admits"],
                    "requests": s["requests"],
                }
            arm["router"] = {
                k: v for k, v in router.router.stats_snapshot().items()
                if k in ("placed", "handoffs", "handoff_retries",
                         "decode_pool", "sheds_forwarded", "errors",
                         "no_replica")}
            return arm
        finally:
            if router is not None:
                router.stop()
            for srv in servers:
                srv.stop()

    result["arms"]["unified"] = one_arm(disagg=False)
    result["arms"]["disagg"] = one_arm(disagg=True)
    uni, dis = result["arms"]["unified"], result["arms"]["disagg"]
    if uni["ttft_p99_ms"] and dis["ttft_p99_ms"]:
        result["ttft_p99_ratio"] = round(
            dis["ttft_p99_ms"] / uni["ttft_p99_ms"], 3)
    if uni["short_ttft_p99_ms"] and dis["short_ttft_p99_ms"]:
        result["short_ttft_p99_ratio"] = round(
            dis["short_ttft_p99_ms"] / uni["short_ttft_p99_ms"], 3)
    if uni["decode_tail_p99_ms"] and dis["decode_tail_p99_ms"]:
        result["decode_tail_p99_ratio"] = round(
            dis["decode_tail_p99_ms"] / uni["decode_tail_p99_ms"], 3)
    result["goodput_ratio"] = round(
        dis["goodput_rps"] / max(uni["goodput_rps"], 1e-9), 3)
    return result
