"""Model serving — the KServe-equivalent subsystem (SURVEY.md §2.2, §7.1.6).

Data plane: Model/JAXModel (AOT bucketed inference), Batcher (request
coalescing), ModelServer (v1 + v2 open-inference HTTP), storage initializer,
and ServingRuntime-style format registry. Fabric layer (ISSUE 9):
Fleet/Router/RouterServer — the front door over N replicas (affinity
placement, draining, autoscaling).

Exports resolve LAZILY (PEP 562): server.py pulls in the engine stack
(jax) at module level, and the front-door router process must be able to
import its engine-free slice (`serve.router`, `serve.fleet`,
`serve.headers`) without paying that stall — an eager `__init__` would
re-defeat exactly that.
"""

import importlib

#: export name -> defining submodule (resolved on first attribute access).
_EXPORTS = {
    "AdmissionController": "kubeflow_tpu.serve.server",
    "Batcher": "kubeflow_tpu.serve.batcher",
    "ControlPlaneScaler": "kubeflow_tpu.serve.fleet",
    "DEADLINE_HEADER": "kubeflow_tpu.serve.headers",
    "Fleet": "kubeflow_tpu.serve.fleet",
    "FleetAutoscaler": "kubeflow_tpu.serve.fleet",
    "HostKVTier": "kubeflow_tpu.serve.kv_transfer",
    "JAXModel": "kubeflow_tpu.serve.model",
    "Model": "kubeflow_tpu.serve.model",
    "ModelRepository": "kubeflow_tpu.serve.server",
    "ModelServer": "kubeflow_tpu.serve.server",
    "Router": "kubeflow_tpu.serve.router",
    "RouterServer": "kubeflow_tpu.serve.router",
    "download": "kubeflow_tpu.serve.storage",
    "export_for_serving": "kubeflow_tpu.serve.runtimes",
    "list_runtimes": "kubeflow_tpu.serve.runtimes",
    "load_model": "kubeflow_tpu.serve.runtimes",
    "register_runtime": "kubeflow_tpu.serve.runtimes",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
