"""Model serving — the KServe-equivalent subsystem (SURVEY.md §2.2, §7.1.6).

Data plane: Model/JAXModel (AOT bucketed inference), Batcher (request
coalescing), ModelServer (v1 + v2 open-inference HTTP), storage initializer,
and ServingRuntime-style format registry.
"""

from kubeflow_tpu.serve.batcher import Batcher
from kubeflow_tpu.serve.model import JAXModel, Model
from kubeflow_tpu.serve.runtimes import (export_for_serving, list_runtimes,
                                         load_model, register_runtime)
from kubeflow_tpu.serve.server import (DEADLINE_HEADER, AdmissionController,
                                       ModelRepository, ModelServer)
from kubeflow_tpu.serve.storage import download

__all__ = [
    "AdmissionController", "Batcher", "DEADLINE_HEADER", "JAXModel",
    "Model", "ModelRepository", "ModelServer", "download",
    "export_for_serving", "list_runtimes", "load_model",
    "register_runtime",
]
