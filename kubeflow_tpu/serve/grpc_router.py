"""gRPC front door: the router's open-inference plane (ISSUE 9).

The replica's gRPC server (serve/grpc_server.py) hand-rolls its service
with `method_handlers_generic_handler`; the router fronts the SAME
method names with identity (de)serializers — requests stay raw bytes
end to end, so the router needs no protobuf schema knowledge and adds
no re-encode cost. Placement is least-loaded over the replicas that
registered a gRPC address (byte-opaque requests carry no usable prefix
signal — affinity stays an HTTP-plane feature); `UNAVAILABLE` failures
(connect refused, replica draining) retry on a different replica under
the caller's gRPC deadline, mirroring the HTTP retry contract.
`x-request-id` metadata is honored/assigned, forwarded, and echoed in
the trailing metadata — one trace identity across both planes.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import TYPE_CHECKING

import grpc

from kubeflow_tpu.utils import obs
from kubeflow_tpu.utils.resilience import metrics as res_metrics

if TYPE_CHECKING:
    from kubeflow_tpu.serve.router import RouterServer

SERVICE = "inference.GRPCInferenceService"
_METHODS = ("ServerLive", "ServerReady", "ModelReady", "ModelMetadata",
            "ModelInfer")


class GrpcRouterServicer:
    """Byte-level forwarders for every replica RPC."""

    def __init__(self, server: "RouterServer"):
        self.server = server
        self.fleet = server.fleet
        self.router = server.router
        #: name -> (addr, channel); keyed on the ADDRESS too, so a
        #: replica relaunched elsewhere doesn't keep being dialed at
        #: its dead old port through a stale cached channel.
        self._channels: dict[str, tuple[str, grpc.Channel]] = {}  # guarded-by: _lock
        #: (name, addr) pairs that have served at least one successful
        #: RPC — a later died RPC on such a channel is a MID-RPC death
        #: (the replica was up and serving), not a connect failure;
        #: the two are counted apart (HTTP-plane parity, ISSUE 14).
        self._served: set[tuple[str, str]] = set()  # guarded-by: _lock
        self._lock = threading.Lock()

    def _channel(self, name: str, addr: str) -> grpc.Channel:
        with self._lock:
            cached = self._channels.get(name)
            if cached is not None and cached[0] == addr:
                return cached[1]
            ch = grpc.insecure_channel(addr)
            self._channels[name] = (addr, ch)
        if cached is not None:
            # Deferred close: closing now would CANCEL any RPC still in
            # flight on the displaced channel; every such RPC carries a
            # timeout <= forward_timeout_s, so past that they are all
            # settled and the close only reclaims the channel.
            timer = threading.Timer(self.server.forward_timeout_s + 1.0,
                                    cached[1].close)
            timer.daemon = True
            timer.start()
        return ch

    def _grpc_replicas(self) -> dict[str, str]:
        """placeable replica name -> gRPC address. Mirrors the HTTP
        plane's Replica.placeable(): a degraded readiness probe routes
        the replica out of placement on BOTH planes. Role-split
        replicas (ISSUE 13) are excluded: the gRPC plane has no
        :prefill/:decode verbs, so only a replica serving BOTH phases
        can answer a generate RPC — disaggregated fleets serve gRPC
        traffic from their unified replicas (or not at all, loudly)."""
        out = {}
        for r in self.server.fleet.snapshot():
            if r["grpc"] and r["state"] in ("starting", "ready") \
                    and r["ready"] is not False \
                    and r.get("role", "any") == "any":
                out[r["name"]] = r["grpc"]
        return out

    def forward(self, full_method: str, request: bytes, context) -> bytes:
        rid = next((v for k, v in (context.invocation_metadata() or ())
                    if k.lower() == "x-request-id"), None)
        trace_id = obs.sanitize_trace_id(rid)
        context.set_trailing_metadata((("x-request-id", trace_id),))
        addrs = self._grpc_replicas()
        exclude: set[str] = set()
        attempts = 0
        last_err = "no gRPC-capable replica registered"
        t_start = time.perf_counter()
        t_start_unix = time.time()
        trail: list[str] = []

        def conclude(outcome: str, reason: str = "") -> None:
            # SLO + flight-recorder parity with the HTTP plane: every
            # terminal path (abort or return) reports an e2e sample and
            # one outcome record. Unary RPCs have no flush boundary, so
            # there is no gRPC TTFT sample — e2e IS the delivery time.
            res_metrics.observe("tpk_router_e2e_seconds",
                                time.perf_counter() - t_start,
                                outcome=outcome)
            self.server.flight_recorder.record(
                trace_id=trace_id, path=full_method, intent="grpc",
                stream=False, t_start_unix=t_start_unix, ttft_s=None,
                e2e_s=time.perf_counter() - t_start, outcome=outcome,
                reason=reason, replicas=list(trail), resumes=0,
                attempts=attempts,
                deadline_miss=outcome == "deadline")
        while True:
            candidates = {n: a for n, a in addrs.items()
                          if n not in exclude}
            loads = self.fleet.loads(sorted(candidates))
            if not candidates:
                res_metrics.inc("tpk_router_requests_total", replica="-",
                                outcome="no_replica")
                self.router._bump("no_replica")
                conclude("no_replica", last_err)
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              f"no live replica: {last_err}")
            with obs.span("router.place", trace_id=trace_id,
                          path=full_method) as sp:
                name = min(candidates,
                           key=lambda n: (loads.get(n, 0.0), n))
                sp.set(replica=name, reason="least-loaded")
            res_metrics.inc("tpk_router_placement_total",
                            reason="least-loaded")
            self.router._bump("placed")
            self.router._bump("least_loaded")
            rem = context.time_remaining()
            timeout = (min(rem, self.server.forward_timeout_s)
                       if rem is not None else self.server.forward_timeout_s)
            if timeout <= 0:
                res_metrics.inc("tpk_router_requests_total", replica=name,
                                outcome="deadline")
                conclude("deadline", "request deadline exceeded (router)")
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                              "request deadline exceeded (router)")
            addr = candidates[name]
            if not trail or trail[-1] != name:
                trail.append(name)
            rpc = self._channel(name, addr).unary_unary(
                full_method,
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            attempts += 1
            self.fleet.checkout(name)
            t0 = time.perf_counter()
            try:
                resp = rpc(request, timeout=timeout,
                           metadata=(("x-request-id", trace_id),))
            except grpc.RpcError as e:
                code = e.code()
                # UNAVAILABLE covers BOTH a refused connect and a
                # replica dying mid-RPC (socket closed / GOAWAY with the
                # request in flight); INTERNAL's RST_STREAM flavor is
                # the same death seen through http2. Both are the HTTP
                # plane's "nothing reached the caller, replay is safe"
                # class for these unary methods — but they are COUNTED
                # apart (reason=midstream vs connect), keyed on whether
                # this (name, addr) channel had already served traffic:
                # a previously-serving replica failing is a mid-stream
                # death, not a placement mistake (ISSUE 14 parity with
                # tpk_router_requests_total{outcome="upstream_error"}).
                details = e.details() or ""
                died = (code == grpc.StatusCode.UNAVAILABLE
                        or (code == grpc.StatusCode.INTERNAL
                            and ("RST_STREAM" in details
                                 or "Received RST" in details)))
                draining = "draining" in details
                with self._lock:
                    midstream = (died and not draining
                                 and (name, addr) in self._served)
                    if midstream:
                        # One death event per served channel: the
                        # FIRST failure after service is the mid-RPC
                        # death; every subsequent attempt against the
                        # dead port is a plain connect refusal and
                        # must count as such (a success re-arms it).
                        self._served.discard((name, addr))
                self.fleet.checkin(name, failed=died and not draining)
                if midstream:
                    self.fleet.observe_forward(
                        name, time.perf_counter() - t0)
                last_err = f"{name}: {code.name}: {details}"
                if died and attempts <= max(len(addrs), 1):
                    exclude.add(name)
                    res_metrics.inc("tpk_router_retry_total",
                                    reason=("draining" if draining
                                            else "midstream" if midstream
                                            else "connect"))
                    self.router._bump("retries")
                    continue
                outcome = ("shed" if code ==
                           grpc.StatusCode.RESOURCE_EXHAUSTED
                           else "upstream_error" if midstream
                           else "retry_exhausted" if died
                           else "upstream_error")
                res_metrics.inc("tpk_router_requests_total",
                                replica=name, outcome=outcome)
                self.router._bump("sheds_forwarded"
                                  if outcome == "shed" else "errors")
                conclude(outcome, last_err)
                # Forward the replica's status verbatim — a shed's
                # RESOURCE_EXHAUSTED is backpressure, not retry fodder.
                context.abort(code, details or code.name)
            except Exception as e:  # noqa: BLE001 — parity with HTTP 502
                # A non-RpcError escaping here would surface to the
                # caller as a bare UNKNOWN with no counter trace — the
                # exact "uncounted raw error" the HTTP plane never
                # emits. Count it and abort with a named INTERNAL.
                self.fleet.checkin(name)
                res_metrics.inc("tpk_router_requests_total",
                                replica=name, outcome="upstream_error")
                self.router._bump("errors")
                conclude("upstream_error",
                         f"{type(e).__name__}: {e}")
                context.abort(grpc.StatusCode.INTERNAL,
                              f"router forward failed: "
                              f"{type(e).__name__}: {e}")
            else:
                self.fleet.checkin(name)
                self.fleet.observe_forward(name,
                                           time.perf_counter() - t0)
                with self._lock:
                    self._served.add((name, addr))
                res_metrics.inc("tpk_router_requests_total",
                                replica=name, outcome="ok")
                self.router._bump("ok")
                conclude("ok")
                return resp


def _identity_handler(fn):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=lambda b: b,
        response_serializer=lambda b: b)


def build_grpc_router(server: "RouterServer", port: int = 0,
                      max_workers: int = 16):
    """Returns (grpc.Server, bound_port) on 127.0.0.1."""
    servicer = GrpcRouterServicer(server)

    def fwd(method):
        full = f"/{SERVICE}/{method}"
        return _identity_handler(
            lambda req, ctx, _f=full: servicer.forward(_f, req, ctx))

    handlers = grpc.method_handlers_generic_handler(
        SERVICE, {m: fwd(m) for m in _METHODS})
    metrics_handlers = grpc.method_handlers_generic_handler(
        "tpk.Metrics", {
            "Prometheus": _identity_handler(
                lambda req, ctx: servicer.forward(
                    "/tpk.Metrics/Prometheus", req, ctx)),
        })
    gserver = grpc.server(futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix="tpk-grpc-router"))
    gserver.add_generic_rpc_handlers((handlers, metrics_handlers))
    bound = gserver.add_insecure_port(f"127.0.0.1:{port}")
    if bound == 0:
        raise RuntimeError(f"cannot bind router gRPC port {port}")
    return gserver, bound
