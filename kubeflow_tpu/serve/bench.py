"""Serving benchmark harness (VERDICT r2 item 3): measured numbers for the
TPU-native serving stack — decode throughput vs slot count, TTFT per
prefill bucket, chunked-prefill admission cost, length-aware decode-bucket
speedup, int8-weight-only vs bf16 delta, and batcher latency percentiles.

`python bench.py --serve` runs it on whatever chip is present and writes
`SERVEBENCH.json`; the regression test pins the harness on a tiny config.
The reference inherits vLLM's numbers for its huggingfaceserver
⟨kserve: python/huggingfaceserver⟩ — this is the artifact that lets the
TPU stack's claims be checked instead of asserted.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _build_model(size: str):
    from kubeflow_tpu.models.llama import (Llama, llama_1b, llama_tiny)

    import dataclasses
    if size == "tiny":
        cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                                  num_layers=2)
    else:
        cfg = llama_1b()
    model = Llama(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = jax.jit(lambda r: model.init(r, toks)["params"])(
        jax.random.key(0))
    return model, params, cfg


def _drain(engine, prompts, max_tokens, per_prompt_kwargs=None):
    """Submit all prompts concurrently; return wall seconds start→last.
    `per_prompt_kwargs` (optional, one dict per prompt) rides into each
    submit — e.g. per-request adapter selection."""
    done = []
    errs = []
    kws = per_prompt_kwargs or [{}] * len(prompts)

    def run(p, kw):
        try:
            done.append(engine.submit(p, max_tokens=max_tokens, **kw))
        except Exception as e:  # pragma: no cover - surfaced in result
            errs.append(str(e))

    threads = [threading.Thread(target=run, args=(p, kw))
               for p, kw in zip(prompts, kws)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    dt = time.monotonic() - t0
    if errs:
        raise RuntimeError(f"servebench requests failed: {errs[:3]}")
    return dt, done


def bench_decode_slots(model, params, cfg, *, slots_list: Sequence[int],
                      max_len: int, chunk: int, buckets, decode_tokens: int,
                      rng: np.random.Generator) -> dict:
    """Decode tok/s at each concurrency: N greedy requests on N slots."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    out = {}
    for slots in slots_list:
        eng = GenerationEngine(model, params, cfg, slots=slots,
                               max_len=max_len, chunk=chunk,
                               prefill_buckets=buckets, prefix_cache=0)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 16))
                       for _ in range(slots)]
            _drain(eng, prompts, decode_tokens)
            s = eng.stats
            out[f"slots_{slots}"] = {
                "decode_tok_s": round(s["decode_tokens"]
                                      / max(s["decode_seconds"], 1e-9), 1),
                "decode_dispatches": s["decode_dispatches"],
            }
        finally:
            eng.close()
    return out


def bench_decode_buckets(model, params, cfg, *, max_len: int, chunk: int,
                         buckets, decode_tokens: int,
                         rng: np.random.Generator) -> dict:
    """Length-aware decode win: short conversations on bucketed vs flat
    (max_len-wide) decode — the VERDICT r2 item 4 'measured speedup'."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    res = {}
    for label, dbuckets in (("bucketed", None), ("flat", [max_len])):
        eng = GenerationEngine(model, params, cfg, slots=4, max_len=max_len,
                               chunk=chunk, prefill_buckets=buckets,
                               decode_buckets=dbuckets, prefix_cache=0)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 8))
                       for _ in range(4)]
            _drain(eng, prompts, decode_tokens)
            s = eng.stats
            res[label] = s["decode_tokens"] / max(s["decode_seconds"], 1e-9)
        finally:
            eng.close()
    return {
        "bucketed_tok_s": round(res["bucketed"], 1),
        "flat_tok_s": round(res["flat"], 1),
        "speedup": round(res["bucketed"] / max(res["flat"], 1e-9), 3),
    }


def bench_ttft(model, params, cfg, *, max_len: int, chunk: int, buckets,
               rng: np.random.Generator) -> dict:
    """Time-to-first-token per prefill bucket (1 generated token), plus
    the chunked-admission cost of a prompt past the largest bucket."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    eng = GenerationEngine(model, params, cfg, slots=1, max_len=max_len,
                           chunk=chunk, prefill_buckets=buckets,
                           prefix_cache=0)
    ttft = {}
    try:
        for b in eng.prefill_buckets:
            n = max(b - 1, 1)
            lat = []
            for _ in range(3):
                r = eng.submit(list(rng.integers(1, cfg.vocab_size, n)),
                               max_tokens=1)
                lat.append(r["latency_s"])
            ttft[str(b)] = round(min(lat), 4)
        chunked = {}
        big = eng.prefill_buckets[-1]
        if big < max_len - 1:  # chunked-prefill reachable
            n = min(2 * big + big // 2, max_len - 1)
            lat = []
            for _ in range(3):
                r = eng.submit(list(rng.integers(1, cfg.vocab_size, n)),
                               max_tokens=1)
                lat.append(r["latency_s"])
            chunked = {"prompt_len": n, "admission_s": round(min(lat), 4)}
    finally:
        eng.close()
    return {"ttft_s": ttft, "chunked_prefill": chunked}


def bench_quant(model, params, cfg, *, max_len: int, chunk: int, buckets,
                decode_tokens: int, rng: np.random.Generator) -> dict:
    """Weight-only int8 vs bf16 decode throughput + HBM saving.

    Three arms since the dequant-placement fix (ROADMAP item 4 first
    half): `int8` is the FIXED path (Int8DenseGeneral — raw-int8 matmul
    operand, output-side scale, no full-weight dequant anywhere in the
    program), `int8_legacy` the old dequantize-per-apply wrapper that
    SERVEBENCH pinned at 0.747x bf16 (the per-step full-weight multiply
    inside the decode scan). The HLO-shape guard in
    tests/test_quant_dequant.py pins the mechanism on CPU; this row
    records the throughput outcome whenever a chip window runs it."""
    from kubeflow_tpu.serve.generation import GenerationEngine
    from kubeflow_tpu.serve.quant import (QuantizedModule, quantize_tree,
                                          quantized_bytes)

    res = {}
    qparams = quantize_tree(params)
    sizes = quantized_bytes(qparams)
    for label, m, p in (
            ("bf16", model, params),
            ("int8", QuantizedModule(model, cfg.dtype), qparams),
            ("int8_legacy",
             QuantizedModule(model, cfg.dtype, legacy_dequant=True),
             qparams)):
        eng = GenerationEngine(m, p, cfg, slots=4, max_len=max_len,
                               chunk=chunk, prefill_buckets=buckets,
                               prefix_cache=0)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 16))
                       for _ in range(4)]
            _drain(eng, prompts, decode_tokens)
            s = eng.stats
            res[label] = s["decode_tokens"] / max(s["decode_seconds"], 1e-9)
        finally:
            eng.close()
    return {
        "bf16_tok_s": round(res["bf16"], 1),
        "int8_tok_s": round(res["int8"], 1),
        "int8_legacy_tok_s": round(res["int8_legacy"], 1),
        "int8_vs_bf16": round(res["int8"] / max(res["bf16"], 1e-9), 3),
        "int8_legacy_vs_bf16": round(
            res["int8_legacy"] / max(res["bf16"], 1e-9), 3),
        "fixed_vs_legacy": round(
            res["int8"] / max(res["int8_legacy"], 1e-9), 3),
        "param_bytes": sizes,
    }


def bench_decode_buckets_long(model, params, cfg, *, max_len: int,
                              chunk: int, decode_tokens: int,
                              rng: np.random.Generator) -> dict:
    """The bucketed-decode row at a length where the feature can show
    value (VERDICT r4 weak #3: at max_len 512 the 1.03x reading was
    non-evidence): short conversations on a LONG-max_len engine — flat
    decode pays max_len-wide attention for every token, bucketed pays
    only the smallest bucket covering the active sequences."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    res = {}
    for label, dbuckets in (("bucketed", None), ("flat", [max_len])):
        eng = GenerationEngine(model, params, cfg, slots=4, max_len=max_len,
                               chunk=chunk, prefill_buckets=(32,),
                               decode_buckets=dbuckets, prefix_cache=0)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 8))
                       for _ in range(4)]
            _drain(eng, prompts, decode_tokens)
            s = eng.stats
            res[label] = s["decode_tokens"] / max(s["decode_seconds"], 1e-9)
        finally:
            eng.close()
    return {
        "max_len": max_len,
        "bucketed_tok_s": round(res["bucketed"], 1),
        "flat_tok_s": round(res["flat"], 1),
        "speedup": round(res["bucketed"] / max(res["flat"], 1e-9), 3),
    }


def bench_spec_decode(model, params, cfg, *, max_len: int, chunk: int,
                      buckets, decode_tokens: int,
                      rng: np.random.Generator, draft_layers: int = 2,
                      draft_hidden: int = 256) -> dict:
    """Speculative decoding measured, not asserted (VERDICT r4 weak #3):
    greedy decode tok/s for vanilla, a SELF-draft (draft == target —
    acceptance ~= 1, the mechanism's speedup ceiling at gamma), and a
    small random-weight draft (acceptance ~= chance — the floor; real
    draft checkpoints land between). Acceptance rates reported so the
    reader can weigh both."""
    import dataclasses

    from kubeflow_tpu.models.llama import Llama
    from kubeflow_tpu.serve.generation import GenerationEngine

    dcfg = dataclasses.replace(
        cfg, hidden_size=draft_hidden,
        intermediate_size=int(draft_hidden * 2.75) // 2 * 2,
        num_layers=draft_layers, num_heads=4, num_kv_heads=2,
        head_dim=draft_hidden // 4)
    dmodel = Llama(dcfg)
    dparams = jax.jit(lambda r: dmodel.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(jax.random.key(3))

    out: dict[str, Any] = {"gamma": 4, "draft_params": dcfg.num_params}
    variants = (
        ("vanilla", None),
        ("self_draft", {"model": model, "params": params, "cfg": cfg,
                        "gamma": 4}),
        ("small_draft", {"model": dmodel, "params": dparams, "cfg": dcfg,
                         "gamma": 4}),
    )
    for label, draft in variants:
        eng = GenerationEngine(model, params, cfg, slots=2, max_len=max_len,
                               chunk=chunk, prefill_buckets=buckets,
                               prefix_cache=0, draft=draft)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 16))
                       for _ in range(2)]
            _drain(eng, prompts, decode_tokens)
            s = eng.stats
            row = {"tok_s": round(s["decode_tokens"]
                                  / max(s["decode_seconds"], 1e-9), 1)}
            if draft is not None:
                row["acceptance"] = round(
                    s["spec_accepted"] / max(s["spec_proposed"], 1), 3)
                row["spec_dispatches"] = s["spec_dispatches"]
            out[label] = row
        finally:
            eng.close()
    out["self_draft_speedup"] = round(
        out["self_draft"]["tok_s"] / max(out["vanilla"]["tok_s"], 1e-9), 3)
    out["small_draft_speedup"] = round(
        out["small_draft"]["tok_s"] / max(out["vanilla"]["tok_s"], 1e-9), 3)
    return out


def _synth_adapter_dir(cfg, path: str, seed: int, r: int = 8) -> str:
    """Write a synthetic PEFT-format LoRA adapter (q/v targets) for the
    bench model — torch-free, so the chip bench never pays a 0.9B torch
    materialization just to exercise the multi-LoRA path."""
    import json
    import os

    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    g = np.random.default_rng(seed)
    tensors = {}
    for i in range(cfg.num_layers):
        for mod, out_dim in (("q_proj", cfg.num_heads * cfg.head_dim),
                             ("v_proj", cfg.num_kv_heads * cfg.head_dim)):
            pre = f"base_model.model.model.layers.{i}.self_attn.{mod}"
            tensors[f"{pre}.lora_A.weight"] = (
                g.normal(0, 0.02, (r, cfg.hidden_size)).astype(np.float32))
            tensors[f"{pre}.lora_B.weight"] = (
                g.normal(0, 0.02, (out_dim, r)).astype(np.float32))
    save_file(tensors, os.path.join(path, "adapter_model.safetensors"))
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump({"peft_type": "LORA", "r": r, "lora_alpha": 2 * r,
                   "target_modules": ["q_proj", "v_proj"],
                   "bias": "none"}, f)
    return path


def bench_multilora(model, params, cfg, *, max_len: int, chunk: int,
                    buckets, decode_tokens: int, rng: np.random.Generator,
                    workdir: str) -> dict:
    """Mixed-adapter batch throughput vs base-only (VERDICT r4 weak #3):
    4 concurrent requests — 2 base, 1 each on two rank-8 adapters —
    against the same 4 requests on a no-adapter engine. The delta is the
    cost of the per-row gather + rank-r delta einsums riding every
    dispatch."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    a1 = _synth_adapter_dir(cfg, f"{workdir}/ml_a", 11)
    a2 = _synth_adapter_dir(cfg, f"{workdir}/ml_b", 12)
    res = {}
    for label, adapters in (("base", None),
                            ("multilora", {"a": a1, "b": a2})):
        eng = GenerationEngine(model, params, cfg, slots=4, max_len=max_len,
                               chunk=chunk, prefill_buckets=buckets,
                               prefix_cache=0, adapters=adapters)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 16))
                       for _ in range(4)]
            names = [None, None, "a", "b"] if adapters else [None] * 4
            _drain(eng, prompts, decode_tokens,
                   per_prompt_kwargs=[{"adapter": ad} for ad in names])
            s = eng.stats
            res[label] = s["decode_tokens"] / max(s["decode_seconds"], 1e-9)
        finally:
            eng.close()
    return {
        "base_tok_s": round(res["base"], 1),
        "mixed_adapter_tok_s": round(res["multilora"], 1),
        "multilora_vs_base": round(
            res["multilora"] / max(res["base"], 1e-9), 3),
    }


def bench_pipelined_vs_sync(model, params, cfg, *, slots: int,
                            max_len: int, chunk: int, buckets,
                            decode_tokens: int,
                            rng: np.random.Generator) -> dict:
    """ISSUE 3 tentpole A/B: the overlapped engine (in-flight decode
    pipelining + off-critical-path admission, `pipeline_depth=2`) against
    the synchronous loop (`pipeline_depth=1`, the escape hatch that IS
    the old engine) on identical traffic — 2 waves of requests so
    admission overlaps in-flight decode. On the axon tunnel every
    synchronous chunk fetch pays the ~66 ms RTT (PROFILE.md §1/§5);
    depth 2 hides it behind the next in-flight chunk. `host_stall_s` and
    the blocking/overlapped fetch split prove the MECHANISM (the stall
    left the loop), `wall_s`/`tok_s_e2e` the outcome. Measurement is
    fetch-synced per the §1 hygiene rule: the wall clock closes when the
    last request's final tokens have been fetched to the host."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    res: dict[str, Any] = {}
    for label, depth in (("sync_depth1", 1), ("pipelined_depth2", 2)):
        eng = GenerationEngine(model, params, cfg, slots=slots,
                               max_len=max_len, chunk=chunk,
                               prefill_buckets=buckets, prefix_cache=0,
                               pipeline_depth=depth)
        try:
            prompts = [list(rng.integers(1, cfg.vocab_size, 16))
                       for _ in range(2 * slots)]
            dt, done = _drain(eng, prompts, decode_tokens)
            s = eng.stats
            emitted = sum(r["num_output_tokens"] for r in done)
            res[label] = {
                "pipeline_depth": depth,
                "wall_s": round(dt, 4),
                # Wall-anchored: under overlap the engine-busy clock
                # (decode_seconds) absorbs admission time the sync loop
                # spends elsewhere, so emitted/wall is the only tok/s
                # comparable across the two modes.
                "tok_s_e2e": round(emitted / max(dt, 1e-9), 1),
                "host_stall_s": round(s["host_stall_seconds"], 4),
                "decode_dispatches": s["decode_dispatches"],
                "blocking_fetches": s["decode_fetch_blocking"],
                "overlapped_fetches": s["decode_fetch_overlapped"],
                "admit_overlap": s["admit_overlap"],
                "wasted_tokens": s["decode_wasted_tokens"],
            }
        finally:
            eng.close()
    res["speedup_wall"] = round(
        res["sync_depth1"]["wall_s"]
        / max(res["pipelined_depth2"]["wall_s"], 1e-9), 3)
    res["host_stall_removed_s"] = round(
        res["sync_depth1"]["host_stall_s"]
        - res["pipelined_depth2"]["host_stall_s"], 4)
    return res


def bench_paged_vs_flat(model, params, cfg, *, slots: int, max_len: int,
                        chunk: int, buckets, decode_tokens: int,
                        rng: np.random.Generator) -> dict:
    """ISSUE 6 tentpole A/B: block-paged KV cache against the flat
    slot-contiguous cache on a mixed-length request set, at EQUAL pool
    memory (the paged pool holds exactly `slots x max_len` tokens, the
    flat engine's footprint) but double the decode width — the paged
    engine admits by free-block accounting, so short requests coexist
    where flat mode pins worst-case rows. `peak_inflight_requests` is
    the mechanism proof (more concurrent rows than flat slots in the
    same memory); wall/tok_s the outcome. Fetch-synced per PROFILE §1:
    _drain returns when every request's tokens are host-side."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    bs = 16  # divides max_len and every power-of-two decode bucket
    pool_blocks = slots * max_len // bs
    n_req = 4 * slots
    prompts = [list(rng.integers(
        1, cfg.vocab_size, int(rng.integers(8, max(10, max_len // 8)))))
        for _ in range(n_req)]
    res: dict[str, Any] = {}
    for label, kw, width in (
            ("flat", {}, slots),
            ("paged", {"kv_block_size": bs, "kv_blocks": pool_blocks},
             2 * slots)):
        eng = GenerationEngine(model, params, cfg, slots=width,
                               max_len=max_len, chunk=chunk,
                               prefill_buckets=buckets, prefix_cache=0,
                               pipeline_depth=2, **kw)
        peak = [0]
        orig = eng._dispatch_chunk

        def spy(active, carry=None, _orig=orig, _peak=peak):
            _peak[0] = max(_peak[0], len(active))
            return _orig(active, carry)

        eng._dispatch_chunk = spy
        try:
            dt, done = _drain(eng, prompts, decode_tokens)
            s = eng.stats
            emitted = sum(r["num_output_tokens"] for r in done)
            res[label] = {
                "slots": width,
                "pool_tokens": slots * max_len,
                "requests": n_req,
                "wall_s": round(dt, 4),
                "tok_s_e2e": round(emitted / max(dt, 1e-9), 1),
                "decode_dispatches": s["decode_dispatches"],
                "peak_inflight_requests": peak[0],
            }
            if label == "paged":
                res[label]["kv_block_size"] = bs
                res[label]["kv_blocks"] = pool_blocks
        finally:
            eng.close()
    res["speedup_wall"] = round(
        res["flat"]["wall_s"] / max(res["paged"]["wall_s"], 1e-9), 3)
    res["concurrency_gain"] = round(
        res["paged"]["peak_inflight_requests"]
        / max(res["flat"]["peak_inflight_requests"], 1), 3)
    return res


def bench_quant_paged(model, params, cfg, *, slots: int, max_len: int,
                      chunk: int, buckets, decode_tokens: int,
                      rng: np.random.Generator) -> dict:
    """ISSUE 19 tentpole A/B: int8 KV pool against the full-precision
    paged pool at EQUAL pool HBM — the quantized arm's block count is
    scaled by the per-token byte ratio (D·itemsize vs D+4 with the f32
    scale, ≈2x at bf16/D=64), floor-rounded so its pool never exceeds
    the full arm's bytes, and its decode width doubled again so the
    extra blocks can become extra concurrent requests.
    `peak_inflight_requests` is the mechanism proof (the quant pool
    RUNS more requests in the same memory); wall/tok_s the outcome.
    Two side rows make the rest of the claim: the same greedy probe
    through both arms (quality delta = max per-token |Δlogprob| —
    measured, not asserted) and one prefill handoff per arm (fmt-3
    wire bytes vs fmt-1 for the identical prompt). Fetch-synced per
    PROFILE §1: _drain returns when every token is host-side."""
    from kubeflow_tpu.serve.generation import GenerationEngine
    from kubeflow_tpu.serve.kv_transfer import peek_meta

    bs = 16  # divides max_len and every power-of-two decode bucket
    d = int(cfg.head_dim)
    fitem = int(jnp.dtype(cfg.dtype).itemsize)
    pool_blocks = slots * max_len // bs
    # Equal HBM: int8 rows cost D bytes + one f32 scale per row-head.
    q_blocks = pool_blocks * (d * fitem) // (d + 4)
    n_req = 8 * slots
    prompts = [list(rng.integers(
        1, cfg.vocab_size, int(rng.integers(8, max(10, max_len // 8)))))
        for _ in range(n_req)]
    probe = list(rng.integers(1, cfg.vocab_size, 16))
    res: dict[str, Any] = {}
    ident: dict[str, Any] = {}
    for label, kw, width, blocks in (
            ("full_paged", {}, 2 * slots, pool_blocks),
            ("quant_paged", {"kv_quant": "int8"}, 4 * slots, q_blocks)):
        eng = GenerationEngine(model, params, cfg, slots=width,
                               max_len=max_len, chunk=chunk,
                               prefill_buckets=buckets, prefix_cache=0,
                               pipeline_depth=2, kv_block_size=bs,
                               kv_blocks=blocks, **kw)
        peak = [0]
        orig = eng._dispatch_chunk

        def spy(active, carry=None, _orig=orig, _peak=peak):
            _peak[0] = max(_peak[0], len(active))
            return _orig(active, carry)

        eng._dispatch_chunk = spy
        try:
            dt, done = _drain(eng, prompts, decode_tokens)
            s = eng.stats
            emitted = sum(r["num_output_tokens"] for r in done)
            res[label] = {
                "slots": width,
                "kv_block_size": bs,
                "kv_blocks": blocks,
                # Measured, not derived: the device pool's actual bytes
                # (values + scale planes + the reserved garbage block).
                "pool_bytes": int(sum(np.asarray(a).nbytes
                                      for a in eng._cache.values())),
                "requests": n_req,
                "wall_s": round(dt, 4),
                "tok_s_e2e": round(emitted / max(dt, 1e-9), 1),
                "decode_dispatches": s["decode_dispatches"],
                "peak_inflight_requests": peak[0],
            }
            out = eng.submit(probe, max_tokens=decode_tokens,
                             temperature=0.0)
            ident[label] = (out["output_ids"], out["output_logprobs"])
        finally:
            eng.close()
    res["kv_blocks_ratio"] = round(q_blocks / max(pool_blocks, 1), 3)
    res["concurrency_gain"] = round(
        res["quant_paged"]["peak_inflight_requests"]
        / max(res["full_paged"]["peak_inflight_requests"], 1), 3)
    res["speedup_wall"] = round(
        res["full_paged"]["wall_s"]
        / max(res["quant_paged"]["wall_s"], 1e-9), 3)
    ids_f, lps_f = ident["full_paged"]
    ids_q, lps_q = ident["quant_paged"]
    res["quality"] = {
        "probe_tokens": len(ids_f),
        "greedy_ids_identical": bool(ids_f == ids_q),
        "max_logprob_delta": round(max(
            abs(a - b) for a, b in zip(lps_f, lps_q)), 5),
    }
    # Wire row: one prefill handoff per arm, identical prompt — the
    # quantized shipment (fmt 3) against the full-precision fmt 1.
    wire: dict[str, Any] = {}
    ship_prompt = list(rng.integers(1, cfg.vocab_size, 24))
    for label, kw in (("fmt1_bytes", {}),
                      ("fmt3_bytes", {"kv_quant": "int8"})):
        eng = GenerationEngine(model, params, cfg, slots=1,
                               max_len=max_len, chunk=chunk,
                               prefill_buckets=buckets, prefix_cache=0,
                               role="prefill", kv_block_size=bs,
                               kv_blocks=pool_blocks, **kw)
        try:
            ship = eng.prefill_ship(ship_prompt,
                                    max_tokens=decode_tokens)
            wire[label] = len(ship["shipment"])
            wire[label.replace("bytes", "fmt")] = peek_meta(
                ship["shipment"])["fmt"]
        finally:
            eng.close()
    wire["fmt3_vs_fmt1"] = round(
        wire["fmt3_bytes"] / max(wire["fmt1_bytes"], 1), 3)
    res["wire"] = wire
    return res


def bench_spec_paged(model, params, cfg, *, slots: int, max_len: int,
                     chunk: int, buckets, decode_tokens: int,
                     rng: np.random.Generator) -> dict:
    """ISSUE 18 tentpole A/B: speculative decoding composed with the
    paged engine at pipeline_depth=2 — vanilla-paged vs spec-paged
    (self-draft: the acceptance≈1 mechanism ceiling) on identical
    seeded MIXED traffic, greedy rows plus one top-p row per wave, so
    the per-sub-batch dispatch is what's measured (the old batch-wide
    gate would zero speculation on exactly this traffic). The pool
    carries both footprints (target + per-slot draft rows).
    Fetch-synced per PROFILE §1: _drain returns when every request's
    tokens are host-side. After the timed waves the SAME greedy prompt
    runs through both engines — the spec output must be token+logprob-
    identical, the lossless claim measured on the composed path."""
    from kubeflow_tpu.serve.generation import GenerationEngine

    bs = 16  # divides max_len and every power-of-two decode bucket
    # Worst-case admission reserve doubles under speculation (the
    # draft's per-slot rows live in the same pool) — size it so `slots`
    # spec-able requests still fit concurrently.
    pool_blocks = 2 * slots * max_len // bs
    n_req = 2 * slots
    prompts = [list(rng.integers(1, cfg.vocab_size, 16))
               for _ in range(n_req)]
    kws: list[dict] = [{"temperature": 0.0}] * (n_req - 1)
    kws.append({"temperature": 0.9, "top_p": 0.9})
    probe = list(rng.integers(1, cfg.vocab_size, 16))
    res: dict[str, Any] = {}
    ident: dict[str, Any] = {}
    for label, draft in (
            ("vanilla_paged", None),
            ("spec_paged", {"model": model, "params": params,
                            "cfg": cfg, "gamma": 4})):
        eng = GenerationEngine(model, params, cfg, slots=slots,
                               max_len=max_len, chunk=chunk,
                               prefill_buckets=buckets, prefix_cache=0,
                               pipeline_depth=2, kv_block_size=bs,
                               kv_blocks=pool_blocks, draft=draft)
        try:
            dt, done = _drain(eng, prompts, decode_tokens,
                              per_prompt_kwargs=kws)
            s = eng.stats
            emitted = sum(r["num_output_tokens"] for r in done)
            row: dict[str, Any] = {
                "pipeline_depth": 2,
                "kv_block_size": bs,
                "kv_blocks": pool_blocks,
                "requests": n_req,
                "wall_s": round(dt, 4),
                "tok_s_e2e": round(emitted / max(dt, 1e-9), 1),
                "decode_dispatches": s["decode_dispatches"],
            }
            if draft is not None:
                row["spec_dispatches"] = s["spec_dispatches"]
                row["spec_proposed"] = s["spec_proposed"]
                row["spec_accepted"] = s["spec_accepted"]
                row["spec_stale_rides"] = s["spec_stale_rides"]
                row["acceptance"] = round(
                    s["spec_accepted"] / max(s["spec_proposed"], 1), 3)
            res[label] = row
            out = eng.submit(probe, max_tokens=decode_tokens,
                             temperature=0.0)
            ident[label] = (out["output_ids"], out["output_logprobs"])
        finally:
            eng.close()
    res["speedup_wall"] = round(
        res["vanilla_paged"]["wall_s"]
        / max(res["spec_paged"]["wall_s"], 1e-9), 3)
    ids_v, lps_v = ident["vanilla_paged"]
    ids_s, lps_s = ident["spec_paged"]
    res["greedy_identical"] = bool(
        ids_v == ids_s and np.allclose(lps_v, lps_s, rtol=1e-4,
                                       atol=1e-5))
    # The sub-batch split proof: a top-p row rode every wave, yet the
    # greedy rows still proposed and accepted draft tokens.
    res["mixed_traffic_speculated"] = bool(
        res["spec_paged"]["spec_dispatches"] > 0
        and res["spec_paged"]["spec_accepted"] > 0)
    return res


def bench_batcher(*, requests: int = 200, threads: int = 8,
                  max_batch_size: int = 32,
                  max_latency_ms: float = 2.0) -> dict:
    """Adaptive-batcher latency distribution under concurrent load, with a
    jitted matmul predictor (the BERT-predictor shape of config 3).

    Requests are [1, 256] — ONE example each, the server's request shape.
    (The r4 harness submitted rank-1 (256,) arrays; the batcher read the
    feature dim as a 256-row batch and every request took the oversized
    BYPASS — 8 threads contending on inline full predicts, zero
    coalescing. THAT was the mysterious 13x p99 tail, not the tunnel:
    PROFILE.md §5.) The predictor pads coalesced batches to power-of-two
    buckets and warms them, like the server's AOT predictors — jit
    recompiles per distinct batch size would otherwise ride the tail."""
    from kubeflow_tpu.serve.batcher import Batcher

    w = jax.random.normal(jax.random.key(1), (256, 256), jnp.float32)

    @jax.jit
    def fwd(x):
        return jnp.tanh(x @ w) @ w

    def predict(inputs):
        x = np.asarray(inputs[0])
        n = x.shape[0]
        b = 1
        while b < n:
            b *= 2
        xp = np.zeros((b,) + x.shape[1:], x.dtype)
        xp[:n] = x
        return [np.asarray(fwd(jnp.asarray(xp)))[:n]]

    b = 1
    while b <= max_batch_size:  # warm the bucket set (AOT-load analog)
        predict([np.zeros((b, 256), np.float32)])
        b *= 2

    batcher = Batcher(predict, max_batch_size=max_batch_size,
                      max_latency_ms=max_latency_ms)
    lat: list[float] = []
    lock = threading.Lock()
    x = np.zeros((1, 256), np.float32)

    def worker(n):
        for _ in range(n):
            t0 = time.monotonic()
            batcher.submit([x]).result(timeout=60)
            dt = time.monotonic() - t0
            with lock:
                lat.append(dt)

    ths = [threading.Thread(target=worker, args=(requests // threads,))
           for _ in range(threads)]
    t0 = time.monotonic()
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=120)
    wall = time.monotonic() - t0
    stats = dict(batcher.stats)
    batcher.close()
    arr = np.asarray(lat) * 1e3
    return {
        "requests": len(lat),
        "throughput_rps": round(len(lat) / wall, 1),
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "coalesced_batches": stats["batches"],
        "examples_per_batch": round(
            stats["examples"] / max(stats["batches"], 1), 2),
    }


def run_servebench(*, size: str = "1b", quick: bool = False,
                   workdir: str = "/tmp/tpk_servebench") -> dict:
    """The full serving benchmark. `size="tiny"`/`quick` is the CI/
    regression shape; the driver's chip run uses the 0.9B bench model.

    The chip config is deliberately lean on AOT surface — every engine
    pays its full warmup compile set, so buckets/slots variants are the
    compile-time budget (each engine ≈ prefill+extend+2·decode-buckets
    executables at 20-40 s/compile on the axon tunnel)."""
    import sys

    if quick:
        max_len, chunk, buckets = 96, 4, (8, 16)
        slots_list: Sequence[int] = (1, 2)
        decode_tokens = 12
        batcher_reqs = 64
        long_max_len = 256
    else:
        max_len, chunk, buckets = 512, 16, (32, 128)
        slots_list = (1, 4)
        decode_tokens = 96
        batcher_reqs = 200
        long_max_len = 2048

    def log(stage):
        print(f"servebench: {stage}", file=sys.stderr, flush=True)

    log(f"building model ({size})")
    model, params, cfg = _build_model(size)
    rng = np.random.default_rng(0)

    result: dict[str, Any] = {
        "metric": "serving",
        "model": size,
        "model_params": cfg.num_params,
        "device_kind": jax.devices()[0].device_kind,
        "max_len": max_len,
        "chunk": chunk,
        "prefill_buckets": list(buckets),
    }
    log("pipelined vs sync engine (overlapped scheduling A/B)")
    result["pipelined_vs_sync"] = bench_pipelined_vs_sync(
        model, params, cfg, slots=2 if quick else 4, max_len=max_len,
        chunk=chunk, buckets=buckets, decode_tokens=decode_tokens, rng=rng)
    log("paged vs flat KV cache (block-table memory A/B)")
    result["paged_vs_flat"] = bench_paged_vs_flat(
        model, params, cfg, slots=2 if quick else 4, max_len=max_len,
        chunk=chunk, buckets=buckets, decode_tokens=decode_tokens, rng=rng)
    log("quantized vs full-precision KV pool (equal-HBM A/B)")
    result["quant_paged"] = bench_quant_paged(
        model, params, cfg, slots=2 if quick else 4, max_len=max_len,
        chunk=chunk, buckets=buckets, decode_tokens=decode_tokens, rng=rng)
    log("spec x paged at depth 2 (speculation composition A/B)")
    result["spec_paged"] = bench_spec_paged(
        model, params, cfg, slots=2 if quick else 4, max_len=max_len,
        chunk=chunk, buckets=buckets, decode_tokens=decode_tokens, rng=rng)
    log("decode throughput vs slots")
    result["decode"] = bench_decode_slots(
        model, params, cfg, slots_list=slots_list, max_len=max_len,
        chunk=chunk, buckets=buckets, decode_tokens=decode_tokens, rng=rng)
    log("length-aware decode buckets")
    result["decode_buckets"] = bench_decode_buckets(
        model, params, cfg, max_len=max_len, chunk=chunk, buckets=buckets,
        decode_tokens=decode_tokens, rng=rng)
    log("ttft per prefill bucket")
    result.update(bench_ttft(model, params, cfg, max_len=max_len,
                             chunk=chunk, buckets=buckets, rng=rng))
    long_max_len = min(long_max_len, cfg.max_seq_len)
    log(f"length-aware decode at max_len {long_max_len}")
    result["decode_buckets_long"] = bench_decode_buckets_long(
        model, params, cfg, max_len=long_max_len, chunk=chunk,
        decode_tokens=decode_tokens, rng=rng)
    log("speculative decoding (vanilla / self-draft / small-draft)")
    result["spec_decode"] = bench_spec_decode(
        model, params, cfg, max_len=max_len, chunk=chunk, buckets=buckets,
        decode_tokens=decode_tokens, rng=rng)
    log("multi-LoRA mixed-adapter batch")
    result["multilora"] = bench_multilora(
        model, params, cfg, max_len=max_len, chunk=chunk, buckets=buckets,
        decode_tokens=decode_tokens, rng=rng, workdir=workdir)
    log("int8 vs bf16")
    result["quant"] = bench_quant(
        model, params, cfg, max_len=max_len, chunk=chunk, buckets=buckets,
        decode_tokens=decode_tokens, rng=rng)
    log("batcher percentiles")
    result["batcher"] = bench_batcher(requests=batcher_reqs)
    return result
