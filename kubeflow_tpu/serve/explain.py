"""Explainers — the KServe explainer component, TPU-native.

The reference's InferenceService explainer (SURVEY.md §2.2 ⟨kserve:
pkg/controller/.../explainer, python alibiexplainer⟩) is a sidecar service
wrapping CPU explanation libraries (Alibi anchors, Captum). Neither ships
in this image, and a poll-the-model-N-times CPU loop is the wrong shape
for TPU serving anyway. These explainers are the native equivalents,
designed so the explanation path rides the same AOT/MXU machinery as
predict:

  * `OcclusionExplainer` — model-agnostic token attribution: occlude each
    position (replace with a baseline id) and measure the drop in the
    predicted class's logit. All S occluded variants plus the original go
    through the model's own bucketed `predict` as ONE batch — the
    explanation is S+1 rows of the serving executable, not S+1 requests.
  * `IntegratedGradientsExplainer` — for continuous inputs: jitted IG
    along the straight-line path from a baseline, the whole Riemann sum
    one `lax.scan` under `jit` (gradients on device, no Python loop).
    Satisfies the completeness axiom: attributions sum to
    f(x) - f(baseline) (asserted in tests to ~1%).

Served via `POST /v1/models/{name}:explain` (server.py) with the v1 body
(`{"instances": [...]}`), responding `{"explanations": [...]}` — request
shape mirrors the reference's v1 explain protocol.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class OcclusionExplainer:
    """Per-position occlusion attribution for token-classifier models.

    attribution[s] = logit_target(x) - logit_target(x with x[s]:=baseline)
    — positive means the token supports the predicted class. `target` is
    the argmax class of the unoccluded row (per instance).
    """

    method = "occlusion"

    def __init__(self, baseline_id: int = 0):
        self.baseline_id = int(baseline_id)

    def explain(self, model, instances: np.ndarray) -> list[dict]:
        x = np.asarray(instances)
        if x.ndim != 2 or not np.issubdtype(x.dtype, np.integer):
            raise ValueError(
                "occlusion explains integer token batches [B, S]; got "
                f"shape {x.shape} dtype {x.dtype}")
        b, s = x.shape
        out = []
        for row in x:
            variants = np.tile(row, (s + 1, 1))
            for i in range(s):
                variants[i + 1, i] = self.baseline_id
            logits = model.predict([variants])[-1]
            if logits.ndim != 2:
                raise ValueError(
                    "occlusion needs per-instance class logits [B, C]; "
                    f"model returned shape {logits.shape} (sequence-level "
                    "heads are not class explanations)")
            target = int(np.argmax(logits[0]))
            attr = logits[0, target] - logits[1:, target]
            out.append({
                "method": self.method,
                "target": target,
                "target_logit": float(logits[0, target]),
                "attributions": [float(a) for a in attr],
            })
        return out


class IntegratedGradientsExplainer:
    """Integrated gradients for continuous-input models, fully on device.

    IG_i(x) = (x_i - x'_i) * (1/m) * sum_k d f_target / d x_i evaluated at
    x' + (k+0.5)/m * (x - x'), with f_target the argmax logit of the real
    input (midpoint rule — halves the endpoint bias of the left Riemann
    sum at the same m). One jitted scan computes the whole sum.
    """

    method = "integrated_gradients"

    def __init__(self, steps: int = 32, baseline: Any | None = None):
        self.steps = int(steps)
        self.baseline = baseline
        self._jitted = None  # one jit; XLA's trace cache keys per shape

    def _ig_fn(self, apply_fn):
        steps = self.steps

        def ig(params, x, x0, target):
            def f(xi):
                out = apply_fn(params, xi)
                out = out[-1] if isinstance(out, (tuple, list)) else out
                # One scalar per batch row: the target-class logit.
                return jnp.take_along_axis(
                    out, target[:, None], axis=-1).sum()

            def body(acc, k):
                alpha = (k + 0.5) / steps
                g = jax.grad(f)(x0 + alpha * (x - x0))
                return acc + g, None

            total, _ = jax.lax.scan(
                body, jnp.zeros_like(x), jnp.arange(steps, dtype=x.dtype))
            return (x - x0) * total / steps

        return ig

    def explain(self, model, instances: np.ndarray) -> list[dict]:
        x = np.asarray(instances, np.float32)
        apply_fn, params = model.apply_and_params()
        x0 = (np.zeros_like(x) if self.baseline is None
              else np.broadcast_to(
                  np.asarray(self.baseline, np.float32), x.shape))
        logits = model.predict([x])[-1]
        if logits.ndim != 2:
            raise ValueError(
                "integrated_gradients needs class logits [B, C]; model "
                f"returned shape {logits.shape}")
        target = np.argmax(logits, axis=-1).astype(np.int32)
        if self._jitted is None:
            self._jitted = jax.jit(self._ig_fn(apply_fn))
        attr = np.asarray(self._jitted(params, jnp.asarray(x),
                                       jnp.asarray(x0),
                                       jnp.asarray(target)))
        base_logits = model.predict([x0.astype(np.float32)])[-1]
        return [{
            "method": self.method,
            "target": int(t),
            "target_logit": float(logits[i, t]),
            "baseline_logit": float(base_logits[i, t]),
            # Completeness: sum(attr) ~= f(x) - f(baseline); report it so
            # callers can judge whether `steps` was enough.
            "completeness_gap": float(
                (logits[i, t] - base_logits[i, t]) - attr[i].sum()),
            "attributions": attr[i].tolist(),
        } for i, t in enumerate(target)]


def build_explainer(spec: dict):
    """model.json `explainer` block → explainer instance."""
    method = spec.get("method", "occlusion")
    if method == "occlusion":
        return OcclusionExplainer(baseline_id=spec.get("baseline_id", 0))
    if method == "integrated_gradients":
        return IntegratedGradientsExplainer(
            steps=spec.get("steps", 32), baseline=spec.get("baseline"))
    raise ValueError(
        f"unknown explainer method {method!r} "
        "(have: occlusion, integrated_gradients)")
