"""Storage initializer — KServe's model-download initContainer, in-process.

The reference runs ⟨kserve: python/kserve/kserve/storage — Storage.download⟩
as an initContainer pulling s3/gcs/pvc/http URIs to /mnt/models before the
server starts (SURVEY.md §3.3). This environment has zero egress, so local
schemes are real and remote schemes fail with a clear error instead of a
silent stub.
"""

from __future__ import annotations

import os
import shutil
import tarfile
import zipfile

LOCAL_SCHEMES = ("file://", "pvc://", "")
REMOTE_SCHEMES = ("s3://", "gs://", "gcs://", "http://", "https://", "hdfs://")


def download(uri: str, dest: str) -> str:
    """Materializes `uri` under `dest`; returns the model directory path."""
    os.makedirs(dest, exist_ok=True)
    for scheme in REMOTE_SCHEMES:
        if uri.startswith(scheme):
            raise NotImplementedError(
                f"remote storage {scheme} requires network egress; mount the "
                f"model locally and use file:// (reference parity: KServe "
                f"storage-initializer would fetch this)")
    path = uri[len("file://"):] if uri.startswith("file://") else uri
    if uri.startswith("pvc://"):
        # pvc://{claim}/{path} — claims are mounted under $TPK_PVC_ROOT.
        root = os.environ.get("TPK_PVC_ROOT", "/mnt/pvc")
        path = os.path.join(root, uri[len("pvc://"):])
    if not os.path.exists(path):
        raise FileNotFoundError(f"model uri {uri!r} -> {path!r} not found")
    if os.path.isdir(path):
        return path  # local dirs are served in place, no copy
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as tf:
            tf.extractall(dest, filter="data")
        return dest
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            zf.extractall(dest)
        return dest
    shutil.copy2(path, dest)
    return dest
