"""Storage initializer — KServe's model-download initContainer, in-process.

The reference runs ⟨kserve: python/kserve/kserve/storage — Storage.download⟩
as an initContainer pulling s3/gcs/pvc/http URIs to /mnt/models before the
server starts (SURVEY.md §3.3). This environment has zero egress, so local
schemes are real and remote schemes fail with a clear error instead of a
silent stub. DESCOPE NOTE (documented, not silent): the remote half of
KServe's storage matrix — s3/gcs/http credentials, range requests, retry
policy — is the piece this build cannot exercise at all; the `download()`
signature and the archive/dir handling match the reference's contract so a
networked executor can slot a real fetcher behind the same call.

Integrity: `uri` may carry a digest fragment `#sha256=<hex>` (the OCI/
KServe-style pinning). For file/pvc sources the materialized file is
hashed and a mismatch fails loudly BEFORE anything is extracted — a
corrupt or swapped model must never reach the server. Directories cannot
be digest-pinned (no canonical serialization); passing a digest for a
directory is an error rather than a silent skip.
"""

from __future__ import annotations

import hashlib
import os
import re
import shutil
import tarfile
import zipfile

LOCAL_SCHEMES = ("file://", "pvc://", "")
REMOTE_SCHEMES = ("s3://", "gs://", "gcs://", "http://", "https://", "hdfs://")


def _split_digest(uri: str) -> tuple[str, str | None]:
    """Split a trailing `#sha256=<64-hex>` digest pin off `uri`.

    Only a fragment that is EXACTLY a sha256 digest counts as a pin.
    On remote URIs ANY other fragment (`#md5=...`, truncated/typo'd hex,
    empty) is a loud ValueError — the user clearly intended an integrity
    pin and silently shipping it to the store as part of the key would
    drop it. On local paths anything else is part of the filename ('#'
    is legal there, e.g. `data#v2/model.tar`) and a bad path already
    fails loudly as FileNotFoundError."""
    base, sep, frag = uri.rpartition("#")
    if sep and re.fullmatch(r"sha256=[0-9a-fA-F]{64}", frag):
        return base, frag[len("sha256="):].lower()
    if sep and uri.startswith(REMOTE_SCHEMES):
        raise ValueError(
            f"unsupported digest fragment {frag!r} (use #sha256=<64-hex>)")
    return uri, None


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(uri: str, dest: str) -> str:
    """Materializes `uri` under `dest`; returns the model directory path."""
    os.makedirs(dest, exist_ok=True)
    uri, digest = _split_digest(uri)
    for scheme in REMOTE_SCHEMES:
        if uri.startswith(scheme):
            raise NotImplementedError(
                f"remote storage {scheme} requires network egress; mount the "
                f"model locally and use file:// (reference parity: KServe "
                f"storage-initializer would fetch this)")
    path = uri[len("file://"):] if uri.startswith("file://") else uri
    if uri.startswith("pvc://"):
        # pvc://{claim}/{path} — claims are mounted under $TPK_PVC_ROOT.
        root = os.environ.get("TPK_PVC_ROOT", "/mnt/pvc")
        path = os.path.join(root, uri[len("pvc://"):])
    if not os.path.exists(path):
        raise FileNotFoundError(f"model uri {uri!r} -> {path!r} not found")
    if os.path.isdir(path):
        if digest:
            raise ValueError(
                f"digest pinning needs a FILE source; {path!r} is a "
                "directory (no canonical bytes to hash)")
        return path  # local dirs are served in place, no copy
    if digest:
        got = _sha256_file(path)
        if got != digest:
            raise ValueError(
                f"model digest mismatch for {uri!r}: expected sha256 "
                f"{digest}, file hashes {got} — refusing to serve a "
                "corrupt/swapped model")
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as tf:
            tf.extractall(dest, filter="data")
        return dest
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            zf.extractall(dest)
        return dest
    shutil.copy2(path, dest)
    return dest
