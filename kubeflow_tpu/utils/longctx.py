"""Long-context evidence for the single-chip bench model (SURVEY.md §5.7).

VERDICT r3 item 4: the long-context stack (chunked fused CE, flash
attention, packed masks) claims to ADMIT sequences the naive path cannot,
but the chip had only ever run s1024 (PROFILE.md §3 tried b8 s2048 and
OOM'd — the wrong batch for the claim). This module produces the evidence
both ways:

  * `analyze_fit(batch, seq)` — AOT-compile the REAL bench train step
    (llama_1b, chunked CE, full-block remat, adamw bf16-mu) on one
    virtual device and read `memory_analysis()`: the per-device working
    set vs the v5e 16 GiB HBM budget. Runs anywhere, chip or not — the
    same pre-flight arithmetic the 8B scale proof uses
    (utils/scaleproof.py).
  * `measure(batch, seq)` — the measured row (tok/s + MFU) on the live
    backend; `bench.py --longctx` runs it on the chip and falls back to
    the fit analysis (explicitly labeled) when the backend is down.

Chunked CE is what makes s>=2048 admissible at all here: the full-CE
fp32 logits buffer is B*S*V*4 bytes (b2 s2048 * 32768 vocab = 0.5 GiB
for ONE residency, and XLA keeps fwd+bwd copies), while the chunked path
peaks at B*chunk*V.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

V5E_HBM_BYTES = 16 * 1024**3
GIB = 1024**3

#: (batch, seq) points for the fit sweep; smallest-batch long-sequence
#: first — these back the "long-context-capable" claim, not throughput.
FIT_CASES = ((1, 2048), (2, 2048), (4, 2048), (1, 4096), (2, 4096),
             (1, 8192))


def _build(batch: int, seq: int, loss_impl: str = "chunked",
           size: str = "1b", loss_chunk: int = 1024,
           remat_policy: str | None = None,
           flash_block: tuple[int, int] | None = None):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models.llama import Llama, llama_1b, llama_tiny
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES
    from kubeflow_tpu.train.step import abstract_train_state, make_train_step

    # Force the flash kernel: `auto` falls back to naive off-TPU, whose
    # materialized [B,H,S,S] scores would inflate the measured temp memory
    # with buffers the TPU deployment never allocates (same rationale as
    # scaleproof's 8B cases). `size="tiny"` is the harness-pinning test
    # shape (tests/test_longctx.py).
    base = llama_1b() if size == "1b" else llama_tiny()
    # max_seq_len sizes the RoPE table; llama_1b pins 2048, and positions
    # past the table would silently CLAMP under jit (same rotary phase for
    # every tail token) — the long-context evidence must model the config
    # a real s-length deployment would run.
    cfg = dataclasses.replace(base, attention_impl="flash",
                              max_seq_len=max(seq, base.max_seq_len))
    if remat_policy:
        cfg = dataclasses.replace(cfg, remat_policy=remat_policy)
    if flash_block:
        cfg = dataclasses.replace(cfg, flash_block_q=flash_block[0],
                                  flash_block_kv=flash_block[1])
    model = Llama(cfg)
    mesh = build_mesh(MeshConfig(data=1), jax.devices()[:1])
    tx = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    _, abstract, shardings = abstract_train_state(
        model, tx, (jnp.zeros((1, 8), jnp.int32),), mesh, DEFAULT_RULES)
    state_args = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract, shardings)
    batch_args = {
        "inputs": jax.ShapeDtypeStruct((batch, seq), jnp.int32),
        "targets": jax.ShapeDtypeStruct((batch, seq), jnp.int32),
    }
    step = make_train_step(model, mesh, DEFAULT_RULES, loss_impl=loss_impl,
                           loss_chunk=loss_chunk)
    return cfg, model, mesh, tx, step, state_args, batch_args


def analyze_fit(batch: int, seq: int, loss_impl: str = "chunked",
                size: str = "1b") -> dict:
    """AOT compile + memory_analysis for one (batch, seq) point, against
    the v5e HBM budget (scaleproof's shared fit arithmetic)."""
    from kubeflow_tpu.utils.scaleproof import _mem_report

    cfg, _, mesh, _, step, state_args, batch_args = _build(
        batch, seq, loss_impl, size)
    with mesh:
        compiled = step.jitted.lower(state_args, batch_args).compile()
    report = _mem_report(compiled, hbm_bytes=V5E_HBM_BYTES, chip="v5e")
    report.update({
        "batch": batch,
        "seq_len": seq,
        "loss_impl": loss_impl,
        "model_params": cfg.num_params,
    })
    return report


def analyze_fit_subprocess(batch: int, seq: int,
                           loss_impl: str = "chunked",
                           timeout_s: float = 1800.0) -> dict:
    """Run the fit analysis in a fresh single-device CPU interpreter
    (backends can't be reconfigured after init — scaleproof pattern)."""
    from kubeflow_tpu.utils.reexec import cpu_reexec_env

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = cpu_reexec_env(1, repo=repo)
    code = (
        "import json\n"
        "from kubeflow_tpu.utils import longctx\n"
        f"r = longctx.analyze_fit({batch}, {seq}, {loss_impl!r})\n"
        "print('LONGCTX_JSON:' + json.dumps(r))\n")
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=timeout_s)
    if proc.returncode != 0:
        raise RuntimeError(
            f"longctx fit b{batch} s{seq} failed rc={proc.returncode}:\n"
            f"{proc.stderr[-3000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("LONGCTX_JSON:"):
            return json.loads(line[len("LONGCTX_JSON:"):])
    raise RuntimeError("longctx: no result line")


def measure(batch: int, seq: int, timed_steps: int = 6,
            loss_impl: str = "chunked", size: str = "1b",
            loss_chunk: int = 1024, remat_policy: str | None = None,
            flash_block: tuple[int, int] | None = None) -> dict:
    """Measured tok/s + MFU at (batch, seq) on the live backend — the
    PROFILE.md §6 row. Pipelined timing, single fetch at the end (the
    axon tunnel adds ~66 ms to every synchronous host fetch). The knob
    kwargs (loss_chunk / remat_policy / flash_block) back the tuning
    sweep (`tune_point`)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES
    from kubeflow_tpu.train.metrics import peak_flops_per_chip
    from kubeflow_tpu.train.step import init_train_state

    cfg, model, mesh, tx, step, _, _ = _build(
        batch, seq, loss_impl, size, loss_chunk=loss_chunk,
        remat_policy=remat_policy, flash_block=flash_block)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    state = init_train_state(model, tx, jax.random.key(0), (tokens,), mesh,
                             DEFAULT_RULES)
    rng = np.random.default_rng(0)

    def make_batch():
        return {
            "inputs": rng.integers(0, cfg.vocab_size, (batch, seq),
                                   dtype=np.int32),
            "targets": rng.integers(0, cfg.vocab_size, (batch, seq),
                                    dtype=np.int32),
        }

    for _ in range(3):  # compile + steady-state warmup
        state, metrics = step(state, make_batch())
        float(metrics["loss"])
    batches = [make_batch() for _ in range(timed_steps)]
    t0 = time.perf_counter()
    for b in batches:
        state, metrics = step(state, b)
    float(metrics["loss"])  # force completion of the chain
    dt = (time.perf_counter() - t0) / timed_steps
    mfu = 6 * cfg.num_params * batch * seq / dt / peak_flops_per_chip()
    return {
        "batch": batch,
        "seq_len": seq,
        "loss_impl": loss_impl,
        "loss_chunk": loss_chunk,
        "remat_policy": remat_policy or cfg.remat_policy,
        "flash_block": list(flash_block) if flash_block else
        [cfg.flash_block_q, cfg.flash_block_kv],
        "tok_s": round(batch * seq / dt, 1),
        "mfu": round(mfu, 4),
        "avg_step_time_s": round(dt, 4),
        "device_kind": jax.devices()[0].device_kind,
    }


#: The s3072 knob grid (PROFILE.md §4's levers): remat policy, CE chunk,
#: flash block shape. Small by design — each variant pays a 20-40 s
#: tunnel compile, and chip windows have been scarce.
TUNE_VARIANTS = (
    {},  # committed defaults: remat nothing, chunk 1024, blocks 512x512
    {"remat_policy": "save_attn"},
    {"loss_chunk": 512},
    {"loss_chunk": 2048},
    {"flash_block": (1024, 512)},
    {"flash_block": (512, 1024)},
)


def tune_point(batch: int, seq: int, timed_steps: int = 4,
               variants=TUNE_VARIANTS, size: str = "1b") -> list[dict]:
    """Sweep the long-context knobs at one (batch, seq) on the live
    chip; returns rows sorted best-MFU-first, failures recorded inline
    (an OOM or compile crash is a data point, not an abort — the r4
    s4096 helper crash must not kill the sweep)."""
    rows = []
    for kv in variants:
        try:
            rows.append(measure(batch, seq, timed_steps=timed_steps,
                                size=size, **kv))
        except Exception as e:  # noqa: BLE001 - recorded per variant
            import re

            msg = re.sub(r"\x1b\[[0-9;]*m", "", f"{type(e).__name__}: {e}")
            rows.append({"batch": batch, "seq_len": seq, **kv,
                         "error": " ".join(msg.split())[:200]})
        print(f"longctx tune {kv}: {rows[-1].get('mfu', 'ERR')}",
              file=sys.stderr, flush=True)
    return sorted(rows, key=lambda r: -r.get("mfu", -1.0))
