"""Re-exec environment for virtual-CPU-device subprocesses.

JAX backends can't be reconfigured after first use, and the axon
sitecustomize initializes the TPU plugin at interpreter start — so any code
that needs "N virtual CPU devices" (multichip dryrun, scale proofs,
multi-process e2e launchers) must re-exec a fresh interpreter with the
platform pinned BEFORE startup. This is the one shared implementation of
that env surgery (previously duplicated in __graft_entry__ and callers).
"""

from __future__ import annotations

import os
import re


def cpu_reexec_env(n_devices: int, base_env: dict | None = None,
                   repo: str | None = None) -> dict:
    """Environment for a child interpreter running on `n_devices` virtual
    CPU devices: forces the CPU platform, disables the axon pool, swaps the
    host-device-count XLA flag, and prepends `repo` (default: the package's
    repository root) to PYTHONPATH while PRESERVING existing entries (they
    carry this environment's site customizations)."""
    env = dict(base_env if base_env is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    xla = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                 env.get("XLA_FLAGS", "")).strip()
    env["XLA_FLAGS"] = (
        f"{xla} --xla_force_host_platform_device_count={int(n_devices)}"
    ).strip()
    if repo is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    parts = [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                      if p and p != repo]
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env
