"""Spec drift guard: ONE schema, two consumers (SURVEY.md §5.6).

The reference shares job-spec types between Go controllers and Python SDKs
through protoc/OpenAPI codegen ⟨kfp: api/ — proto; training-operator:
pkg/apis — OpenAPI⟩; this build's JSON-convention deviation (README
"Config schema") previously had no mechanical guard — C++ admission and
`TrainJobSpec.from_json` could drift and only an e2e would notice.

This module is the single source of truth for the JAXJob `runtime`
field table. One generator emits BOTH artifacts, checked in:

  * `spec_schema.json`      — the schema document (repo root)
  * `cpp/spec_schema.gen.h` — the same table embedded as a C++ constant,
                              parsed once by `cpp/admission.h`, which
                              validates every runtime field against it
                              (unknown fields are rejected — typo'd knobs
                              fail at submit, not as a worker crash)

Drift breaks mechanically, without e2e:
  * dataclass field added/removed without regenerating → the Python
    suite fails (`tests/test_spec_schema.py` cross-checks KNOBS against
    `TrainJobSpec` and the on-disk artifacts against the generator);
  * schema field deleted → the same Python cross-check fails, AND the
    C++ suite fails (`cpp/tests/test_spec_schema.cc` loops the embedded
    table and asserts admission enforces every entry; a spec using the
    deleted field is now rejected as unknown).

Regenerate after editing KNOBS or TrainJobSpec:
    python -m kubeflow_tpu.utils.spec_schema
"""

from __future__ import annotations

import json
import os

#: field -> constraint spec. Types:
#:   int            JSON number, integral, >= min
#:   number         JSON number, >= min
#:   string         JSON string (optionally from enum)
#:   string_or_null string or null
#:   bool_or_string bool or string (ring_attention's switch/mode union)
#:   object         JSON object (contents validated downstream)
KNOBS: dict[str, dict] = {
    "model": {"type": "string"},
    "model_kwargs": {"type": "object"},
    "dataset": {"type": "string"},
    "dataset_kwargs": {"type": "object"},
    "strategy": {"type": "string"},
    "mesh": {"type": "object"},
    "steps": {"type": "int", "min": 1},
    "batch_size": {"type": "int", "min": 1},
    "seq_len": {"type": "int", "min": 1},
    "learning_rate": {"type": "number", "min": 0},
    "warmup_steps": {"type": "int", "min": 0},
    "weight_decay": {"type": "number", "min": 0},
    "lr_schedule": {"type": "string",
                    "enum": ["constant", "cosine", "linear"]},
    "lr_final": {"type": "number", "min": 0},
    "max_grad_norm": {"type": "number", "min": 0},
    "accum_steps": {"type": "int", "min": 1},
    "seed": {"type": "int", "min": 0},
    "ring_attention": {"type": "bool_or_string"},
    "loss_impl": {"type": "string", "enum": ["full", "chunked"]},
    "loss_chunk": {"type": "int", "min": 1},
    "pipeline": {"type": "object"},
    "lora": {"type": "object"},
    "checkpoint": {"type": "object"},
    "restart_policy": {"type": "string",
                       "enum": ["Never", "OnFailure",
                                "ExponentialBackoff"]},
    "backoff_limit": {"type": "int", "min": 0},
    "prefetch": {"type": "int", "min": 0},
    "metrics_path": {"type": "string_or_null"},
    "profile": {"type": "object"},
    "profile_start_step": {"type": "int", "min": 0},
    "profile_stop_step": {"type": "int", "min": 0},
    "log_every": {"type": "int", "min": 1},
    "eval_dataset": {"type": "string_or_null"},
    "eval_dataset_kwargs": {"type": "object"},
    "eval_every": {"type": "int", "min": 0},
    "eval_batches": {"type": "int", "min": 1},
}


def check_against_dataclass() -> None:
    """KNOBS must name exactly the TrainJobSpec fields — a field on either
    side only is drift, refused here (this runs in the test suite)."""
    import dataclasses

    from kubeflow_tpu.train.trainer import TrainJobSpec

    dc = {f.name for f in dataclasses.fields(TrainJobSpec)}
    missing = dc - set(KNOBS)
    extra = set(KNOBS) - dc
    if missing or extra:
        raise AssertionError(
            f"spec schema drift: fields on TrainJobSpec but not in KNOBS "
            f"{sorted(missing)}; in KNOBS but not on TrainJobSpec "
            f"{sorted(extra)} — edit kubeflow_tpu/utils/spec_schema.py "
            f"and regenerate (python -m kubeflow_tpu.utils.spec_schema)")


def schema_document() -> dict:
    return {
        "version": 1,
        "generated_by": "kubeflow_tpu/utils/spec_schema.py",
        "JAXJob.runtime": KNOBS,
    }


def render_json() -> str:
    return json.dumps(schema_document(), indent=1, sort_keys=True) + "\n"


def render_cpp_header() -> str:
    """The schema as an embedded C++ string constant, parsed once by
    admission. Generated — do not edit by hand."""
    payload = json.dumps(schema_document(), sort_keys=True)
    escaped = payload.replace("\\", "\\\\").replace('"', '\\"')
    return (
        "// GENERATED by kubeflow_tpu/utils/spec_schema.py — DO NOT EDIT.\n"
        "// Regenerate: python -m kubeflow_tpu.utils.spec_schema\n"
        "// The JAXJob runtime field table; cpp/admission.h validates\n"
        "// every runtime field against it (unknown fields rejected).\n"
        "#pragma once\n\n"
        "namespace tpk {\n\n"
        "inline const char* kSpecSchemaJson =\n"
        f'    "{escaped}";\n\n'
        "}  // namespace tpk\n")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main() -> int:
    root = repo_root()
    with open(os.path.join(root, "spec_schema.json"), "w") as fh:
        fh.write(render_json())
    with open(os.path.join(root, "cpp", "spec_schema.gen.h"), "w") as fh:
        fh.write(render_cpp_header())
    print("wrote spec_schema.json + cpp/spec_schema.gen.h")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
