"""Spec drift guard: ONE schema, two consumers (SURVEY.md §5.6).

The reference shares job-spec types between Go controllers and Python SDKs
through protoc/OpenAPI codegen ⟨kfp: api/ — proto; training-operator:
pkg/apis — OpenAPI⟩; this build's JSON-convention deviation (README
"Config schema") previously had no mechanical guard — C++ admission and
`TrainJobSpec.from_json` could drift and only an e2e would notice.

This module is the single source of truth for the JAXJob `runtime`
field table. One generator emits BOTH artifacts, checked in:

  * `spec_schema.json`      — the schema document (repo root)
  * `cpp/spec_schema.gen.h` — the same table embedded as a C++ constant,
                              parsed once by `cpp/admission.h`, which
                              validates every runtime field against it
                              (unknown fields are rejected — typo'd knobs
                              fail at submit, not as a worker crash)

Drift breaks mechanically, without e2e:
  * dataclass field added/removed without regenerating → the Python
    suite fails (`tests/test_spec_schema.py` cross-checks KNOBS against
    `TrainJobSpec` and the on-disk artifacts against the generator);
  * schema field deleted → the same Python cross-check fails, AND the
    C++ suite fails (`cpp/tests/test_spec_schema.cc` loops the embedded
    table and asserts admission enforces every entry; a spec using the
    deleted field is now rejected as unknown).

Regenerate after editing KNOBS or TrainJobSpec:
    python -m kubeflow_tpu.utils.spec_schema

Tier-1 also enforces this WITHOUT importing jax: tpklint's `spec-schema`
rule regenerates both artifacts in memory from these tables and diffs
the committed files, so "edited a table, forgot to regenerate (or to
rebuild the C++ binary)" fails as a lint finding with a file:line, not
as a C++ admission e2e surprise.
"""

from __future__ import annotations

import json
import os

#: field -> constraint spec. Types:
#:   int            JSON number, integral, >= min
#:   number         JSON number, >= min
#:   string         JSON string (optionally from enum)
#:   string_or_null string or null
#:   bool_or_string bool or string (ring_attention's switch/mode union)
#:   object         JSON object (contents validated downstream)
#:   int_array      non-empty JSON array of integral numbers (each
#:                  >= min when given — an empty bucket list would
#:                  crash the engine at load, after admission)
#:   int_or_null    integral number or null
KNOBS: dict[str, dict] = {
    "model": {"type": "string"},
    "model_kwargs": {"type": "object"},
    "dataset": {"type": "string"},
    "dataset_kwargs": {"type": "object"},
    "strategy": {"type": "string"},
    "mesh": {"type": "object"},
    "steps": {"type": "int", "min": 1},
    "batch_size": {"type": "int", "min": 1},
    "seq_len": {"type": "int", "min": 1},
    "learning_rate": {"type": "number", "min": 0},
    "warmup_steps": {"type": "int", "min": 0},
    "weight_decay": {"type": "number", "min": 0},
    "lr_schedule": {"type": "string",
                    "enum": ["constant", "cosine", "linear"]},
    "lr_final": {"type": "number", "min": 0},
    "max_grad_norm": {"type": "number", "min": 0},
    "accum_steps": {"type": "int", "min": 1},
    # Canonical gradient-accumulation knob (0 defers to the legacy
    # accum_steps alias; both set and disagreeing is refused).
    "grad_accum": {"type": "int", "min": 0},
    # FSDP master-state sharding degree over the `fsdp` mesh axis
    # (parallel/fsdp.py); 0 = off, N fills mesh.fsdp = N.
    "fsdp": {"type": "int", "min": 0},
    # Compute dtype of the fsdp runtime's gathered param copies.
    "param_dtype": {"type": "string_or_null",
                    "enum": ["float32", "bfloat16"]},
    "seed": {"type": "int", "min": 0},
    "ring_attention": {"type": "bool_or_string"},
    "loss_impl": {"type": "string", "enum": ["full", "chunked"]},
    "loss_chunk": {"type": "int", "min": 1},
    "pipeline": {"type": "object"},
    "lora": {"type": "object"},
    "checkpoint": {"type": "object"},
    "restart_policy": {"type": "string",
                       "enum": ["Never", "OnFailure",
                                "ExponentialBackoff"]},
    "backoff_limit": {"type": "int", "min": 0},
    "prefetch": {"type": "int", "min": 0},
    "metrics_path": {"type": "string_or_null"},
    "profile": {"type": "object"},
    "profile_start_step": {"type": "int", "min": 0},
    "profile_stop_step": {"type": "int", "min": 0},
    "log_every": {"type": "int", "min": 1},
    "eval_dataset": {"type": "string_or_null"},
    "eval_dataset_kwargs": {"type": "object"},
    "eval_every": {"type": "int", "min": 0},
    "eval_batches": {"type": "int", "min": 1},
}

#: InferenceService `model.generative` knob table — the serving twin of
#: KNOBS (same generator, same two consumers). C++ admission validates
#: the generative object field-by-field against it, so a typo'd serving
#: knob (or a kv_block_size on a binary that predates paging) fails at
#: submit instead of as a replica crash-loop. Superset of both
#: generative runtimes: the causal-LM engine (GenerationEngine kwargs +
#: GenerativeJAXModel's eos_id/tokenizer/mesh/draft) and the T5
#: text2text engine (in_buckets/max_tokens/pad_id). Deliberate limit:
#: which runtime applies is decided by the checkpoint's architectures
#: at LOAD time, which admission cannot see — so a cross-runtime knob
#: (in_buckets on a causal-LM service) passes admission and fails at
#: model load; the table exists to catch typos and type errors early,
#: not to discriminate engines.
GENERATIVE_KNOBS: dict[str, dict] = {
    "slots": {"type": "int", "min": 1},
    "max_len": {"type": "int", "min": 2},
    "chunk": {"type": "int", "min": 1},
    "prefill_buckets": {"type": "int_array", "min": 1},
    "decode_buckets": {"type": "int_array", "min": 1},
    "prefix_cache": {"type": "int", "min": 0},
    "seed": {"type": "int", "min": 0},
    "pipeline_depth": {"type": "int", "min": 1},
    # Paged KV cache (serve/paging.py): 0 = flat escape hatch.
    "kv_block_size": {"type": "int", "min": 0},
    "kv_blocks": {"type": "int", "min": 0},
    # Disaggregated prefill/decode (ISSUE 13): "unified" (default) |
    # "prefill" | "decode"; split roles need kv_block_size > 0 (the
    # cross-field rule lives in cpp/admission.h next to the table).
    "role": {"type": "string_or_null",
             "enum": ["unified", "prefill", "decode"]},
    # Host-RAM KV spill tier capacity in blocks (0 = off).
    "kv_host_tier_blocks": {"type": "int", "min": 0},
    # Quantized KV pool blocks (ISSUE 19): "none" (default, bit-exact
    # escape hatch) | "int8" | "fp8". Cross-field rules live in
    # cpp/admission.h next to the table: kv_quant requires
    # kv_block_size > 0 (the scale pool is a paged structure) and
    # refuses draft (a speculative rejection rewind would re-quantize
    # committed rows — see PROFILE.md §17 for the measured decision).
    "kv_quant": {"type": "string_or_null",
                 "enum": ["none", "int8", "fp8"]},
    "mesh": {"type": "object"},
    # Speculative decoding draft spec: {"checkpoint": hf_dir,
    # "gamma"?: int >= 1, "model_overrides"?: {...}} — contents are
    # cross-field-validated in cpp/admission.h (ISSUE 18): a draft
    # without a checkpoint, a fractional gamma, or a typo'd key fails
    # at submit instead of crash-looping the replica at load. Since
    # ISSUE 18 the draft COMPOSES with kv_block_size, role and
    # pipeline_depth; only checkpoint-derived refusals (sliding-window
    # drafts past their window, vocab mismatch) remain load-time.
    "draft": {"type": "object"},
    "adapters": {"type": "object"},
    "eos_id": {"type": "int_or_null"},
    "tokenizer": {"type": "string_or_null"},
    "in_buckets": {"type": "int_array", "min": 1},
    "max_tokens": {"type": "int", "min": 1},
    "pad_id": {"type": "int", "min": 0},
}


def check_against_dataclass() -> None:
    """KNOBS must name exactly the TrainJobSpec fields — a field on either
    side only is drift, refused here (this runs in the test suite)."""
    import dataclasses

    from kubeflow_tpu.train.trainer import TrainJobSpec

    dc = {f.name for f in dataclasses.fields(TrainJobSpec)}
    missing = dc - set(KNOBS)
    extra = set(KNOBS) - dc
    if missing or extra:
        raise AssertionError(
            f"spec schema drift: fields on TrainJobSpec but not in KNOBS "
            f"{sorted(missing)}; in KNOBS but not on TrainJobSpec "
            f"{sorted(extra)} — edit kubeflow_tpu/utils/spec_schema.py "
            f"and regenerate (python -m kubeflow_tpu.utils.spec_schema)")


def check_generative_against_engine() -> None:
    """Every GenerationEngine kwarg must have a GENERATIVE_KNOBS entry
    (plus the wrapper-level keys GenerativeJAXModel pops) — a new engine
    knob without a schema row would be REJECTED by C++ admission on
    every spec that sets it. `rules` is deliberately schema-less: it
    takes in-process sharding-rule objects, never JSON."""
    import inspect

    from kubeflow_tpu.serve.generation import GenerationEngine

    sig = inspect.signature(GenerationEngine.__init__)
    knobs = {n for n in sig.parameters
             if n not in ("self", "model", "params", "cfg", "rules")}
    missing = knobs - set(GENERATIVE_KNOBS)
    if missing:
        raise AssertionError(
            f"generative schema drift: GenerationEngine kwargs missing "
            f"from GENERATIVE_KNOBS: {sorted(missing)} — edit "
            f"kubeflow_tpu/utils/spec_schema.py and regenerate")


def schema_document() -> dict:
    return {
        "version": 1,
        "generated_by": "kubeflow_tpu/utils/spec_schema.py",
        "JAXJob.runtime": KNOBS,
        "InferenceService.model.generative": GENERATIVE_KNOBS,
    }


def render_json() -> str:
    return json.dumps(schema_document(), indent=1, sort_keys=True) + "\n"


def render_cpp_header() -> str:
    """The schema as an embedded C++ string constant, parsed once by
    admission. Generated — do not edit by hand."""
    payload = json.dumps(schema_document(), sort_keys=True)
    escaped = payload.replace("\\", "\\\\").replace('"', '\\"')
    return (
        "// GENERATED by kubeflow_tpu/utils/spec_schema.py — DO NOT EDIT.\n"
        "// Regenerate: python -m kubeflow_tpu.utils.spec_schema\n"
        "// The JAXJob runtime field table; cpp/admission.h validates\n"
        "// every runtime field against it (unknown fields rejected).\n"
        "#pragma once\n\n"
        "namespace tpk {\n\n"
        "inline const char* kSpecSchemaJson =\n"
        f'    "{escaped}";\n\n'
        "}  // namespace tpk\n")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main() -> int:
    root = repo_root()
    with open(os.path.join(root, "spec_schema.json"), "w") as fh:
        fh.write(render_json())
    with open(os.path.join(root, "cpp", "spec_schema.gen.h"), "w") as fh:
        fh.write(render_cpp_header())
    print("wrote spec_schema.json + cpp/spec_schema.gen.h")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
