"""Virtual-device plumbing shared by the test harness and CLI test modes."""

from __future__ import annotations

import os


def force_cpu_device_count(n: int) -> None:
    """Pin jax to the CPU backend with `n` virtual devices — the
    device-plane analog of envtest/kind: real XLA collectives over `n`
    host devices. Works on jax >= 0.5 (`jax_num_cpu_devices` config) and
    older jax (XLA_FLAGS, read at first backend init). Must run before
    any backend use; importing jax beforehand is fine."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # jax < 0.5 has no jax_num_cpu_devices; XLA_FLAGS is still read
        # at first backend init, which has not happened yet.
        flag = f"--xla_force_host_platform_device_count={n}"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
