"""Model / dataset / optimizer registries.

The config-driven analog of the reference's ConfigMap-based registries
(katib-config's algorithm→image map, KServe's ServingRuntime model-format→
container recipe; SURVEY.md §5.6): a job spec names a model and dataset by
string; controllers and runtimes resolve them here.
"""

from __future__ import annotations

from typing import Any, Callable

_MODELS: dict[str, Callable[..., Any]] = {}
_DATASETS: dict[str, Callable[..., Any]] = {}


def register_model(name: str):
    def deco(fn):
        _MODELS[name] = fn
        return fn
    return deco


def register_dataset(name: str):
    def deco(fn):
        _DATASETS[name] = fn
        return fn
    return deco


def build_model(name: str, **kwargs):
    """Returns (flax_module, info dict with num_params/batch spec hints)."""
    _ensure_builtin()
    try:
        fn = _MODELS[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; have {sorted(_MODELS)}") from None
    return fn(**kwargs)


def build_dataset(name: str, **kwargs):
    _ensure_builtin()
    try:
        fn = _DATASETS[name]
    except KeyError:
        raise ValueError(
            f"unknown dataset {name!r}; have {sorted(_DATASETS)}") from None
    return fn(**kwargs)


def list_models() -> list[str]:
    _ensure_builtin()
    return sorted(_MODELS)


_builtin_loaded = False


def _ensure_builtin() -> None:
    global _builtin_loaded
    if _builtin_loaded:
        return

    import jax.numpy as jnp  # noqa: F401

    from kubeflow_tpu.models import bert, llama, mlp

    @register_model("mnist_mlp")
    def _mnist_mlp(**kw):
        cfg = mlp.MLPConfig(**kw)
        model = mlp.MLP(cfg)
        return model, {"task": "classify", "example_shape": (1, cfg.in_dim),
                       "example_dtype": "float32", "num_params": None}

    def _llama(cfg: llama.LlamaConfig):
        return llama.Llama(cfg), {
            "task": "lm", "example_shape": (1, 16), "example_dtype": "int32",
            "num_params": cfg.num_params, "vocab_size": cfg.vocab_size,
            "config": cfg}

    @register_model("llama_tiny")
    def _llama_tiny(**kw):
        import dataclasses
        return _llama(dataclasses.replace(llama.llama_tiny(), **kw))

    @register_model("llama_1b")
    def _llama_1b(**kw):
        import dataclasses
        return _llama(dataclasses.replace(llama.llama_1b(), **kw))

    @register_model("llama3_8b")
    def _llama3_8b(**kw):
        import dataclasses
        return _llama(dataclasses.replace(llama.llama3_8b(), **kw))

    from kubeflow_tpu.models import moe

    def _moe(cfg):
        return moe.MoELlama(cfg), {
            "task": "lm", "example_shape": (1, 16), "example_dtype": "int32",
            "num_params": cfg.num_params,
            "active_params": cfg.active_params,
            "vocab_size": cfg.vocab_size, "config": cfg}

    @register_model("moe_tiny")
    def _moe_tiny(**kw):
        import dataclasses
        return _moe(dataclasses.replace(moe.moe_tiny(), **kw))

    @register_model("mixtral_8x7b")
    def _mixtral_8x7b(**kw):
        import dataclasses
        return _moe(dataclasses.replace(moe.mixtral_8x7b(), **kw))

    @register_model("bert_tiny")
    def _bert_tiny(**kw):
        import dataclasses
        cfg = dataclasses.replace(bert.bert_tiny(), **kw)
        return bert.Bert(cfg), {
            "task": "classify", "example_shape": (1, 16),
            "example_dtype": "int32", "num_params": None, "config": cfg}

    @register_model("bert_base")
    def _bert_base(**kw):
        import dataclasses
        cfg = dataclasses.replace(bert.bert_base(), **kw)
        return bert.Bert(cfg), {
            "task": "classify", "example_shape": (1, 128),
            "example_dtype": "int32", "num_params": None, "config": cfg}

    @register_model("gpt2_tiny")
    def _gpt2_tiny(**kw):
        import dataclasses

        from kubeflow_tpu.models import gpt2

        cfg = dataclasses.replace(gpt2.gpt2_tiny(), **kw)
        return gpt2.GPT2(cfg), {
            "task": "lm", "example_shape": (1, 16),
            "example_dtype": "int32", "num_params": cfg.num_params,
            "vocab_size": cfg.vocab_size, "config": cfg}

    from kubeflow_tpu.data import synthetic

    @register_dataset("synthetic_lm")
    def _synthetic_lm(batch_size=8, seq_len=128, vocab_size=512, seed=0, **kw):
        return synthetic.token_batches(batch_size, seq_len, vocab_size, seed)

    @register_dataset("learnable_lm")
    def _learnable_lm(batch_size=8, seq_len=32, vocab_size=64, seed=0, **kw):
        return synthetic.learnable_token_batches(
            batch_size, seq_len, vocab_size, seed)

    @register_dataset("mnist_like")
    def _mnist_like(batch_size=64, seed=0, **kw):
        return synthetic.mnist_like(batch_size, seed)

    @register_dataset("token_file")
    def _token_file(path, batch_size=8, seq_len=128, seed=0, shuffle=True,
                    vocab_size=None, process_index=None, process_count=None,
                    **kw):
        """Grain-backed tokenized corpus (.npy/.bin/.txt) with
        checkpointable iterator state — the production input path. The
        trainer passes the model's vocab_size so a wrong-tokenizer corpus
        fails at startup instead of training on clamped ids, and its batch
        replica group as (process_index, process_count) so ranks sharing a
        batch shard load identical rows."""
        from kubeflow_tpu.data import loader

        return loader.lm_dataset(
            path, batch_size=batch_size, seq_len=seq_len, seed=seed,
            shuffle=shuffle, vocab_size=vocab_size,
            process_index=process_index, process_count=process_count)

    @register_dataset("packed_lm")
    def _packed_lm(path, batch_size=8, seq_len=128, eos_id=0, seed=0,
                   shuffle=True, vocab_size=None, **kw):
        """Document-packed corpus: batches carry segment_ids/positions/mask
        so attention and loss respect document boundaries (the packed-
        sequence path through the fused kernels)."""
        from kubeflow_tpu.data import loader

        return loader.packed_lm_dataset(
            path, batch_size=batch_size, seq_len=seq_len, eos_id=eos_id,
            seed=seed, shuffle=shuffle, vocab_size=vocab_size,
            process_index=kw.get("process_index"),
            process_count=kw.get("process_count"))

    # Only mark loaded once every builtin registered — a failed import above
    # must re-raise on the next call, not leave the registry silently empty.
    _builtin_loaded = True
