"""Shared resilience primitives: backoff, deadlines, retry budgets.

The reference platform spreads failure semantics across subsystems —
training-operator restartPolicy/backoffLimit on the job spec, client-go
rate limiters + wait.Backoff in every controller, Knative/KServe request
timeouts and probe-driven readiness (SURVEY.md §2.1/§2.2/§3.2). Here ONE
module owns the primitives so train, controlplane, and serve agree on
semantics and metric names:

  * `BackoffPolicy` — exponential backoff with decorrelated jitter (the
    client-go / AWS-architecture-blog recipe); deterministic when handed
    a seeded rng, which is how the fault-injection tests pin schedules.
  * `Deadline` — an absolute budget on the monotonic clock, threaded
    through call stacks instead of per-hop flat timeouts (gRPC-style
    deadline propagation). `DeadlineExceeded` is the one typed expiry
    error every layer raises (serve maps it to HTTP 504).
  * `RetryBudget` — SRE-style token bucket capping the retry *ratio*, so
    a hard-down dependency sees a bounded trickle, not attempts×clients.
  * `retry_call` — the one retry loop (attempt cap AND deadline cap,
    backoff between attempts, metrics per attempt/exhaustion).
  * `metrics` — process-global counters with uniform names
    (`tpk_retry_attempts_total`, `tpk_deadline_expired_total`, ...);
    the model server's /metrics endpoint renders them alongside its own.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Callable


class DeadlineExceeded(TimeoutError):
    """A per-request/per-call budget expired (serve maps this to 504)."""


class BackoffLimitExceeded(RuntimeError):
    """A supervised retry loop exhausted its restart/attempt budget (the
    training-operator's `backoffLimit` failure, typed)."""


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff schedule with full jitter.

    `delay(attempt)` is the sleep before retry number `attempt` (0-based):
    base·multiplier^attempt, capped at `max_s`, then jittered down by up
    to `jitter` fraction (uniform). Pass a seeded `random.Random` for a
    deterministic schedule (tests); the default draws from the module rng.
    """

    initial_s: float = 0.05
    max_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.5

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        d = min(self.initial_s * self.multiplier ** max(attempt, 0),
                self.max_s)
        if self.jitter > 0:
            u = (rng or _RNG).random()
            d *= 1.0 - self.jitter * u
        return d


_RNG = random.Random()


class Deadline:
    """Absolute time budget on the monotonic clock.

    `Deadline(None)` never expires — callers thread one object through
    unconditionally instead of branching on "has a deadline". The clock is
    injectable so tests advance time without sleeping.
    """

    __slots__ = ("_clock", "_at")

    def __init__(self, budget_s: float | None,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._at = None if budget_s is None else clock() + float(budget_s)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    def remaining(self) -> float | None:
        """Seconds left (may be <= 0), or None for a never-expiring one."""
        if self._at is None:
            return None
        return self._at - self._clock()

    def expired(self) -> bool:
        return self._at is not None and self._clock() >= self._at

    def bound(self, timeout: float) -> float:
        """`timeout` clipped to the remaining budget (for per-hop socket/
        wait timeouts under an end-to-end deadline)."""
        rem = self.remaining()
        return timeout if rem is None else min(timeout, max(rem, 0.0))

    def require(self, what: str = "operation",
                component: str = "") -> None:
        """Raise `DeadlineExceeded` (and count it) if the budget is gone."""
        if self.expired():
            if component:
                metrics.inc("tpk_deadline_expired_total",
                            component=component)
            raise DeadlineExceeded(f"deadline expired before {what}")


class RetryBudget:
    """Token-bucket retry budget (the SRE retry-ratio cap).

    Every first attempt deposits `deposit_per_call` tokens (clipped at
    `capacity`); every retry withdraws one. When the bucket is empty,
    `allow()` refuses — so a dependency that is hard-down sees retries in
    proportion to fresh traffic, never an amplified storm.
    """

    def __init__(self, capacity: float = 10.0,
                 deposit_per_call: float = 0.1):
        self.capacity = float(capacity)
        self.deposit_per_call = float(deposit_per_call)
        self._tokens = float(capacity)
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.deposit_per_call,
                               self.capacity)

    def allow(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


def retry_call(fn: Callable[[], Any], *,
               retry_on: tuple[type[BaseException], ...],
               policy: BackoffPolicy | None = None,
               max_attempts: int = 5,
               deadline: Deadline | None = None,
               budget: RetryBudget | None = None,
               component: str = "",
               sleep: Callable[[float], None] = time.sleep,
               rng: random.Random | None = None) -> Any:
    """Run `fn` under the unified retry semantics.

    Retries only `retry_on` exceptions, waiting `policy.delay(i)` between
    attempts, until `max_attempts` calls have failed OR the deadline
    cannot cover the next backoff sleep OR the retry budget refuses. On
    exhaustion the LAST error re-raises (callers wrap it in their typed
    error: `ControlPlaneUnavailable`, `BackoffLimitExceeded`, ...).
    """
    policy = policy or BackoffPolicy()
    deadline = deadline or Deadline.never()
    if budget is not None:
        budget.deposit()
    attempt = 0
    while True:
        deadline.require("attempt", component=component)
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            metrics.inc("tpk_retry_attempts_total", component=component)
            delay = policy.delay(attempt - 1, rng=rng)
            rem = deadline.remaining()
            if (attempt >= max_attempts
                    or (rem is not None and rem <= delay)
                    or (budget is not None and not budget.allow())):
                metrics.inc("tpk_retry_exhausted_total",
                            component=component)
                raise
            sleep(delay)


class Counters:
    """Process-global labeled counters with prometheus rendering — the
    uniform metrics surface every resilience consumer increments."""

    def __init__(self):
        self._counts: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counts[key] = self._counts.get(key, 0.0) + value

    def get(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counts.get(key, 0.0)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        """Last-write-wins gauge (config knobs, live depths) rendered
        next to the counters with the proper prometheus TYPE."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = float(value)

    def get_gauge(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key, 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._counts.items()) + sorted(
                self._gauges.items())
            return {
                name + ("{%s}" % ",".join(f'{k}="{v}"' for k, v in lbl)
                        if lbl else ""): v
                for (name, lbl), v in items}

    def prometheus_text(self) -> str:
        lines = []
        seen: set[str] = set()
        with self._lock:
            items = ([(n, lbl, v, "counter")
                      for (n, lbl), v in sorted(self._counts.items())]
                     + [(n, lbl, v, "gauge")
                        for (n, lbl), v in sorted(self._gauges.items())])
        for name, lbl, v, kind in items:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} {kind}")
            tag = ("{%s}" % ",".join(f'{k}="{v2}"' for k, v2 in lbl)
                   if lbl else "")
            val = int(v) if float(v).is_integer() else v
            lines.append(f"{name}{tag} {val}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Test hook — counters are process-global."""
        with self._lock:
            self._counts.clear()
            self._gauges.clear()


metrics = Counters()
