"""Shared resilience primitives: backoff, deadlines, retry budgets.

The reference platform spreads failure semantics across subsystems —
training-operator restartPolicy/backoffLimit on the job spec, client-go
rate limiters + wait.Backoff in every controller, Knative/KServe request
timeouts and probe-driven readiness (SURVEY.md §2.1/§2.2/§3.2). Here ONE
module owns the primitives so train, controlplane, and serve agree on
semantics and metric names:

  * `BackoffPolicy` — exponential backoff with decorrelated jitter (the
    client-go / AWS-architecture-blog recipe); deterministic when handed
    a seeded rng, which is how the fault-injection tests pin schedules.
  * `Deadline` — an absolute budget on the monotonic clock, threaded
    through call stacks instead of per-hop flat timeouts (gRPC-style
    deadline propagation). `DeadlineExceeded` is the one typed expiry
    error every layer raises (serve maps it to HTTP 504).
  * `RetryBudget` — SRE-style token bucket capping the retry *ratio*, so
    a hard-down dependency sees a bounded trickle, not attempts×clients.
  * `retry_call` — the one retry loop (attempt cap AND deadline cap,
    backoff between attempts, metrics per attempt/exhaustion).
  * `metrics` — process-global counters with uniform names
    (`tpk_retry_attempts_total`, `tpk_deadline_expired_total`, ...);
    the model server's /metrics endpoint renders them alongside its own.
"""

from __future__ import annotations

import dataclasses
import random
import re
import threading
import time
from typing import Any, Callable


class DeadlineExceeded(TimeoutError):
    """A per-request/per-call budget expired (serve maps this to 504)."""


class BackoffLimitExceeded(RuntimeError):
    """A supervised retry loop exhausted its restart/attempt budget (the
    training-operator's `backoffLimit` failure, typed)."""


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff schedule with full jitter.

    `delay(attempt)` is the sleep before retry number `attempt` (0-based):
    base·multiplier^attempt, capped at `max_s`, then jittered down by up
    to `jitter` fraction (uniform). Pass a seeded `random.Random` for a
    deterministic schedule (tests); the default draws from the module rng.
    """

    initial_s: float = 0.05
    max_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.5

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        d = min(self.initial_s * self.multiplier ** max(attempt, 0),
                self.max_s)
        if self.jitter > 0:
            u = (rng or _RNG).random()
            d *= 1.0 - self.jitter * u
        return d


_RNG = random.Random()


class Deadline:
    """Absolute time budget on the monotonic clock.

    `Deadline(None)` never expires — callers thread one object through
    unconditionally instead of branching on "has a deadline". The clock is
    injectable so tests advance time without sleeping.
    """

    __slots__ = ("_clock", "_at")

    def __init__(self, budget_s: float | None,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._at = None if budget_s is None else clock() + float(budget_s)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    def remaining(self) -> float | None:
        """Seconds left (may be <= 0), or None for a never-expiring one."""
        if self._at is None:
            return None
        return self._at - self._clock()

    def expired(self) -> bool:
        return self._at is not None and self._clock() >= self._at

    def bound(self, timeout: float) -> float:
        """`timeout` clipped to the remaining budget (for per-hop socket/
        wait timeouts under an end-to-end deadline)."""
        rem = self.remaining()
        return timeout if rem is None else min(timeout, max(rem, 0.0))

    def require(self, what: str = "operation",
                component: str = "") -> None:
        """Raise `DeadlineExceeded` (and count it) if the budget is gone."""
        if self.expired():
            if component:
                metrics.inc("tpk_deadline_expired_total",
                            component=component)
            raise DeadlineExceeded(f"deadline expired before {what}")


class RetryBudget:
    """Token-bucket retry budget (the SRE retry-ratio cap).

    Every first attempt deposits `deposit_per_call` tokens (clipped at
    `capacity`); every retry withdraws one. When the bucket is empty,
    `allow()` refuses — so a dependency that is hard-down sees retries in
    proportion to fresh traffic, never an amplified storm.
    """

    def __init__(self, capacity: float = 10.0,
                 deposit_per_call: float = 0.1):
        self.capacity = float(capacity)
        self.deposit_per_call = float(deposit_per_call)
        self._tokens = float(capacity)  # guarded-by: _lock
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.deposit_per_call,
                               self.capacity)

    def allow(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


def retry_call(fn: Callable[[], Any], *,
               retry_on: tuple[type[BaseException], ...],
               policy: BackoffPolicy | None = None,
               max_attempts: int = 5,
               deadline: Deadline | None = None,
               budget: RetryBudget | None = None,
               component: str = "",
               sleep: Callable[[float], None] = time.sleep,
               rng: random.Random | None = None) -> Any:
    """Run `fn` under the unified retry semantics.

    Retries only `retry_on` exceptions, waiting `policy.delay(i)` between
    attempts, until `max_attempts` calls have failed OR the deadline
    cannot cover the next backoff sleep OR the retry budget refuses. On
    exhaustion the LAST error re-raises (callers wrap it in their typed
    error: `ControlPlaneUnavailable`, `BackoffLimitExceeded`, ...).
    """
    policy = policy or BackoffPolicy()
    deadline = deadline or Deadline.never()
    if budget is not None:
        budget.deposit()
    attempt = 0
    while True:
        deadline.require("attempt", component=component)
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            metrics.inc("tpk_retry_attempts_total", component=component)
            delay = policy.delay(attempt - 1, rng=rng)
            rem = deadline.remaining()
            if (attempt >= max_attempts
                    or (rem is not None and rem <= delay)
                    or (budget is not None and not budget.allow())):
                metrics.inc("tpk_retry_exhausted_total",
                            component=component)
                raise
            sleep(delay)


def _escape_label(value: str) -> str:
    """Prometheus exposition-format label-value escaping: backslash,
    double-quote, and newline must be escaped or the line is invalid
    (and a crafted value could inject whole fake series). Backslash
    first — escaping it last would re-mangle the other escapes."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(lbl: tuple) -> str:
    """Render a sorted (key, value) label tuple as `k1="v1",k2="v2"`
    with spec-compliant value escaping (shared by snapshot and the
    exposition renderer so the two can't drift)."""
    return ",".join(f'{k}="{_escape_label(v)}"' for k, v in lbl)


def _fmt_value(v: float):
    return int(v) if float(v).is_integer() else v


#: Default histogram buckets, in seconds — latency-shaped (sub-ms to
#: 10 s), cumulative `le` rendering adds +Inf. Callers measuring
#: something else pass explicit buckets on first observe().
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


class Counters:
    """Process-global labeled counters/gauges/histograms with prometheus
    rendering — the uniform metrics surface every resilience consumer
    increments."""

    def __init__(self):
        self._counts: dict[tuple, float] = {}  # guarded-by: _lock
        self._gauges: dict[tuple, float] = {}  # guarded-by: _lock
        # Histograms: family name -> bucket upper bounds (fixed at first
        # observe — every label set of a family shares one bucket
        # layout, as prometheus requires); (name, labels) -> [per-bucket
        # counts (NON-cumulative; +Inf implicit), sum, count].
        self._hist_buckets: dict[str, tuple] = {}  # guarded-by: _lock
        self._hists: dict[tuple, list] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counts[key] = self._counts.get(key, 0.0) + value

    def get(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counts.get(key, 0.0)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        """Last-write-wins gauge (config knobs, live depths) rendered
        next to the counters with the proper prometheus TYPE."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = float(value)

    def get_gauge(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key, 0.0)

    def observe(self, name: str, value: float,
                buckets: tuple | None = None, **labels: str) -> None:
        """Record one histogram observation. The family's bucket layout
        is fixed by the FIRST observe (explicit `buckets` or
        DEFAULT_BUCKETS); later calls reuse it — prometheus histograms
        cannot change buckets mid-flight."""
        value = float(value)
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            bkts = self._hist_buckets.get(name)
            if bkts is None:
                bkts = tuple(sorted(float(b) for b in
                                    (buckets or DEFAULT_BUCKETS)))
                self._hist_buckets[name] = bkts
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [[0] * len(bkts), 0.0, 0]
            counts, _, _ = h
            for i, le in enumerate(bkts):
                if value <= le:
                    counts[i] += 1
                    break
            # else: only the implicit +Inf bucket (== count) holds it.
            h[1] += value
            h[2] += 1

    def get_histogram(self, name: str, **labels: str) -> dict:
        """{"buckets": {le: CUMULATIVE count}, "sum", "count"} — the
        test/introspection view of one labeled series ("+Inf" included)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            bkts = self._hist_buckets.get(name, ())
            h = self._hists.get(key)
            if h is None:
                return {"buckets": {}, "sum": 0.0, "count": 0}
            counts, total, n = list(h[0]), h[1], h[2]
        cum, out = 0, {}
        for le, c in zip(bkts, counts):
            cum += c
            out[le] = cum
        out["+Inf"] = n
        return {"buckets": out, "sum": total, "count": n}

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._counts.items()) + sorted(
                self._gauges.items())
            # Copy sum/count under the lock: the stored lists are live
            # and a concurrent observe() could tear the pair.
            hists = [(key, (h[1], h[2]))
                     for key, h in sorted(self._hists.items())]
        out = {
            name + ("{%s}" % _label_str(lbl) if lbl else ""): v
            for (name, lbl), v in items}
        for (name, lbl), (total, n) in hists:
            tag = "{%s}" % _label_str(lbl) if lbl else ""
            out[f"{name}_sum{tag}"] = total
            out[f"{name}_count{tag}"] = n
        return out

    def prometheus_text(self) -> str:
        lines = []
        seen: set[str] = set()
        with self._lock:
            items = ([(n, lbl, v, "counter")
                      for (n, lbl), v in sorted(self._counts.items())]
                     + [(n, lbl, v, "gauge")
                        for (n, lbl), v in sorted(self._gauges.items())])
            hists = [(n, lbl, list(h[0]), h[1], h[2])
                     for (n, lbl), h in sorted(self._hists.items())]
            hist_buckets = dict(self._hist_buckets)
        for name, lbl, v, kind in items:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} {kind}")
            tag = "{%s}" % _label_str(lbl) if lbl else ""
            lines.append(f"{name}{tag} {_fmt_value(v)}")
        for name, lbl, counts, total, n in hists:
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} histogram")
            base = _label_str(lbl)
            cum = 0
            for le, c in zip(hist_buckets.get(name, ()), counts):
                cum += c
                tag = (base + "," if base else "") + f'le="{le:g}"'
                lines.append(f"{name}_bucket{{{tag}}} {cum}")
            tag = (base + "," if base else "") + 'le="+Inf"'
            lines.append(f"{name}_bucket{{{tag}}} {n}")
            suffix = "{%s}" % base if base else ""
            lines.append(f"{name}_sum{suffix} {_fmt_value(total)}")
            lines.append(f"{name}_count{suffix} {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Test hook — counters are process-global."""
        with self._lock:
            self._counts.clear()
            self._gauges.clear()
            self._hists.clear()
            self._hist_buckets.clear()


metrics = Counters()


# ---------------------------------------------------------------------------
# Exposition-format parse + fleet merge (router's /fleet/metrics).
#
# The fleet poller already scrapes every replica's /metrics text; merging
# those cached expositions gives one whole-fleet document without a
# Prometheus server in the loop. Merge semantics are deliberate:
# counters SUM, gauges stay PER-REPLICA (summing a gauge like
# tpk_decode_inflight across replicas is meaningful but summing
# tpk_serve_batch_size is nonsense — so gauges uniformly keep a
# `replica` label and the reader decides), histograms sum BUCKET-WISE
# only when every replica agrees on the bucket layout. A layout
# mismatch REFUSES loudly (MetricsMergeError): silently merging
# incompatible buckets would fabricate quantiles.
# ---------------------------------------------------------------------------

class MetricsMergeError(ValueError):
    """Fleet metrics merge refused — incompatible per-replica
    expositions (same family, different kind or bucket layout)."""


_EXPO_TYPE = re.compile(r"^# TYPE ([A-Za-z_:][A-Za-z0-9_:]*) (\S+)\s*$")
_EXPO_SAMPLE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_EXPO_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(value: str) -> str:
    """Reverse `_escape_label` (char walk — a regex sub would mis-handle
    runs of backslashes)."""
    out, i = [], 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                            "\\" + nxt))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _parse_labels(raw: str | None) -> dict[str, str]:
    if not raw:
        return {}
    return {m.group(1): _unescape_label(m.group(2))
            for m in _EXPO_LABEL.finditer(raw)}


def parse_prometheus_text(text: str) -> dict[str, dict]:
    """Parse one exposition document into families.

    Returns `{family: {"kind": counter|gauge|histogram|untyped, ...}}`:
    scalar families carry `"samples": {labels_tuple: value}`, histogram
    families carry `"hist": {labels_tuple_without_le: {"buckets":
    {le_float: cumulative}, "sum": x, "count": n}}` (+Inf as
    `float("inf")`). Unparseable lines are skipped — a scrape is partial
    truth, not a schema."""
    kinds: dict[str, str] = {}
    for line in text.splitlines():
        m = _EXPO_TYPE.match(line)
        if m:
            kinds[m.group(1)] = m.group(2)
    hist_families = {n for n, k in kinds.items() if k == "histogram"}

    def hist_family_of(name: str) -> tuple[str, str] | None:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in \
                    hist_families:
                return name[:-len(suffix)], suffix
        return None

    out: dict[str, dict] = {}
    for name in hist_families:
        out[name] = {"kind": "histogram", "hist": {}}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _EXPO_SAMPLE.match(line)
        if not m:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = _parse_labels(raw_labels)
        hf = hist_family_of(name)
        if hf is not None:
            family, suffix = hf
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            series = out[family]["hist"].setdefault(
                key, {"buckets": {}, "sum": 0.0, "count": 0.0})
            if suffix == "_bucket":
                if le is None:
                    continue
                series["buckets"][float(le)] = value
            elif suffix == "_sum":
                series["sum"] = value
            else:
                series["count"] = value
            continue
        fam = out.setdefault(
            name, {"kind": kinds.get(name, "untyped"), "samples": {}})
        fam.setdefault("samples", {})[
            tuple(sorted(labels.items()))] = value
    return out


def _fmt_le(le: float) -> str:
    return "+Inf" if le == float("inf") else f"{le:g}"


def merge_prometheus_texts(texts: dict[str, str]) -> str:
    """Merge per-replica exposition documents into one fleet document.

    `texts` maps replica name -> its scraped /metrics text. Counters are
    summed across replicas; gauges (and untyped samples) are re-emitted
    per replica with a `replica` label added; histogram families whose
    bucket layouts agree across every exposing replica are bucket-wise
    summed (cumulative counts, sum, count). Disagreements — one family
    declared with two kinds, or two bucket layouts — raise
    `MetricsMergeError` naming the family: refusal is the contract,
    silent merging of incompatible series never happens.
    """
    parsed = {replica: parse_prometheus_text(text)
              for replica, text in sorted(texts.items())}

    kinds: dict[str, str] = {}
    for replica, families in parsed.items():
        for name, fam in families.items():
            prev = kinds.get(name)
            if prev is not None and prev != fam["kind"] and \
                    "untyped" not in (prev, fam["kind"]):
                raise MetricsMergeError(
                    f"family {name}: declared {prev} by one replica but "
                    f"{fam['kind']} by {replica} — refusing to merge")
            if prev is None or prev == "untyped":
                kinds[name] = fam["kind"]

    counters: dict[str, dict[tuple, float]] = {}
    per_replica: dict[str, dict[tuple, float]] = {}
    hists: dict[str, dict[tuple, dict]] = {}
    hist_layout: dict[str, tuple[frozenset, str]] = {}
    for replica, families in parsed.items():
        for name, fam in families.items():
            if kinds[name] == "counter":
                dst = counters.setdefault(name, {})
                for lbl, v in fam.get("samples", {}).items():
                    dst[lbl] = dst.get(lbl, 0.0) + v
            elif kinds[name] == "histogram":
                dst = hists.setdefault(name, {})
                for lbl, series in fam.get("hist", {}).items():
                    layout = frozenset(series["buckets"])
                    prev = hist_layout.get(name)
                    if prev is None:
                        hist_layout[name] = (layout, replica)
                    elif prev[0] != layout:
                        raise MetricsMergeError(
                            f"histogram {name}: bucket layout "
                            f"[{', '.join(_fmt_le(b) for b in sorted(prev[0]))}]"
                            f" (from {prev[1]}) != "
                            f"[{', '.join(_fmt_le(b) for b in sorted(layout))}]"
                            f" (from {replica}) — refusing to merge "
                            "mismatched buckets")
                    agg = dst.setdefault(
                        lbl, {"buckets": {}, "sum": 0.0, "count": 0.0})
                    for le, v in series["buckets"].items():
                        agg["buckets"][le] = agg["buckets"].get(le,
                                                                0.0) + v
                    agg["sum"] += series["sum"]
                    agg["count"] += series["count"]
            else:  # gauge / untyped: per-replica, never summed
                dst = per_replica.setdefault(name, {})
                for lbl, v in fam.get("samples", {}).items():
                    labeled = dict(lbl)
                    labeled["replica"] = replica
                    dst[tuple(sorted(labeled.items()))] = v

    lines: list[str] = []
    for name in sorted(kinds):
        kind = kinds[name]
        lines.append(f"# TYPE {name} {kind}")
        if kind == "counter":
            for lbl, v in sorted(counters.get(name, {}).items()):
                tag = "{%s}" % _label_str(lbl) if lbl else ""
                lines.append(f"{name}{tag} {_fmt_value(v)}")
        elif kind == "histogram":
            for lbl, agg in sorted(hists.get(name, {}).items()):
                base = _label_str(lbl)
                for le in sorted(agg["buckets"]):
                    tag = (base + "," if base else "") + \
                        f'le="{_fmt_le(le)}"'
                    lines.append(f"{name}_bucket{{{tag}}} "
                                 f"{_fmt_value(agg['buckets'][le])}")
                suffix = "{%s}" % base if base else ""
                lines.append(f"{name}_sum{suffix} "
                             f"{_fmt_value(agg['sum'])}")
                lines.append(f"{name}_count{suffix} "
                             f"{_fmt_value(agg['count'])}")
        else:
            for lbl, v in sorted(per_replica.get(name, {}).items()):
                tag = "{%s}" % _label_str(lbl) if lbl else ""
                lines.append(f"{name}{tag} {_fmt_value(v)}")
    return "\n".join(lines) + ("\n" if lines else "")
