"""Deterministic, seedable fault injection — the platform's chaos fixture.

The reference stack is *tested around failure*: training-operator e2e
suites kill pods to exercise restartPolicy, KServe relies on probe flaps,
and client-go retries are unit-tested against fake clients that error N
times. This module gives the rebuild the same muscle without pods: code
paths declare named **injection points** (`register_point` at import,
`fire(point, **ctx)` on the hot path), and tests arm **policies** against
them inside a scoped harness:

    with faults.harness(seed=7) as h:
        h.arm("controlplane.request", faults.FailN(2, ConnectionRefusedError))
        client.metrics()          # first two attempts refused, third lands
    assert h.counts["controlplane.request"]["injected"] == 2

Design constraints, in priority order:

  * **Zero overhead disarmed** — `fire()` is one module-global `is None`
    check when no harness is active; production never pays for the hook.
    The serve bench's happy-path numbers must be indistinguishable.
  * **Deterministic** — probabilistic policies draw from the harness's
    seeded rng in firing order; a test that replays the same call
    sequence injects the same faults. No wall-clock, no global random.
  * **Scoped** — the harness installs via context manager and uninstalls
    on exit even when the workload under test raises; tests can't leak
    armed faults into each other.

Policies (ISSUE 1): `FailN` (fail the first n matching firings),
`FailProb` (fail each matching firing with probability p), `Latency`
(sleep before proceeding — deadline/timeout exercise). Every policy takes
`match={...}` to restrict to firings whose context matches (e.g.
`FailN(1, match={"step": 4})` kills exactly training step 4).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Iterator


class FaultError(RuntimeError):
    """Default injected failure — a stand-in for 'the process died here'."""


#: name -> docstring; populated at import time by instrumented modules.
_POINTS: dict[str, str] = {}


def register_point(name: str, doc: str = "") -> str:
    """Declare an injection point (idempotent). Called at module import by
    instrumented code so `arm()` can reject typo'd names."""
    _POINTS.setdefault(name, doc)
    return name


def list_points() -> dict[str, str]:
    return dict(_POINTS)


class Policy:
    """Base: `match` filters firings by context equality on the given
    keys; non-matching firings pass through untouched (and uncounted)."""

    def __init__(self, match: dict | None = None):
        self.match = dict(match or {})

    def matches(self, ctx: dict) -> bool:
        return all(ctx.get(k) == v for k, v in self.match.items())

    def on_fire(self, rng, ctx: dict) -> BaseException | float | None:
        """Return an exception to inject, a float latency (seconds) to
        sleep, or None to pass through."""
        raise NotImplementedError

    def _make(self, ctx):
        """Instantiate this policy's `exc` (class or ready instance)."""
        if isinstance(self.exc, BaseException):
            return self.exc
        return self.exc(f"injected fault ({ctx.get('point')})")


class FailN(Policy):
    """Fail the first `n` matching firings with `exc`, then pass — the
    'transient error that heals' shape every retry loop is written for."""

    def __init__(self, n: int, exc: type[BaseException] | BaseException
                 = FaultError, match: dict | None = None):
        super().__init__(match)
        self.n = int(n)
        self.exc = exc
        self._left = int(n)

    def on_fire(self, rng, ctx):
        if self._left > 0:
            self._left -= 1
            return self._make(ctx)
        return None

    @property
    def remaining(self) -> int:
        return self._left


class FailProb(Policy):
    """Fail each matching firing with probability `p`, drawing from the
    harness rng (deterministic per seed + firing order)."""

    def __init__(self, p: float, exc: type[BaseException] | BaseException
                 = FaultError, match: dict | None = None):
        super().__init__(match)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p}")
        self.p = float(p)
        self.exc = exc

    def on_fire(self, rng, ctx):
        if rng.random() < self.p:
            return self._make(ctx)
        return None


class Latency(Policy):
    """Sleep `seconds` before the protected operation proceeds — how
    deadline/overload behavior is exercised without a slow model."""

    def __init__(self, seconds: float, match: dict | None = None):
        super().__init__(match)
        self.seconds = float(seconds)

    def on_fire(self, rng, ctx):
        return self.seconds


class FaultHarness:
    """Holds armed policies and per-point firing counts. Thread-safe:
    instrumented code fires from worker threads (batcher, engine loop)."""

    def __init__(self, seed: int = 0):
        import random

        self.rng = random.Random(seed)
        self._armed: dict[str, list[Policy]] = {}
        self.counts: dict[str, dict[str, int]] = {}
        self._lock = threading.Lock()

    def arm(self, point: str, policy: Policy) -> "FaultHarness":
        """Attach `policy` to `point`. Unknown points raise — a typo'd
        name would otherwise arm a fault that can never fire."""
        if point not in _POINTS:
            raise ValueError(
                f"unknown injection point {point!r}; registered: "
                f"{sorted(_POINTS)}")
        with self._lock:
            self._armed.setdefault(point, []).append(policy)
        return self

    def disarm(self, point: str) -> None:
        with self._lock:
            self._armed.pop(point, None)

    def fire(self, point: str, ctx: dict) -> None:
        with self._lock:
            policies = list(self._armed.get(point, ()))
            if not policies:
                return
            c = self.counts.setdefault(point,
                                       {"fired": 0, "injected": 0,
                                        "delayed": 0})
            c["fired"] += 1
            actions = []
            for p in policies:
                if not p.matches(ctx):
                    continue
                act = p.on_fire(self.rng, dict(ctx, point=point))
                if act is not None:
                    actions.append(act)
                    if isinstance(act, BaseException):
                        c["injected"] += 1
                    else:
                        c["delayed"] += 1
        # Sleep/raise OUTSIDE the lock: a Latency policy must not block
        # concurrent firings (that would serialize the very overload the
        # test is trying to create).
        for act in actions:
            if isinstance(act, BaseException):
                raise act
            time.sleep(act)

    def scope(self) -> "contextlib.AbstractContextManager[FaultHarness]":
        return _install(self)


_ACTIVE: FaultHarness | None = None
_INSTALL_LOCK = threading.Lock()


def fire(point: str, **ctx: Any) -> None:
    """Hot-path hook. ONE global read + None check when disarmed — the
    whole production cost of the harness."""
    h = _ACTIVE
    if h is None:
        return
    h.fire(point, ctx)


def active() -> FaultHarness | None:
    return _ACTIVE


@contextlib.contextmanager
def _install(h: FaultHarness) -> Iterator[FaultHarness]:
    global _ACTIVE
    with _INSTALL_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a fault harness is already installed "
                               "(nesting would make injections ambiguous)")
        _ACTIVE = h
    try:
        yield h
    finally:
        _ACTIVE = None


@contextlib.contextmanager
def harness(seed: int = 0) -> Iterator[FaultHarness]:
    """`with faults.harness(seed=7) as h: h.arm(...)` — the test fixture."""
    with _install(FaultHarness(seed)) as h:
        yield h
