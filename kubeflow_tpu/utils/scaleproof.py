"""AOT scale proof: compile the 8B contract on virtual v5p-shaped meshes.

The capability contract (BASELINE.json / SURVEY.md §6) is Llama-3-8B
fine-tune via JAXJob on v5p at >=45% MFU. This environment has one emulated
v5e chip, so 8B can never *run* here — but it can be **proven to compile and
fit**: XLA's AOT path (`jit(step).lower(...).compile()`) works on N virtual
CPU devices with the real shardings, and `compiled.memory_analysis()`
reports per-device buffer sizes (arguments = parameter/optimizer/batch
shards, temp = activation working set). That is the strongest signal this
environment can produce about the target topology, and it is exactly how a
production launch would pre-flight a config before burning pod-hours.

Cases (device == chip; v5p carries 95 GB HBM per chip):
  * train_8b_v5p8       — fsdp=4 x tensor=2 over 8 devices, seq 4096
  * train_8b_v5p8_long  — same mesh, seq 8192 (long-context fine-tune point)
  * train_8b_v5p32_2slice — data=2 (DCN) x fsdp=16 over 32 devices as two
    slices: the eval-config-5 topology, slice-major device order so only DP
    gradient all-reduce crosses DCN (parallel/mesh.py).
  * serve_8b_tp8        — bf16 weights sharded tensor=8; prefill bucket +
    batched decode step against an 8k KV cache (serving memory envelope).

Every training case compiles the FULL train step — fwd + bwd + adamw
(bf16 mu) — with full-block remat and chunked cross entropy, i.e. the same
knobs the trainer runs (train/step.py, train/trainer.py).

Each case runs in a fresh subprocess so the virtual device count can be set
before backend init (same re-exec pattern as __graft_entry__.dryrun).
Output: SCALEPROOF.json with per-device byte budgets + fit assertions.

Reference parity note: the reference platform cannot make this promise at
all — Kubeflow schedules pods and leaves OOM discovery to the user's first
real run (SURVEY.md §2.6: no parallelism math in the platform).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

V5P_HBM_BYTES = 95 * 1024**3  # 95 GiB per v5p chip
GIB = 1024**3

CASES = (
    "train_8b_v5p8",
    "train_8b_v5p8_long",
    "train_8b_v5p8_fsdp",
    "train_8b_v5p32_2slice",
    "serve_8b_tp8",
)

_CASE_DEVICES = {
    "train_8b_v5p8": 8,
    "train_8b_v5p8_long": 8,
    "train_8b_v5p8_fsdp": 8,
    "train_8b_v5p32_2slice": 32,
    "serve_8b_tp8": 8,
}


def _mem_report(compiled, *, hbm_bytes: int = V5P_HBM_BYTES,
                chip: str = "v5p") -> dict:
    """memory_analysis() → conservative per-device fit report. The ONE
    copy of this arithmetic — the long-context analysis
    (utils/longctx.py) consumes it with the v5e budget."""
    ma = compiled.memory_analysis()
    args = int(ma.argument_size_in_bytes)
    temp = int(ma.temp_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    # Newer jaxlibs dropped peak_memory_in_bytes from CompiledMemoryStats;
    # args+temp is the same conservative stand-in the total already uses
    # (peak <= live arguments + live temps at the worst program point).
    peak = int(getattr(ma, "peak_memory_in_bytes", 0)) or (args + temp)
    # Conservative per-device live set: arguments + temps + outputs with no
    # donation credit (alias_size already subtracts what XLA aliased; the
    # CPU backend typically reports 0, so this double-counts donated state
    # — if even that fits, the TPU number fits with margin).
    total = args + temp + out - alias
    return {
        "argument_bytes": args,
        "temp_bytes": temp,
        "output_bytes": out,
        "alias_bytes": alias,
        "peak_memory_bytes": peak,
        "total_conservative_bytes": total,
        "total_conservative_gib": round(total / GIB, 2),
        f"fits_{chip}_hbm": total <= hbm_bytes,
        "hbm_budget_gib": round(hbm_bytes / GIB, 2),
    }


def _train_case(mesh_cfg_kwargs: dict, batch: int, seq: int, *,
                fsdp_runtime: bool = False,
                param_dtype: str | None = None,
                grad_accum: int = 1) -> dict:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import flax.linen as nn
    import optax

    from kubeflow_tpu.models.llama import Llama, llama3_8b
    from kubeflow_tpu.parallel.fsdp import FSDP, parse_compute_dtype, \
        tree_bytes_per_device
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES
    from kubeflow_tpu.train.step import abstract_train_state, make_train_step

    # Force the flash kernel (interpret-lowered off-TPU): the production
    # config's attention never materializes the [S,T] score tensor, and
    # `auto` would fall back to naive on the CPU backend, inflating the
    # measured temp memory with buffers the TPU deployment doesn't have.
    cfg = dataclasses.replace(llama3_8b(), attention_impl="flash")
    model = Llama(cfg)
    mesh = build_mesh(MeshConfig(**mesh_cfg_kwargs))
    rules = DEFAULT_RULES
    tx = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)

    from jax.sharding import NamedSharding, PartitionSpec as P

    # The fsdp master-state runtime (parallel/fsdp.py), exactly as the
    # trainer would launch it: every fp32-param/Adam-moment leaf carries
    # the fsdp axis, gathers for compute happen inside the step.
    plan = None
    if fsdp_runtime:
        plan = FSDP(mesh, compute_dtype=parse_compute_dtype(param_dtype))

    # The SAME layout derivation the trainer uses (train/step.py) — the
    # proof must measure the production layout, not a reimplementation.
    _, abstract, shardings = abstract_train_state(
        model, tx, (jnp.zeros((1, 8), jnp.int32),), mesh, rules, fsdp=plan)
    state_args = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract, shardings)

    with mesh, nn.logical_axis_rules(rules):
        batch_sh = NamedSharding(mesh, P(("data", "fsdp"), None))
        batch_args = {
            "inputs": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                           sharding=batch_sh),
            "targets": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                            sharding=batch_sh),
        }

        step = make_train_step(model, mesh, rules, loss_impl="chunked",
                               loss_chunk=2048, fsdp=plan,
                               accum_steps=grad_accum)
        lowered = step.jitted.lower(state_args, batch_args)
    compiled = lowered.compile()

    n_params = cfg.num_params
    report = _mem_report(compiled)
    report.update({
        "model": "llama3_8b",
        "num_params": n_params,
        "mesh": {k: v for k, v in mesh.shape.items() if v > 1},
        "num_devices": mesh.devices.size,
        "global_batch": batch,
        "seq_len": seq,
        "remat": cfg.remat_policy,
        "loss_impl": "chunked",
        "optimizer": "adamw(mu=bf16)",
        # Analytic floor for sanity: fp32 params + bf16 mu + fp32 nu,
        # sharded over every mesh axis the param rules use.
        "analytic_state_gib": round(
            n_params * (4 + 2 + 4) / mesh.devices.size / GIB, 2),
        # State-layout accounting from the ACTUAL shardings (the same
        # arithmetic the trainer's tpk_train_*_bytes_per_chip gauges
        # report): what one chip holds of params / optimizer state.
        "param_bytes_per_chip": tree_bytes_per_device(state_args.params),
        "opt_state_bytes_per_chip": tree_bytes_per_device(
            state_args.opt_state),
    })
    if fsdp_runtime:
        report.update({
            "fsdp_runtime": True,
            "param_dtype": param_dtype or "master",
            "grad_accum": grad_accum,
            # What pure-DP replication would pin on EVERY chip (fp32
            # params + bf16 mu + fp32 nu) — the number the fsdp axis
            # divides; the measured per-chip fields above are the
            # divided reality.
            "analytic_state_replicated_gib": round(
                n_params * (4 + 2 + 4) / GIB, 2),
        })
    return report


def _case_train_8b_v5p8() -> dict:
    return _train_case(dict(data=1, fsdp=4, tensor=2), batch=8, seq=4096)


def _case_train_8b_v5p8_long() -> dict:
    return _train_case(dict(data=1, fsdp=4, tensor=2), batch=8, seq=8192)


def _case_train_8b_v5p8_fsdp() -> dict:
    """ISSUE 15 tentpole row: the same v5p-8 bench point as
    train_8b_v5p8, but through the fsdp master-state runtime — fp32
    params + Adam moments sharded over fsdp on EVERY leaf, bf16 gathered
    compute copies, grad_accum=2 decoupling global batch from per-chip
    activation memory. The delta against train_8b_v5p8 is the
    optimizer-state unlock PROFILE §4 names."""
    return _train_case(dict(data=1, fsdp=4, tensor=2), batch=8, seq=4096,
                       fsdp_runtime=True, param_dtype="bfloat16",
                       grad_accum=2)


def _case_train_8b_v5p32_2slice() -> dict:
    return _train_case(dict(data=2, fsdp=16, num_slices=2),
                       batch=32, seq=8192)


def _case_serve_8b_tp8() -> dict:
    """Serving envelope: bf16 8B weights tensor-sharded 8-way. Compiles
    the GENERATION ENGINE'S OWN functions (serve/generation.py
    build_engine_fns — the exact prefill/chunked-decode programs the
    product dispatches, not hand-written stand-ins) with the same
    shardings `GenerationEngine(mesh=...)` installs, and asserts the
    working set fits one v5p chip's HBM share. This is the proof that TP
    serving of the flagship — which an 8B bf16 model *requires*, not
    fitting one chip — compiles and fits as the product would run it."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    from kubeflow_tpu.models.llama import Llama, init_cache, llama3_8b
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec
    from kubeflow_tpu.serve.generation import build_engine_fns
    from jax.sharding import NamedSharding, PartitionSpec as P

    # remat off: inference has no backward, and the remat wrapper's static
    # argnums don't admit a traced cache anyway.
    cfg = dataclasses.replace(llama3_8b(), param_dtype=jnp.bfloat16,
                              remat=False)
    model = Llama(cfg)
    mesh = build_mesh(MeshConfig(data=1, tensor=8))
    rules = DEFAULT_RULES

    slots, max_len, prefill_bucket, chunk = 8, 8192, 2048, 16

    with mesh, nn.logical_axis_rules(rules):
        abstract = jax.eval_shape(
            lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))["params"],
            jax.random.key(0))
        specs = nn.get_partition_spec(abstract)
        shardings = nn.logical_to_mesh_sharding(specs, mesh, rules)
        params_args = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            nn.meta.unbox(abstract), shardings)

        # KV heads shard over tensor — the same spec GenerationEngine
        # derives (generation.py _shard_params).
        cache_sh = NamedSharding(
            mesh, logical_to_spec(("layers", None, None, "heads", "kv"),
                                  rules))
        cache_shape = jax.eval_shape(
            lambda: init_cache(cfg, slots, max_len))
        cache_args = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=cache_sh), cache_shape)
        repl = NamedSharding(mesh, P())

        fns = build_engine_fns(
            model, cfg, max_len=max_len, chunk=chunk,
            prefill_buckets=(prefill_bucket,),
            offset_writes=True, cache_sharding=cache_sh)

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

        key_arg = jax.eval_shape(lambda: jax.random.key(0))
        # Engine prefill: slot-batch-1 fragment, on-device sampling.
        pre_lowered = jax.jit(fns["prefill"]).lower(
            params_args, sds((1, prefill_bucket), jnp.int32),
            sds((1,), jnp.int32), sds((1,), jnp.float32),
            sds((1,), jnp.int32), sds((1,), jnp.float32), key_arg)
        # Engine chunked decode: `chunk` steps over the full slot batch
        # under one dispatch (the steady-state hot program).
        dec_lowered = jax.jit(fns["make_decode"](False, max_len),
                              donate_argnums=(1,)).lower(
            params_args, cache_args, sds((slots,), jnp.int32),
            sds((slots,), jnp.int32), sds((slots,), jnp.float32),
            sds((slots,), jnp.int32), sds((slots,), jnp.float32), key_arg)
    pre = _mem_report(pre_lowered.compile())
    dec = _mem_report(dec_lowered.compile())
    return {
        "model": "llama3_8b",
        "weights": "bf16",
        "mesh": {"tensor": 8},
        "num_devices": 8,
        "slots": slots,
        "max_len": max_len,
        "prefill_bucket": prefill_bucket,
        "decode_chunk": chunk,
        "engine_fns": "serve/generation.py build_engine_fns",
        "prefill": pre,
        "decode": dec,
        "fits_v5p_hbm": pre["fits_v5p_hbm"] and dec["fits_v5p_hbm"],
    }


def run_case(name: str) -> dict:
    fn = globals()[f"_case_{name}"]
    return fn()


def run_case_subprocess(name: str, timeout_s: float = 1800.0) -> dict:
    """Re-exec with the CPU platform and the case's virtual device count
    (backends can't be reconfigured after init — same constraint as
    __graft_entry__.dryrun_multichip)."""
    from kubeflow_tpu.utils.reexec import cpu_reexec_env

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = cpu_reexec_env(_CASE_DEVICES[name], repo=repo)
    code = (
        "import json, sys\n"
        "from kubeflow_tpu.utils import scaleproof\n"
        f"r = scaleproof.run_case({name!r})\n"
        "print('SCALEPROOF_JSON:' + json.dumps(r))\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=timeout_s)
    if proc.returncode != 0:
        raise RuntimeError(
            f"scaleproof case {name} failed rc={proc.returncode}:\n"
            f"{proc.stderr[-4000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("SCALEPROOF_JSON:"):
            return json.loads(line[len("SCALEPROOF_JSON:"):])
    raise RuntimeError(f"scaleproof case {name}: no result line in output")


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="SCALEPROOF.json")
    parser.add_argument("--cases", nargs="*", default=list(CASES))
    parser.add_argument(
        "--merge", action="store_true",
        help="update only --cases inside an existing --out document "
             "(other rows kept verbatim; all_fit recomputed over the "
             "union) instead of rewriting it with just this run")
    args = parser.parse_args(argv)

    results, ok = {}, True
    if args.merge and os.path.exists(args.out):
        with open(args.out) as fh:
            results = dict(json.load(fh).get("cases", {}))
    for name in args.cases:
        print(f"[scaleproof] compiling {name} "
              f"({_CASE_DEVICES[name]} virtual devices)...",
              file=sys.stderr, flush=True)
        try:
            results[name] = run_case_subprocess(name)
            fit = results[name].get("fits_v5p_hbm")
            print(f"[scaleproof] {name}: fits_v5p_hbm={fit}",
                  file=sys.stderr, flush=True)
            ok = ok and bool(fit)
        except Exception as e:  # record the failure, keep proving the rest
            results[name] = {"error": str(e)}
            ok = False
            print(f"[scaleproof] {name}: ERROR {e}", file=sys.stderr)
    # all_fit covers the whole document — including rows a --merge run
    # kept verbatim — so a merge can never launder a failing row.
    ok = ok and all("error" not in r and bool(r.get("fits_v5p_hbm"))
                    for r in results.values())
    payload = {
        "contract": "Llama-3-8B fine-tune via JAXJob on v5p (BASELINE.json)",
        "method": "AOT jit().lower().compile() + memory_analysis() on "
                  "virtual CPU device meshes with production shardings",
        "hbm_budget_gib": round(V5P_HBM_BYTES / GIB, 2),
        "all_fit": ok,
        "cases": results,
    }
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    print(json.dumps({"scaleproof": {"all_fit": ok, "out": args.out}}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
