"""Request-scoped tracing: spans, trace ids, a bounded ring, Chrome export.

The reference platform has NO unified tracing (SURVEY.md §5.1: per-
controller Prometheus counters only) — a slow request tells you *that*
it was slow, never *where the time went*. This module is the one tracing
surface every layer shares:

  * **Trace identity.** One request id threads through the whole stack:
    the model server assigns/honors `X-Request-Id`, the control-plane
    client attaches its id to every RPC, the trainer uses its job name.
    Spans carry the id, so a single request's admit → batch-gather →
    prefill → decode → fetch timeline can be filtered out of process
    noise.
  * **Spans.** Host-side wall intervals with a name, a trace id, and
    small attrs. Two recording styles: `span(...)` as a context manager
    around synchronous work, and `Tracer.record(...)` for intervals
    measured externally (the serving engine times dispatch→fetch itself
    — the device executes asynchronously, so a `with` block around the
    dispatch would lie).
  * **Bounded ring, zero hot-path cost.** Finished spans land in a
    process-local ring (`deque(maxlen=capacity)`) — old spans fall off,
    memory never grows with run length. Spans never touch device
    arrays: recording is perf_counter arithmetic + one append, so the
    train/decode hot loops keep their zero-host-sync guarantees with
    tracing at default settings (the span-overhead guard test pins
    this). `TPK_TRACE=0` (or `tracer.enabled = False`) turns recording
    into a shared no-op object — nothing is allocated at all.
  * **Chrome trace export.** `chrome_trace()` renders the ring as
    Chrome trace-event JSON (`ph: "X"` complete events), loadable in
    chrome://tracing / Perfetto: `GET /debug/trace` on the model
    server, `tpukit trace` for the control plane — no mesh, no sidecar,
    no collector.
"""

from __future__ import annotations

import os
import re
import threading
import time
import uuid
from collections import deque

#: ts values are microseconds since this process-local epoch (Chrome
#: trace wants a monotonic µs timeline, not wall time).
_EPOCH = time.perf_counter()

_TRACE_ID_RE = re.compile(r"[^A-Za-z0-9._:-]")
_MAX_TRACE_ID = 128


def new_trace_id() -> str:
    """A fresh request/trace id (uuid4 hex — no coordination needed)."""
    return uuid.uuid4().hex


def perf_to_us(t: float) -> float:
    """A time.perf_counter() reading as microseconds on this process's
    span timeline (the `ts` unit chrome_trace exports)."""
    return (t - _EPOCH) * 1e6


def sanitize_trace_id(raw: str | None) -> str:
    """A caller-supplied id, made safe for logs/exposition: restricted
    charset, bounded length; empty/None gets a fresh id."""
    if not raw:
        return new_trace_id()
    return _TRACE_ID_RE.sub("_", str(raw))[:_MAX_TRACE_ID] or new_trace_id()


class Span:
    """A finished (or in-flight, inside `with`) host-side interval."""

    __slots__ = ("name", "trace_id", "attrs", "ts_us", "dur_us", "tid",
                 "_tracer", "_t0")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 attrs: dict | None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs
        self.ts_us = 0.0
        self.dur_us = 0.0
        self.tid = ""
        self._t0 = 0.0

    @property
    def dur_s(self) -> float:
        return self.dur_us / 1e6

    def set(self, **attrs) -> "Span":
        """Attach/overwrite attrs (mid-span annotations)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        self.ts_us = (self._t0 - _EPOCH) * 1e6
        self.dur_us = (t1 - self._t0) * 1e6
        self.tid = threading.current_thread().name
        self._tracer._append(self)


class _NopSpan:
    """Shared do-nothing span — what `span()` hands out when tracing is
    disabled. One instance for the whole process: zero allocation on the
    disabled path."""

    __slots__ = ()
    name = ""
    trace_id = ""
    attrs: dict | None = None
    ts_us = dur_us = 0.0
    dur_s = 0.0
    tid = ""

    def set(self, **attrs) -> "_NopSpan":
        return self

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOP_SPAN = _NopSpan()


class Tracer:
    """Process-local span recorder with a bounded ring buffer."""

    def __init__(self, capacity: int = 4096, enabled: bool | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        if enabled is None:
            enabled = os.environ.get("TPK_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        self._ring: deque[Span] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    # -- recording ----------------------------------------------------------

    def span(self, name: str, trace_id: str = "", **attrs):
        """Context manager measuring the enclosed block. Returns the
        Span (its `dur_s` is valid after exit) — or the shared no-op
        when tracing is disabled."""
        if not self.enabled:
            return NOP_SPAN
        return Span(self, name, trace_id, attrs or None)

    def record(self, name: str, t0: float, t1: float, trace_id: str = "",
               **attrs) -> None:
        """Record an externally measured interval (`t0`/`t1` are
        time.perf_counter() readings)."""
        if not self.enabled:
            return
        sp = Span(self, name, trace_id, attrs or None)
        sp.ts_us = (t0 - _EPOCH) * 1e6
        sp.dur_us = max(t1 - t0, 0.0) * 1e6
        sp.tid = threading.current_thread().name
        self._append(sp)

    def _append(self, sp: Span) -> None:
        with self._lock:
            self._ring.append(sp)

    # -- export -------------------------------------------------------------

    def events(self, trace_id: str | None = None) -> list[dict]:
        """Spans as plain dicts, oldest first; optionally filtered to one
        trace id."""
        with self._lock:
            spans = list(self._ring)
        out = []
        for sp in spans:
            if trace_id is not None and sp.trace_id != trace_id:
                continue
            out.append({
                "name": sp.name, "trace_id": sp.trace_id,
                "ts_us": sp.ts_us, "dur_us": sp.dur_us, "tid": sp.tid,
                "attrs": dict(sp.attrs) if sp.attrs else {},
            })
        return out

    def chrome_trace(self, trace_id: str | None = None) -> dict:
        """The ring as a Chrome trace-event document (chrome://tracing /
        Perfetto's legacy JSON format): `ph: "X"` complete events, ts/dur
        in microseconds, the trace id and attrs under `args`."""
        pid = os.getpid()
        events = []
        for ev in self.events(trace_id):
            events.append({
                "name": ev["name"], "cat": "tpk", "ph": "X",
                "ts": round(ev["ts_us"], 3), "dur": round(ev["dur_us"], 3),
                "pid": pid, "tid": ev["tid"] or "main",
                "args": {"trace_id": ev["trace_id"], **ev["attrs"]},
            })
        # `now_us` stamps export time on this process's own µs timeline.
        # A fetcher that measured the request's RTT can estimate the
        # clock offset between its timeline and ours (midpoint method,
        # see merge_chrome_traces) — Chrome/Perfetto ignore unknown
        # top-level keys.
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "now_us": perf_to_us(time.perf_counter())}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


#: The process-global tracer. Read through `get_tracer()` / the module
#: helpers so tests can swap in a bounded/disabled instance.
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer (tests); returns the previous one."""
    global _TRACER
    prev, _TRACER = _TRACER, tracer
    return prev


def span(name: str, trace_id: str = "", **attrs):
    """Module-level convenience: a span on the process-global tracer."""
    return _TRACER.span(name, trace_id, **attrs)


def record(name: str, t0: float, t1: float, trace_id: str = "",
           **attrs) -> None:
    _TRACER.record(name, t0, t1, trace_id, **attrs)


def merge_chrome_traces(parts: list[dict]) -> dict:
    """Merge per-process Chrome trace documents onto ONE timeline.

    Each part is `{"process": name, "doc": chrome_trace() output,
    "offset_us": float, "err_us": float | None}` — `offset_us` shifts
    that process's span timestamps onto the merging process's timeline
    (add it to every `ts`), `err_us` is the honest uncertainty of that
    estimate (half the fetch RTT with the midpoint method; None means
    the part was NOT aligned — e.g. an old replica whose export lacks
    `now_us` — and rides un-shifted).

    Every process gets a synthetic pid (original pids can collide across
    hosts) plus a `ph: "M"` process_name metadata event, so Perfetto
    shows one labeled track per process. The alignment estimates are
    kept in the output under `clock_alignment` — the merged timeline is
    an ESTIMATE with a stated error bar, never presented as exact.
    """
    events: list[dict] = []
    alignment: dict[str, dict] = {}
    for pid, part in enumerate(parts):
        name = str(part.get("process") or f"proc{pid}")
        doc = part.get("doc") or {}
        offset_us = float(part.get("offset_us") or 0.0)
        err_us = part.get("err_us")
        alignment[name] = {
            "offset_us": round(offset_us, 3),
            "skew_err_us": (round(float(err_us), 3)
                            if err_us is not None else None),
            "aligned": err_us is not None,
        }
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        for ev in doc.get("traceEvents") or []:
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + offset_us, 3)
            events.append(ev)
    events.sort(key=lambda ev: (ev.get("ph") != "M", ev.get("ts", 0.0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "clock_alignment": alignment}


class FlightRecorder:
    """Bounded ring of per-request outcome records + chaos snapshots.

    The router drops one record per concluded request (trace id, replica
    trail including resumes, TTFT/e2e, outcome, shed/deadline reason) —
    a postmortem of the last-K requests that costs one dict append, no
    live debugger, no log scraping. `snapshot(reason)` freezes the tail
    at interesting moments (resume fired, replica ejected) so the
    context *around* a chaos event survives ring turnover.
    """

    def __init__(self, capacity: int = 512, snapshot_capacity: int = 16,
                 snapshot_tail: int = 32):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.snapshot_tail = int(snapshot_tail)
        self._lock = threading.Lock()
        self._records: deque[dict] = deque(
            maxlen=self.capacity)  # guarded-by: _lock
        self._snapshots: deque[dict] = deque(
            maxlen=int(snapshot_capacity))  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    def record(self, **fields) -> dict:
        """Append one concluded-request record; returns it (with its
        monotone `seq` stamped)."""
        rec = dict(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._records.append(rec)
        return rec

    def tail(self, n: int | None = None) -> list[dict]:
        """Most-recent-last copies of the last `n` records (all, when
        n is None)."""
        with self._lock:
            recs = list(self._records)
        if n is not None:
            recs = recs[-max(int(n), 0):] if n else []
        return [dict(r) for r in recs]

    def lookup(self, trace_id: str) -> dict | None:
        """The most recent record for `trace_id`, or None."""
        with self._lock:
            for rec in reversed(self._records):
                if rec.get("trace_id") == trace_id:
                    return dict(rec)
        return None

    def snapshot(self, reason: str, **context) -> dict:
        """Freeze the last `snapshot_tail` records under `reason` (e.g.
        ``resume:dec0``, ``eject:m1``) with a wall-clock stamp."""
        with self._lock:
            snap = {
                "reason": reason, "t_unix": time.time(),
                "context": dict(context),
                "records": [dict(r) for r in
                            list(self._records)[-self.snapshot_tail:]],
            }
            self._snapshots.append(snap)
        return snap

    def snapshots(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._snapshots]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._snapshots.clear()
