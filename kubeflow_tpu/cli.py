"""tpukit — the CLI for the whole platform (SURVEY.md §7.1 item 9).

Replaces the reference's kubectl+web-UI surface (L5 descoped to CLI per
§7.0): submit/get/list/logs/delete for JAXJobs, control-plane lifecycle,
slice and metrics introspection.

  tpukit controlplane --socket /tmp/tpk.sock --workdir /tmp/tpk --slices local=8
  tpukit submit examples/mnist_jaxjob.yaml
  tpukit get job mnist
  tpukit list jobs
  tpukit logs mnist -r 0 [-f]
  tpukit delete job mnist
  tpukit slices | tpukit metrics
  tpukit events mnist          # per-job event history (WAL-persisted)
  tpukit trace -o trace.json   # control-plane spans for chrome://tracing
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _client(args) -> "Client":
    from kubeflow_tpu.controlplane.client import Client

    return Client(args.socket)


def _load_spec(path: str) -> dict:
    with open(path) as fh:
        text = fh.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        return yaml.safe_load(text)
    return json.loads(text)


def cmd_controlplane(args) -> int:
    from kubeflow_tpu.controlplane.client import find_binary
    import subprocess

    cmd = [find_binary(), "--socket", args.socket, "--workdir", args.workdir,
           "--slices", args.slices, "--python", sys.executable]
    if args.wal:
        cmd += ["--wal", args.wal]
    # Durability knobs pass straight through to the binary.
    cmd += ["--fsync", args.fsync, "--fsync-interval",
            str(args.fsync_interval), "--compact", str(args.compact),
            "--group-commit", str(args.group_commit)]
    print("exec:", " ".join(cmd), file=sys.stderr)
    return subprocess.call(cmd)


def cmd_submit(args) -> int:
    doc = _load_spec(args.file)
    # YAML docs may be CR-style ({kind, metadata:{name}, spec}) or bare spec.
    kind = doc.get("kind", "JAXJob")
    name = args.name or doc.get("metadata", {}).get("name")
    spec = doc.get("spec", doc if "kind" not in doc else {})
    if not name:
        print("error: job name required (metadata.name or --name)",
              file=sys.stderr)
        return 2
    c = _client(args)
    c.create(kind, name, spec)
    print(f"{kind}/{name} created")
    if args.wait:
        phase = c.wait_for_phase(name, timeout=args.timeout, kind=kind)
        print(f"{kind}/{name} {phase}")
        return 0 if phase == "Succeeded" else 1
    return 0


def _kind_alias(kind: str) -> str:
    aliases = {"job": "JAXJob", "jobs": "JAXJob", "jaxjob": "JAXJob",
               "inferenceservice": "InferenceService", "isvc": "InferenceService",
               "experiment": "Experiment", "experiments": "Experiment",
               "trial": "Trial", "trials": "Trial",
               "pipeline": "Pipeline", "pipelines": "Pipeline",
               "run": "PipelineRun", "runs": "PipelineRun",
               "trainedmodel": "TrainedModel", "tm": "TrainedModel",
               "profile": "Profile", "profiles": "Profile"}
    return aliases.get(kind.lower(), kind)


def cmd_get(args) -> int:
    c = _client(args)
    res = c.get(_kind_alias(args.kind), args.name)
    print(json.dumps(res, indent=2))
    return 0


def cmd_list(args) -> int:
    c = _client(args)
    items = c.list(_kind_alias(args.kind),
                   namespace=getattr(args, "namespace", None))
    fmt = "{:<24} {:<12} {:<12} {:<10} {:<8}"
    print(fmt.format("NAME", "NAMESPACE", "PHASE", "RESTARTS", "GEN"))
    from kubeflow_tpu.controlplane.client import namespace_of

    for r in items:
        st = r.get("status", {})
        ns = namespace_of(r)
        print(fmt.format(r["name"], ns, st.get("phase", ""),
                         str(st.get("restarts", 0)), str(r.get("generation"))))
    return 0


def cmd_logs(args) -> int:
    c = _client(args)
    if not args.follow:
        sys.stdout.write(c.logs(args.name, args.replica, stderr=args.stderr))
        return 0
    seen = 0  # absolute file offset already printed

    def emit():
        nonlocal seen
        r = c.logs_ex(args.name, args.replica, stderr=args.stderr,
                      max_bytes=1 << 20)
        size, offset, content = r["size"], r["offset"], r["content"]
        if size > seen:
            # Print only bytes past `seen`; if the tail window already
            # scrolled past them, print the whole window (gap is lost).
            start = max(seen - offset, 0)
            sys.stdout.write(content[start:])
            sys.stdout.flush()
            seen = size

    while True:
        try:
            emit()
        except Exception:
            pass  # log file may not exist yet
        phase = c.phase(args.name)
        if phase in ("Succeeded", "Failed"):
            emit()
            print(f"\n--- job {phase} ---", file=sys.stderr)
            return 0 if phase == "Succeeded" else 1
        time.sleep(1.0)


def cmd_delete(args) -> int:
    c = _client(args)
    c.delete(_kind_alias(args.kind), args.name)
    print(f"{args.kind}/{args.name} deleted")
    return 0


def cmd_compile(args) -> int:
    """Compile @pipeline objects from a python file to IR JSON
    (kfp-compiler CLI parity)."""
    import importlib.util

    from kubeflow_tpu.pipelines.dsl import Pipeline, compile_pipeline

    spec = importlib.util.spec_from_file_location("user_pipeline", args.file)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # Dedup aliases (two module names bound to one Pipeline) by identity.
    pipelines = {id(p): p for p in vars(mod).values()
                 if isinstance(p, Pipeline)}
    if args.pipeline:
        pipelines = {k: v for k, v in pipelines.items()
                     if v.name == args.pipeline}
        if not pipelines:
            print(f"error: no pipeline named {args.pipeline!r}",
                  file=sys.stderr)
            return 2
    if not pipelines:
        print("error: no @pipeline objects found", file=sys.stderr)
        return 2
    if len(pipelines) > 1:
        names = sorted(p.name for p in pipelines.values())
        print(f"error: multiple pipelines {names}; pick one with "
              f"--pipeline", file=sys.stderr)
        return 2
    (p,) = pipelines.values()
    ir = compile_pipeline(p)
    text = json.dumps(ir, indent=2, sort_keys=True)
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {args.output}")
    else:
        print(text)
    return 0


def cmd_slices(args) -> int:
    for s in _client(args).slices():
        print(f"{s['name']}: {s['used']}/{s['capacity']} devices used")
    return 0


def cmd_metrics(args) -> int:
    print(json.dumps(_client(args).metrics(), indent=2))
    return 0


def cmd_stateinfo(args) -> int:
    """Durability + replication health. Default is a human summary
    (the `tpukit replicas` style); --json emits the full stateinfo
    document (replay/groupCommit/watch/replication objects verbatim —
    the scriptable surface, documented in README 'Control plane')."""
    info = _client(args).stateinfo()
    if args.json:
        print(json.dumps(info, indent=2))
        return 0
    replay = info.get("replay", {})
    print(f"wal: {info.get('walPath') or '<in-memory>'} "
          f"({info.get('walRecords', 0)} records, seq "
          f"{info.get('walSeq', 0)}, fsync={info.get('fsync')}, "
          f"{'BROKEN' if info.get('walBroken') else 'healthy'})")
    print(f"replay: {replay.get('applied', 0)} applied = "
          f"{replay.get('snapshotRecords', 0)} snapshot + "
          f"{replay.get('tailRecords', 0)} tail, "
          f"{replay.get('truncatedBytes', 0)} bytes truncated, "
          f"{'clean' if replay.get('clean') else 'STOPPED AT CORRUPTION'}")
    gc = info.get("groupCommit", {})
    if gc.get("maxBatch"):
        print(f"group-commit: {gc.get('commits', 0)} commits / "
              f"{gc.get('records', 0)} records / "
              f"{gc.get('fsyncs', 0)} fsyncs "
              f"(mean batch {gc.get('meanBatch', 0):.1f})")
    repl = info.get("replication")
    if repl:
        print(f"replication: {repl['role']} term {repl['term']} "
              f"(leader: {repl.get('leader') or '<election pending>'}, "
              f"quorum {repl['quorum']}/{repl['replicas']}, "
              f"seq {repl['seq']}, applied {repl['appliedSeq']}, "
              f"commit {repl['commitSeq']}, lag {repl['lagRecords']})")
        print(f"  quorum commits {repl['quorumCommits']}, failures "
              f"{repl['quorumFailures']}, elections {repl['elections']}, "
              f"stale-leader rejections {repl['staleRejections']}, "
              f"snapshots shipped {repl['snapshotsShipped']}")
        fmt = "  {:<40} {:>10} {:>6} {}"
        print(fmt.format("FOLLOWER", "ACKED_SEQ", "LAG", "REACHABLE"))
        for f in repl.get("followers", []):
            print(fmt.format(f["sock"], f["ackedSeq"], f["lagRecords"],
                             "yes" if f["reachable"] else "no"))
    else:
        print("replication: off (single node)")
    return 0


def cmd_events(args) -> int:
    """Ordered per-job event history (the `kubectl describe` events
    table analog) — WAL-persisted, so it survives control-plane
    restarts."""
    out = _client(args).events(args.name, kind=_kind_alias(args.kind))
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    fmt = "{:<20} {:<8} {:<22} {:<6} {}"
    print(fmt.format("TIME", "TYPE", "REASON", "COUNT", "MESSAGE"))
    for ev in out["events"]:
        print(fmt.format(ev.get("timestamp", ""), ev.get("type", ""),
                         ev.get("reason", ""), str(ev.get("count", 1)),
                         ev.get("message", "")))
    return 0


def cmd_replicas(args) -> int:
    """The serving fleet as the router sees it (ISSUE 9): address,
    ready/draining state, live in-flight depth, free KV blocks, and the
    age of the last scrape — against the router's admin endpoint."""
    from kubeflow_tpu.serve.fleet import fetch_replicas

    out = fetch_replicas(args.router)
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    fmt = "{:<12} {:<28} {:<8} {:<9} {:>9} {:>8} {:>8} {:>8} {:>10}"
    print(fmt.format("NAME", "ADDRESS", "ROLE", "STATE", "OUT",
                     "INFLIGHT", "KV_FREE", "LAT_MS", "SCRAPE_AGE"))

    def cell(v, unit=""):
        return "-" if v is None else f"{v:g}{unit}"

    for r in out.get("replicas", []):
        print(fmt.format(r["name"], r["url"], r.get("role", "any"),
                         r["state"], str(r["outstanding"]),
                         cell(r["decode_inflight"]),
                         cell(r["kv_blocks_free"]),
                         cell(r.get("fwd_ewma_ms")),
                         cell(r["scrape_age_s"], "s")))
    handoffs = out.get("router", {}).get("handoffs", 0)
    if handoffs:
        print(f"disagg: handoffs={handoffs} "
              f"handoff_retries="
              f"{out['router'].get('handoff_retries', 0)} "
              f"resumes={out['router'].get('resumes', 0)} "
              f"resume_failures="
              f"{out['router'].get('resume_failures', 0)}")
    stats = out.get("router", {})
    if stats:
        print(f"router: placed={stats.get('placed', 0)} "
              f"affinity={stats.get('affinity_hits', 0)} "
              f"spill={stats.get('spills', 0)} "
              f"retries={stats.get('retries', 0)} "
              f"sheds={stats.get('sheds_forwarded', 0)}")
    return 0


def cmd_trace(args) -> int:
    """Spans as Chrome trace-event JSON — load the output in
    chrome://tracing or https://ui.perfetto.dev. Two sources: the
    control plane's span ring (default), or, with `--router URL
    TRACE_ID`, the router's ASSEMBLED distributed trace — router +
    prefill + decode (+ resume) replica spans merged onto one
    clock-aligned timeline."""
    if args.router:
        if not args.trace_id:
            print("error: tpukit trace --router needs a TRACE_ID "
                  "(the request's X-Request-Id)", file=sys.stderr)
            return 1
        import urllib.parse
        import urllib.request

        url = (f"{args.router.rstrip('/')}/debug/trace?trace_id="
               f"{urllib.parse.quote(args.trace_id)}")
        with urllib.request.urlopen(url, timeout=10.0) as r:
            doc = json.loads(r.read().decode())
    else:
        doc = _client(args).trace()
    text = json.dumps(doc, indent=1)
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {args.output} "
              f"({len(doc.get('traceEvents', []))} spans)")
    else:
        print(text)
    return 0


def cmd_requests(args) -> int:
    """The router's flight recorder (last-K per-request outcomes): who
    served each request, how many resumes/retries, TTFT/e2e, and the
    shed/deadline reason — the postmortem surface for 'what happened to
    request X' without a live debugger."""
    import urllib.request

    url = f"{args.router.rstrip('/')}/admin/flightrecorder"
    if args.n:
        url += f"?n={int(args.n)}"
    with urllib.request.urlopen(url, timeout=10.0) as r:
        out = json.loads(r.read().decode())
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    fmt = "{:<34} {:<10} {:<14} {:>8} {:>8} {:>7} {:>7} {}"
    print(fmt.format("TRACE_ID", "INTENT", "OUTCOME", "TTFT_MS",
                     "E2E_MS", "RESUME", "TRIES", "REPLICAS"))

    def ms(v):
        return "-" if v is None else f"{v * 1e3:.1f}"

    for rec in out.get("records", []):
        line = fmt.format(
            str(rec.get("trace_id", ""))[:34],
            rec.get("intent", "-"), rec.get("outcome", "-"),
            ms(rec.get("ttft_s")), ms(rec.get("e2e_s")),
            str(rec.get("resumes", 0)), str(rec.get("attempts", 0)),
            ",".join(rec.get("replicas") or []) or "-")
        if rec.get("reason"):
            line += f"  [{rec['reason']}]"
        print(line)
    snaps = out.get("snapshots", [])
    if snaps:
        print(f"snapshots: "
              + " ".join(s.get("reason", "?") for s in snaps))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpukit")
    parser.add_argument("--socket", default="/tmp/tpk.sock")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("controlplane", help="run the control plane")
    p.add_argument("--workdir", default="/tmp/tpk")
    p.add_argument("--slices", default="local=8")
    p.add_argument("--wal", default="")
    p.add_argument("--fsync", default="never",
                   choices=("never", "interval", "always"),
                   help="WAL fsync policy (loss window after SIGKILL)")
    p.add_argument("--fsync-interval", type=int, default=64)
    p.add_argument("--compact", type=int, default=4096,
                   help="snapshot+truncate past this many WAL records")
    p.add_argument("--group-commit", type=int, default=64,
                   help="max mutations per covering fsync "
                        "(0 = per-record appends)")
    p.set_defaults(fn=cmd_controlplane)

    p = sub.add_parser("submit", help="submit a job spec (yaml/json)")
    p.add_argument("file")
    p.add_argument("--name")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600.0)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("get")
    p.add_argument("kind")
    p.add_argument("name")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("list")
    p.add_argument("kind")
    p.add_argument("--namespace", "-n", default=None,
                   help="filter to one namespace (Profile name)")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("logs")
    p.add_argument("name")
    p.add_argument("-r", "--replica", type=int, default=0)
    p.add_argument("--stderr", action="store_true")
    p.add_argument("-f", "--follow", action="store_true")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("delete")
    p.add_argument("kind")
    p.add_argument("name")
    p.set_defaults(fn=cmd_delete)

    p = sub.add_parser("compile", help="compile a @pipeline file to IR JSON")
    p.add_argument("file")
    p.add_argument("-o", "--output")
    p.add_argument("--pipeline", help="pipeline name if the file has several")
    p.set_defaults(fn=cmd_compile)

    p = sub.add_parser("slices")
    p.set_defaults(fn=cmd_slices)

    p = sub.add_parser("metrics")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("stateinfo",
                       help="WAL/snapshot durability + replication "
                            "health")
    p.add_argument("--json", action="store_true",
                   help="full stateinfo document (scriptable)")
    p.set_defaults(fn=cmd_stateinfo)

    p = sub.add_parser("events",
                       help="per-job event history (WAL-persisted)")
    p.add_argument("name")
    p.add_argument("--kind", default="JAXJob")
    p.add_argument("--json", action="store_true",
                   help="raw JSON (events + conditions)")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("replicas",
                       help="serving-fleet table from the front-door "
                            "router's admin endpoint")
    p.add_argument("--router", default="http://127.0.0.1:8090",
                   help="router base URL (tpk-router --port)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_replicas)

    p = sub.add_parser("trace",
                       help="control-plane spans as Chrome trace JSON; "
                            "with --router URL TRACE_ID, the router's "
                            "assembled distributed trace")
    p.add_argument("trace_id", nargs="?", default=None,
                   help="request id to assemble (with --router)")
    p.add_argument("--router", default=None,
                   help="router base URL — assemble the distributed "
                        "trace for TRACE_ID instead of dumping the "
                        "control-plane ring")
    p.add_argument("-o", "--output")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("requests",
                       help="router flight recorder: last-K per-request "
                            "outcome records (trail, resumes, TTFT/e2e)")
    p.add_argument("--router", default="http://127.0.0.1:8090",
                   help="router base URL (tpk-router --port)")
    p.add_argument("-n", type=int, default=0,
                   help="only the last N records (0 = all retained)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_requests)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except Exception as e:  # CLI boundary: render errors, not tracebacks
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
