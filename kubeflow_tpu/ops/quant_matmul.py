"""W8A8 Pallas matmul — int8×int8→int32 on the MXU with fused rescale.

The serving quantization path (serve/quant.py) is weight-only: int8
weights are dequantized on read, so it halves HBM traffic but still pays
bf16 MXU throughput. This kernel takes the next step (ops/ROADMAP.md):
activations are quantized per row-block INSIDE the kernel (dynamic
symmetric max-abs — the standard W8A8 recipe), the matmul runs
int8×int8→int32 on the MXU at double the bf16 rate, and the per-row ×
per-channel rescale fuses into the epilogue. Nothing int8 ever round-trips
through HBM in float.

    y[m, n] ≈ (Σ_k qx[m, k]·qw[k, n]) · sx[m] · sw[n]

Accuracy: per-row activation scales keep the quantization error at the
int8 noise floor (~0.5% RMS per operand); suited to serving, not to
gradient paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401


def _qmm_kernel(qx_ref, sx_ref, qw_ref, sw_ref, o_ref):
    """qx [bm, K] int8; sx [bm, 1] f32; qw [K, bn] int8; sw [1, bn] f32.
    One program per (M-block, N-block); both operands fit VMEM at int8
    (the grid bounds bm/bn; K rides whole — 1 MB per 4k×256 int8 tile),
    so the contraction is a single int8×int8→int32 MXU dot with the
    per-row × per-channel rescale fused into the epilogue. Activation
    quantization happens OUTSIDE (once per row — inside the kernel it
    would be redundantly recomputed for every N block)."""
    acc = jax.lax.dot_general(
        qx_ref[...], qw_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    o_ref[...] = (acc.astype(jnp.float32) * sx_ref[...] *
                  sw_ref[0][None, :]).astype(o_ref.dtype)


def int8_matmul(x: jax.Array, qw: jax.Array, sw: jax.Array,
                *, block_m: int = 256, block_n: int = 256,
                out_dtype=jnp.float32,
                interpret: bool | None = None) -> jax.Array:
    """x [M, K] float; qw [K, N] int8; sw [N] f32 per-channel scales.
    Returns x @ (qw·sw) computed as an int8×int8→int32 MXU matmul with
    in-kernel dynamic activation quantization. M, K, N are padded to the
    block grid internally."""
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    m, k = x.shape
    k2, n = qw.shape
    if k != k2 or sw.shape != (n,):
        raise ValueError(f"shape mismatch: x{x.shape} qw{qw.shape} "
                         f"sw{sw.shape}")
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    # K pads to the int8 lane tile (zeros contribute nothing to the dot;
    # they cannot raise the row abs-max either).
    pad_m, pad_n, pad_k = (-m) % block_m, (-n) % block_n, (-k) % 128
    if pad_m or pad_k:
        x = jnp.pad(x, ((0, pad_m), (0, pad_k)))
    if pad_n or pad_k:
        qw = jnp.pad(qw, ((0, pad_k), (0, pad_n)))
    if pad_n:
        sw = jnp.pad(sw, (0, pad_n))
    mp, kp = x.shape
    np_ = qw.shape[1]

    # Per-row symmetric activation quantization, once (XLA fuses this
    # into a single pass over x).
    x32 = x.astype(jnp.float32)
    sx = jnp.maximum(jnp.max(jnp.abs(x32), axis=1, keepdims=True),
                     1e-12) / 127.0
    qx = jnp.clip(jnp.round(x32 / sx), -127, 127).astype(jnp.int8)

    out = pl.pallas_call(
        _qmm_kernel,
        grid=(mp // block_m, np_ // block_n),
        in_specs=[
            pl.BlockSpec((block_m, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((block_m, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        interpret=interpret,
    )(qx, sx, qw, sw[None, :])
    return out[:m, :n]


# Measured on the axon-emulated v5e (2026-07-30, 4096^3): this kernel
# reaches ~4.7 TF/s-equiv vs ~27-40 TF/s for XLA's bf16 matmul — 0.17x.
# Isolation probes show ALL Mosaic matmuls (bf16 included) run far below
# XLA's native matmul on this target, so a bare-matmul kernel cannot win
# here regardless of dtype; the flash kernels win because XLA has no
# fused-attention alternative. Keep serving on the weight-only path
# (serve/quant.py) on this hardware; this op is for targets whose Mosaic
# int8 dots hit the MXU at double rate.
