"""Reference (einsum) attention — the numerics golden for every fused path.

Single source of truth for GQA softmax attention: models call it as the
portable fallback, flash_attention's VJP differentiates through it, and the
kernel tests compare against it. O(S·T) score materialization — correct at
any size, only efficient at small ones.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def naive_attention(q, k, v, *, causal: bool = True,
                    positions_q=None, positions_kv=None,
                    segment_ids=None, segment_ids_kv=None,
                    mask=None, softcap: float = 0.0,
                    windowed=None, k_scale=None, v_scale=None) -> jax.Array:
    """q: [B,S,H,D]; k,v: [B,T,KH,D] with H % KH == 0; fp32 softmax.
    Causality is masked by absolute positions when given (packed/offset
    sequences), else by array index. `segment_ids` [B,S] (and optionally a
    separate kv set) additionally confine attention within equal-id spans
    — the packed-sequence mask. `mask` (a flash_attention.MaskSpec)
    selects causal/full/prefix_lm/sliding_window, overriding `causal`.

    `softcap` > 0 applies Gemma-2's attention-logit soft-cap
    tanh(s/cap)*cap after scaling, before masking. `windowed` (traced
    scalar bool, Gemma-2's alternating layers) gates a sliding_window
    mask's band per call: where False the mask degrades to plain causal
    — dynamic, so one scanned trunk serves both layer types.

    `k_scale`/`v_scale` [B,T,KH] f32 are per-row dequant scales for a
    QUANTIZED cache (serve/quant.py KV helpers): k/v arrive as the raw
    quantized values through a bare convert, and the scales land on the
    score/prob tensors — `scores * k_scale` after Q·Kᵀ, `probs *
    v_scale` before probs·V (the scale varies along the contraction
    axis, so pre-contraction on probs is the output-side placement). No
    cache-width `[..., T, KH, D]` multiply ever exists; the HLO guard
    in tests/test_kv_quant.py pins this."""
    if (mask is not None and mask.kind == "prefix_lm"
            and segment_ids is not None):
        # Same refusal as flash_attention: a global prefix boundary is
        # ill-defined over packed documents whose positions restart per
        # segment — accepting it here would let attention_impl='naive'
        # run semantics the fused path deliberately rejects.
        raise ValueError(
            "prefix_lm mask is incompatible with packed segment_ids: "
            "the prefix boundary is global but packed positions restart "
            "per document")
    b, s, h, d = q.shape
    t, kh = k.shape[1], k.shape[2]
    group = h // kh
    qg = q.reshape(b, s, kh, group, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32)
    if k_scale is not None:
        scores = scores * k_scale.transpose(0, 2, 1)[:, :, None, None, :]
    scores = scores / jnp.sqrt(d).astype(jnp.float32)
    if softcap:
        scores = jnp.tanh(scores / softcap) * softcap
    if mask is not None:
        pq = positions_q if positions_q is not None else jnp.arange(s)[None]
        pk = positions_kv if positions_kv is not None else jnp.arange(t)[None]
        rows = pq[:, None, None, :, None]
        cols = pk[:, None, None, None, :]
        if mask.kind == "causal":
            m = rows >= cols
        elif mask.kind == "prefix_lm":
            m = (rows >= cols) | (cols < mask.prefix)
        elif mask.kind == "sliding_window":
            band = rows - cols < mask.window
            if windowed is not None:
                band = band | jnp.logical_not(windowed)
            m = (rows >= cols) & band
        else:  # full
            m = None
        if m is not None:
            scores = jnp.where(m, scores, -1e30)
    elif causal:
        pq = positions_q if positions_q is not None else jnp.arange(s)[None]
        pk = positions_kv if positions_kv is not None else jnp.arange(t)[None]
        mask = pq[:, None, None, :, None] >= pk[:, None, None, None, :]
        scores = jnp.where(mask, scores, -1e30)
    if segment_ids is not None:
        sk = segment_ids_kv if segment_ids_kv is not None else segment_ids
        seg = (segment_ids[:, None, None, :, None]
               == sk[:, None, None, None, :])
        scores = jnp.where(seg, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        probs = probs * v_scale.transpose(0, 2, 1)[:, :, None, None, :]
    probs = probs.astype(q.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)
