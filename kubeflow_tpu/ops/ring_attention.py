"""Ring attention: causal attention over a sequence-sharded mesh axis.

First-class sequence/context parallelism (SURVEY.md §5.7) — the capability
the reference could only *host* (DeepSpeed-Ulysses / Megatron-CP ran inside
user containers; the platform just provided pods + NCCL env). Here it is an
op: K/V shards rotate around the `seq` mesh-axis ring via
`jax.lax.ppermute` while each device accumulates online-softmax partial
results for its resident Q shard, so peak memory is O(S/n) per device and
the permute overlaps with the block compute under XLA's async collectives.

Works under `jit` by nesting a `shard_map` over the seq axis; differentiable
(each ring step is rematerialized). The all-to-all "Ulysses" alternative is
`ulysses_attention` below: resharding seq↔heads around a local attention so
existing per-head kernels apply — preferable when heads ≥ ring size and
context is moderate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from kubeflow_tpu.parallel.mesh import current_mesh

NEG_INF = -1e30


def _block_attn(q, k, v, q_pos, kv_pos, q_seg=None, kv_seg=None):
    """One blockwise attention contribution with causal masking by absolute
    positions. q [b,s,h,d] (local shard), k/v [b,t,kh,d]. Returns fp32
    (acc [b,s,h,d], m [b,s,h,1], l [b,s,h,1]) partials. `q_seg`/`kv_seg`
    [b,s]/[b,t] additionally confine attention within equal-id spans (the
    packed-sequence mask, matching ops/reference.py semantics)."""
    b, s, h, d = q.shape
    kh = k.shape[2]
    group = h // kh
    qg = q.reshape(b, s, kh, group, d).astype(jnp.float32)
    scores = jnp.einsum("bskgd,btkd->bskgt", qg, k.astype(jnp.float32))
    scores = scores / (d ** 0.5)
    mask = q_pos[:, :, None, None, None] >= kv_pos[:, None, None, None, :]
    if q_seg is not None:
        mask &= (q_seg[:, :, None, None, None]
                 == kv_seg[:, None, None, None, :])
    scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)  # [b,s,kh,g,1]
    # Rows with no visible keys: exp(NEG_INF - NEG_INF) would be 1; zero them
    # via l and guard m so downstream exp() stays finite.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(scores - m_safe) * (m > NEG_INF / 2)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bskgt,btkd->bskgd", p, v.astype(jnp.float32))
    return (acc.reshape(b, s, h, d), m_safe.reshape(b, s, h, 1),
            l.reshape(b, s, h, 1))


def _batch_spec(mesh, axis_name):
    """Shard batch over whichever dp-like axes the mesh actually has
    (never the ring axis itself) — a dedicated single-axis ring mesh
    (kernel tests, standalone CP) leaves batch replicated."""
    axes = tuple(a for a in ("data", "fsdp")
                 if a in mesh.axis_names and a != axis_name)
    return axes or None


def _merge(carry, update):
    """Merge two online-softmax partials."""
    acc, m, l = carry
    acc_u, m_u, l_u = update
    m_new = jnp.maximum(m, m_u)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m_u - m_new)
    return acc * a1 + acc_u * a2, m_new, l * a1 + l_u * a2


def _rotate_if(more, operand, axis_name, n):
    """ppermute `operand` one step around the ring when `more` (skipped on
    the final step, whose rotation would be discarded)."""
    def rotate(o):
        perm = [(j, (j + 1) % n) for j in range(n)]
        return jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), o)

    return jax.lax.cond(more, rotate, lambda o: o, operand)


def _merge_lse(carry, update):
    """Merge two NORMALIZED partials (o, lse): o fp32 [b,s,h,d], lse fp32
    [b,s,h,1]. o·exp(lse) recovers the unnormalized accumulator, so the
    stable combine is a weighted average with weights exp(lse - max)."""
    o, lse = carry
    o_u, lse_u = update
    m = jnp.maximum(jnp.maximum(lse, lse_u), NEG_INF / 2)
    w1 = jnp.exp(lse - m)
    w2 = jnp.exp(lse_u - m)
    denom = jnp.maximum(w1 + w2, 1e-30)
    return (o * w1 + o_u * w2) / denom, m + jnp.log(denom)


def _flash_case_block(q, k, v, case, block_q, block_kv):
    """Fused inner block for ring schedules. `case` (traced int32): 0 = the
    causal mask kills the whole block (skip — zero partials), 1 = diagonal
    block (aligned causal flash), 2 = fully visible (non-causal flash).
    Returns fp32 (o, lse). Offset-ordered layouts (contiguous ring shards,
    zigzag chunks) make every block one of these three cases, so the fused
    kernel needs no position-aware masking."""
    from kubeflow_tpu.ops.flash_attention import flash_attention_lse

    b, s, h, d = q.shape

    def skip(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, s, h, 1), NEG_INF, jnp.float32))

    def diag(_):
        o, l = flash_attention_lse(q, k, v, True, block_q, block_kv)
        return o.astype(jnp.float32), l

    def full(_):
        o, l = flash_attention_lse(q, k, v, False, block_q, block_kv)
        return o.astype(jnp.float32), l

    return jax.lax.switch(case, (skip, diag, full), None)


def ring_attention_manual(q, k, v, pos, axis_name: str, n: int,
                          segment_ids=None) -> jax.Array:
    """Einsum-inner causal ring body for callers ALREADY inside a manual
    (`shard_map`) region whose mesh includes `axis_name` — context
    parallelism composed inside another manually-partitioned schedule, e.g.
    the pipeline stage region (models/llama_pp.py, CP-inside-PP).

    All shapes are per-shard: q [b_loc, s_loc, H, D], k/v [b_loc, s_loc,
    KH, D], pos [b_loc, s_loc] GLOBAL positions of the resident shard
    (causality is masked by absolute position, so any contiguous or
    permuted layout works). `segment_ids` [b_loc, s_loc] (packed
    documents) rotate around the ring with K/V so every step masks
    within-document exactly. Differentiable (each ring step
    rematerializes)."""
    h, d = q.shape[2], q.shape[3]
    packed = segment_ids is not None

    def step(i, carry):
        acc_m_l, kv, kv_pos, kv_seg = carry
        k_i, v_i = kv
        update = _block_attn(q, k_i, v_i, pos, kv_pos,
                             segment_ids if packed else None, kv_seg)
        acc_m_l = _merge(acc_m_l, update)
        kv, kv_pos, kv_seg = _rotate_if(
            i < n - 1, (kv, kv_pos, kv_seg), axis_name, n)
        return acc_m_l, kv, kv_pos, kv_seg

    b_loc, s_loc = q.shape[0], q.shape[1]
    init = (jnp.zeros((b_loc, s_loc, h, d), jnp.float32),
            jnp.full((b_loc, s_loc, h, 1), NEG_INF, jnp.float32),
            jnp.zeros((b_loc, s_loc, h, 1), jnp.float32))
    # None is a leaf-less pytree node: unpacked callers carry (and
    # ppermute) nothing extra.
    (acc, _, l), _, _, _ = jax.lax.fori_loop(
        0, n, jax.checkpoint(step), (init, (k, v), pos, segment_ids))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_flash_manual(q, k, v, axis_name: str, n: int,
                                block_q: int = 512,
                                block_kv: int = 512) -> jax.Array:
    """Fused-inner contiguous-layout ring body for manual-region callers
    (see ring_attention_manual). Requires the CONTIGUOUS layout — shard r
    of the ring owns global positions [r*s_loc, (r+1)*s_loc) — because
    causality is derived from ring offsets, not positions."""
    me = jax.lax.axis_index(axis_name)
    b_loc, s_loc, h, d = q.shape

    def step(i, carry):
        (o, lse), kv = carry
        k_i, v_i = kv
        src = jnp.mod(me - i, n)  # origin shard of the resident KV
        case = jnp.where(src == me, 1,
                         jnp.where(src < me, 2, 0)).astype(jnp.int32)
        update = _flash_case_block(q, k_i, v_i, case, block_q, block_kv)
        o, lse = _merge_lse((o, lse), update)
        kv = _rotate_if(i < n - 1, kv, axis_name, n)
        return (o, lse), kv

    init = (jnp.zeros((b_loc, s_loc, h, d), jnp.float32),
            jnp.full((b_loc, s_loc, h, 1), NEG_INF, jnp.float32))
    (o, _), _ = jax.lax.fori_loop(
        0, n, jax.checkpoint(step), (init, (k, v)))
    return o.astype(q.dtype)


def ring_attention(q, k, v, axis_name: str = "seq",
                   positions: jax.Array | None = None,
                   mesh=None, inner: str = "einsum",
                   block_q: int = 512, block_kv: int = 512) -> jax.Array:
    """Causal ring attention. q [B,S,H,D], k/v [B,S,KH,D] — S is the GLOBAL
    sequence; arrays may be traced under jit with any sharding, the inner
    shard_map forces P(axis_name) on dim 1. `positions` defaults to
    arange(S) broadcast over batch (standard packing comes later).

    inner="flash" runs the fused Pallas kernel per ring step (ops/ROADMAP
    item: no O(s_loc·t_loc) score materialization): with the contiguous
    layout each incoming KV shard is entirely before/at/after the resident
    Q shard, so the step is a skip / causal / full flash call selected by
    ring offset. Requires default positions (the layout IS the mask)."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ring_attention needs a mesh (with mesh: ...)")
    n = mesh.shape[axis_name]
    b, s, h, d = q.shape
    if inner == "flash":
        if positions is not None:
            raise ValueError(
                "inner='flash' derives causality from the contiguous ring "
                "layout; custom positions need inner='einsum'")
        return _ring_attention_flash(q, k, v, axis_name, mesh, n,
                                     block_q, block_kv)
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None],
                                     (b, s))
    if n == 1:
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=True, positions_q=positions,
                               positions_kv=positions)

    # Batch stays sharded over the dp-like axes — replicating it here would
    # all-gather the global batch onto every seq-ring member.
    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)
    pos_spec = P(_batch_spec(mesh, axis_name), axis_name)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, pos_spec),
        out_specs=spec, check_vma=False)
    def _ring(q, k, v, pos):
        # All shapes here are per-shard: s_loc = S / n, b_loc = B / dp.
        return ring_attention_manual(q, k, v, pos, axis_name, n)

    return _ring(q, k, v, positions)


def _ring_attention_flash(q, k, v, axis_name, mesh, n, block_q, block_kv):
    """Contiguous-layout ring with the fused flash inner block. Shard r of
    the ring owns positions [r·s_loc, (r+1)·s_loc); after i rotations the
    resident KV originates from shard (me - i) mod n, so the whole step is
    before/at/after the Q shard — see _flash_case_block."""
    if n == 1:
        from kubeflow_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, True, block_q, block_kv)

    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _ring(q, k, v):
        return ring_attention_flash_manual(q, k, v, axis_name, n,
                                           block_q, block_kv)

    return _ring(q, k, v)


def zigzag_indices(s: int, n: int) -> jax.Array:
    """Zigzag sequence layout for a ring of n devices (SURVEY.md §5.7
    "causal load-balance"): the sequence splits into 2n chunks and shard i
    holds chunks (i, 2n-1-i) — one early, one late — so every ring member
    owns the same amount of causally-visible work: sum over its chunks of
    (chunk_id+1) = (i+1) + (2n-i) = 2n+1, constant in i. Contiguous
    layout instead gives member i work ∝ i+1: the last member does n× the
    first's, and under lockstep SPMD the ring runs at the slowest
    member's pace.

    Returns the permutation `idx` such that `x[:, idx]` is zigzag-ordered;
    invert with jnp.argsort(idx)."""
    if s % (2 * n):
        raise ValueError(f"seq len {s} must divide 2*ring ({2 * n})")
    c = s // (2 * n)
    chunks = jnp.arange(s, dtype=jnp.int32).reshape(2 * n, c)
    order = []
    for i in range(n):
        order += [i, 2 * n - 1 - i]
    return chunks[jnp.asarray(order)].reshape(-1)


def _maybe_block_attn(q, k, v, q_pos, kv_pos):
    """_block_attn, skipped entirely (zero partials) when the causal mask
    kills the whole block — the predicate comes from absolute positions, so
    skipping can never change numerics, only save the dense FLOPs."""
    b, s, h, d = q.shape

    def compute(_):
        return _block_attn(q, k, v, q_pos, kv_pos)

    def skip(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, s, h, 1), NEG_INF, jnp.float32),
                jnp.zeros((b, s, h, 1), jnp.float32))

    visible = jnp.max(q_pos) >= jnp.min(kv_pos)
    return jax.lax.cond(visible, compute, skip, None)


def zigzag_ring_attention(q, k, v, axis_name: str = "seq", mesh=None,
                          pre_permuted: bool = False,
                          inner: str = "einsum",
                          block_q: int = 512,
                          block_kv: int = 512) -> jax.Array:
    """Causal ring attention with the zigzag layout. Inputs/outputs are in
    NORMAL sequence order unless `pre_permuted` (the efficient path: lay
    the batch out with zigzag_indices in the input pipeline and skip the
    runtime gather). Each ring step splits the resident Q and incoming KV
    into their two chunks and computes only the causally-visible
    sub-blocks — ~2× less dense work at the lockstep pace vs the
    contiguous schedule.

    inner="flash": zigzag chunks are contiguous position ranges, so every
    (q chunk, kv chunk) sub-block is skip / aligned-causal / full — the
    fused Pallas kernel serves all of them (_flash_case_block)."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("zigzag_ring_attention needs a mesh")
    n = mesh.shape[axis_name]
    b, s, h, d = q.shape
    if n == 1:
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=True)

    idx = zigzag_indices(s, n)
    if not pre_permuted:
        q, k, v = (x[:, idx] for x in (q, k, v))
    if inner == "flash":
        out = _zigzag_ring_flash(q, k, v, axis_name, mesh, n,
                                 block_q, block_kv)
        return out if pre_permuted else out[:, jnp.argsort(idx)]
    positions = jnp.broadcast_to(idx[None].astype(jnp.int32), (b, s))

    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)
    pos_spec = P(_batch_spec(mesh, axis_name), axis_name)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, pos_spec),
        out_specs=spec, check_vma=False)
    def _ring(q, k, v, pos):
        b_loc, s_loc = q.shape[0], q.shape[1]
        half = s_loc // 2  # chunk boundary inside the zigzag shard

        def split(x):
            return x[:, :half], x[:, half:]

        q_lo, q_hi = split(q)
        p_lo, p_hi = split(pos)

        def step(i, carry):
            (lo_part, hi_part), kv, kv_pos = carry
            k_i, v_i = kv
            k_lo, k_hi = split(k_i)
            v_lo, v_hi = split(v_i)
            kp_lo, kp_hi = split(kv_pos)
            # 4 sub-blocks; fully-masked ones cost ~nothing (lax.cond).
            for kk, vv, kp in ((k_lo, v_lo, kp_lo), (k_hi, v_hi, kp_hi)):
                lo_part = _merge(lo_part,
                                 _maybe_block_attn(q_lo, kk, vv, p_lo, kp))
                hi_part = _merge(hi_part,
                                 _maybe_block_attn(q_hi, kk, vv, p_hi, kp))

            kv, kv_pos = _rotate_if(i < n - 1, (kv, kv_pos), axis_name, n)
            return (lo_part, hi_part), kv, kv_pos

        def zero_part(width):
            return (jnp.zeros((b_loc, width, h, d), jnp.float32),
                    jnp.full((b_loc, width, h, 1), NEG_INF, jnp.float32),
                    jnp.zeros((b_loc, width, h, 1), jnp.float32))

        init = (zero_part(half), zero_part(s_loc - half))
        (lo, hi), _, _ = jax.lax.fori_loop(
            0, n, jax.checkpoint(step), (init, (k, v), pos))

        def finish(part):
            acc, _, l = part
            return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

        return jnp.concatenate([finish(lo), finish(hi)], axis=1)

    out = _ring(q, k, v, positions)
    if pre_permuted:
        return out
    return out[:, jnp.argsort(idx)]


def _zigzag_ring_flash(q, k, v, axis_name, mesh, n, block_q, block_kv):
    """Zigzag schedule with the fused flash inner block. Shard i holds
    chunks (i, 2n-1-i); chunk c covers positions [c·cs, (c+1)·cs), so
    chunk-id comparison decides each sub-block's case."""
    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _ring(q, k, v):
        me = jax.lax.axis_index(axis_name)
        b_loc, s_loc, h, d = q.shape
        half = s_loc // 2

        def split(x):
            return x[:, :half], x[:, half:]

        q_lo, q_hi = split(q)
        qc_lo, qc_hi = me, 2 * n - 1 - me  # chunk ids of the two q halves

        def case(qc, kc):
            return jnp.where(qc == kc, 1,
                             jnp.where(qc > kc, 2, 0)).astype(jnp.int32)

        def step(i, carry):
            (lo, hi), kv = carry
            k_i, v_i = kv
            src = jnp.mod(me - i, n)
            k_lo, k_hi = split(k_i)
            v_lo, v_hi = split(v_i)
            for kk, vv, kc in ((k_lo, v_lo, src), (k_hi, v_hi, 2 * n - 1 - src)):
                lo = _merge_lse(lo, _flash_case_block(
                    q_lo, kk, vv, case(qc_lo, kc), block_q, block_kv))
                hi = _merge_lse(hi, _flash_case_block(
                    q_hi, kk, vv, case(qc_hi, kc), block_q, block_kv))

            kv = _rotate_if(i < n - 1, kv, axis_name, n)
            return (lo, hi), kv

        def zero_part(width):
            return (jnp.zeros((b_loc, width, h, d), jnp.float32),
                    jnp.full((b_loc, width, h, 1), NEG_INF, jnp.float32))

        init = (zero_part(half), zero_part(s_loc - half))
        ((o_lo, _), (o_hi, _)), _ = jax.lax.fori_loop(
            0, n, jax.checkpoint(step), (init, (k, v)))
        return jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)

    return _ring(q, k, v)


def ulysses_attention(q, k, v, axis_name: str = "seq",
                      mesh=None) -> jax.Array:
    """DeepSpeed-Ulysses-style context parallelism: all_to_all seq↔heads so
    each device holds full sequence for H/n heads, runs local (flash)
    attention, then all_to_all back. Requires H % n == 0 and KH % n == 0."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ulysses_attention needs a mesh")
    n = mesh.shape[axis_name]
    if n == 1:
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=True)

    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _ulysses(q, k, v):
        # [b, s/n, h, d] -> all_to_all -> [b, s, h/n, d]
        def scatter_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        def gather_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        # Forward AND backward run the fused Pallas kernels (O(S) memory;
        # flash_attention's custom VJP is the two-pass dq/dkv recipe).
        from kubeflow_tpu.ops.flash_attention import flash_attention
        out = flash_attention(ql, kl, vl, True)
        return gather_heads(out)

    return _ulysses(q, k, v)
