"""Ring attention: causal attention over a sequence-sharded mesh axis.

First-class sequence/context parallelism (SURVEY.md §5.7) — the capability
the reference could only *host* (DeepSpeed-Ulysses / Megatron-CP ran inside
user containers; the platform just provided pods + NCCL env). Here it is an
op: K/V shards rotate around the `seq` mesh-axis ring via
`jax.lax.ppermute` while each device accumulates online-softmax partial
results for its resident Q shard, so peak memory is O(S/n) per device and
the permute overlaps with the block compute under XLA's async collectives.

Works under `jit` by nesting a `shard_map` over the seq axis; differentiable
(each ring step is rematerialized). The all-to-all "Ulysses" alternative is
`ulysses_attention` below: resharding seq↔heads around a local attention so
existing per-head kernels apply — preferable when heads ≥ ring size and
context is moderate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from kubeflow_tpu.parallel.mesh import current_mesh

NEG_INF = -1e30


def _block_attn(q, k, v, q_pos, kv_pos):
    """One blockwise attention contribution with causal masking by absolute
    positions. q [b,s,h,d] (local shard), k/v [b,t,kh,d]. Returns fp32
    (acc [b,s,h,d], m [b,s,h,1], l [b,s,h,1]) partials."""
    b, s, h, d = q.shape
    kh = k.shape[2]
    group = h // kh
    qg = q.reshape(b, s, kh, group, d).astype(jnp.float32)
    scores = jnp.einsum("bskgd,btkd->bskgt", qg, k.astype(jnp.float32))
    scores = scores / (d ** 0.5)
    mask = q_pos[:, :, None, None, None] >= kv_pos[:, None, None, None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)  # [b,s,kh,g,1]
    # Rows with no visible keys: exp(NEG_INF - NEG_INF) would be 1; zero them
    # via l and guard m so downstream exp() stays finite.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(scores - m_safe) * (m > NEG_INF / 2)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bskgt,btkd->bskgd", p, v.astype(jnp.float32))
    return (acc.reshape(b, s, h, d), m_safe.reshape(b, s, h, 1),
            l.reshape(b, s, h, 1))


def _merge(carry, update):
    """Merge two online-softmax partials."""
    acc, m, l = carry
    acc_u, m_u, l_u = update
    m_new = jnp.maximum(m, m_u)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m_u - m_new)
    return acc * a1 + acc_u * a2, m_new, l * a1 + l_u * a2


def ring_attention(q, k, v, axis_name: str = "seq",
                   positions: jax.Array | None = None,
                   mesh=None) -> jax.Array:
    """Causal ring attention. q [B,S,H,D], k/v [B,S,KH,D] — S is the GLOBAL
    sequence; arrays may be traced under jit with any sharding, the inner
    shard_map forces P(axis_name) on dim 1. `positions` defaults to
    arange(S) broadcast over batch (standard packing comes later)."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ring_attention needs a mesh (with mesh: ...)")
    n = mesh.shape[axis_name]
    b, s, h, d = q.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None],
                                     (b, s))
    if n == 1:
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=True, positions_q=positions,
                               positions_kv=positions)

    # Batch stays sharded over the dp-like axes — replicating it here would
    # all-gather the global batch onto every seq-ring member.
    spec = P(("data", "fsdp"), axis_name, None, None)
    pos_spec = P(("data", "fsdp"), axis_name)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, pos_spec),
        out_specs=spec, check_vma=False)
    def _ring(q, k, v, pos):
        # All shapes here are per-shard: s_loc = S / n, b_loc = B / dp.
        def step(i, carry):
            acc_m_l, kv, kv_pos = carry
            k_i, v_i = kv
            update = _block_attn(q, k_i, v_i, pos, kv_pos)
            acc_m_l = _merge(acc_m_l, update)

            # Rotate K/V (and their positions) to the next ring neighbour —
            # skipped on the final step, whose rotation would be discarded.
            def rotate(operand):
                perm = [(j, (j + 1) % n) for j in range(n)]
                return jax.tree.map(
                    lambda x: jax.lax.ppermute(x, axis_name, perm), operand)

            kv, kv_pos = jax.lax.cond(
                i < n - 1, rotate, lambda o: o, (kv, kv_pos))
            return acc_m_l, kv, kv_pos

        b_loc, s_loc = q.shape[0], q.shape[1]
        init = (jnp.zeros((b_loc, s_loc, h, d), jnp.float32),
                jnp.full((b_loc, s_loc, h, 1), NEG_INF, jnp.float32),
                jnp.zeros((b_loc, s_loc, h, 1), jnp.float32))
        (acc, m, l), _, _ = jax.lax.fori_loop(
            0, n, jax.checkpoint(step), (init, (k, v), pos))
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    return _ring(q, k, v, positions)


def zigzag_indices(s: int, n: int) -> jax.Array:
    """Zigzag sequence layout for a ring of n devices (SURVEY.md §5.7
    "causal load-balance"): the sequence splits into 2n chunks and shard i
    holds chunks (i, 2n-1-i) — one early, one late — so every ring member
    owns the same amount of causally-visible work: sum over its chunks of
    (chunk_id+1) = (i+1) + (2n-i) = 2n+1, constant in i. Contiguous
    layout instead gives member i work ∝ i+1: the last member does n× the
    first's, and under lockstep SPMD the ring runs at the slowest
    member's pace.

    Returns the permutation `idx` such that `x[:, idx]` is zigzag-ordered;
    invert with jnp.argsort(idx)."""
    if s % (2 * n):
        raise ValueError(f"seq len {s} must divide 2*ring ({2 * n})")
    c = s // (2 * n)
    chunks = jnp.arange(s, dtype=jnp.int32).reshape(2 * n, c)
    order = []
    for i in range(n):
        order += [i, 2 * n - 1 - i]
    return chunks[jnp.asarray(order)].reshape(-1)


def _maybe_block_attn(q, k, v, q_pos, kv_pos):
    """_block_attn, skipped entirely (zero partials) when the causal mask
    kills the whole block — the predicate comes from absolute positions, so
    skipping can never change numerics, only save the dense FLOPs."""
    b, s, h, d = q.shape

    def compute(_):
        return _block_attn(q, k, v, q_pos, kv_pos)

    def skip(_):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, s, h, 1), NEG_INF, jnp.float32),
                jnp.zeros((b, s, h, 1), jnp.float32))

    visible = jnp.max(q_pos) >= jnp.min(kv_pos)
    return jax.lax.cond(visible, compute, skip, None)


def zigzag_ring_attention(q, k, v, axis_name: str = "seq", mesh=None,
                          pre_permuted: bool = False) -> jax.Array:
    """Causal ring attention with the zigzag layout. Inputs/outputs are in
    NORMAL sequence order unless `pre_permuted` (the efficient path: lay
    the batch out with zigzag_indices in the input pipeline and skip the
    runtime gather). Each ring step splits the resident Q and incoming KV
    into their two chunks and computes only the causally-visible
    sub-blocks — ~2× less dense work at the lockstep pace vs the
    contiguous schedule."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("zigzag_ring_attention needs a mesh")
    n = mesh.shape[axis_name]
    b, s, h, d = q.shape
    if n == 1:
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=True)

    idx = zigzag_indices(s, n)
    if not pre_permuted:
        q, k, v = (x[:, idx] for x in (q, k, v))
    positions = jnp.broadcast_to(idx[None].astype(jnp.int32), (b, s))

    spec = P(("data", "fsdp"), axis_name, None, None)
    pos_spec = P(("data", "fsdp"), axis_name)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, pos_spec),
        out_specs=spec, check_vma=False)
    def _ring(q, k, v, pos):
        b_loc, s_loc = q.shape[0], q.shape[1]
        half = s_loc // 2  # chunk boundary inside the zigzag shard

        def split(x):
            return x[:, :half], x[:, half:]

        q_lo, q_hi = split(q)
        p_lo, p_hi = split(pos)

        def step(i, carry):
            (lo_part, hi_part), kv, kv_pos = carry
            k_i, v_i = kv
            k_lo, k_hi = split(k_i)
            v_lo, v_hi = split(v_i)
            kp_lo, kp_hi = split(kv_pos)
            # 4 sub-blocks; fully-masked ones cost ~nothing (lax.cond).
            for kk, vv, kp in ((k_lo, v_lo, kp_lo), (k_hi, v_hi, kp_hi)):
                lo_part = _merge(lo_part,
                                 _maybe_block_attn(q_lo, kk, vv, p_lo, kp))
                hi_part = _merge(hi_part,
                                 _maybe_block_attn(q_hi, kk, vv, p_hi, kp))

            def rotate(operand):
                perm = [(j, (j + 1) % n) for j in range(n)]
                return jax.tree.map(
                    lambda x: jax.lax.ppermute(x, axis_name, perm), operand)

            kv, kv_pos = jax.lax.cond(
                i < n - 1, rotate, lambda o: o, (kv, kv_pos))
            return (lo_part, hi_part), kv, kv_pos

        def zero_part(width):
            return (jnp.zeros((b_loc, width, h, d), jnp.float32),
                    jnp.full((b_loc, width, h, 1), NEG_INF, jnp.float32),
                    jnp.zeros((b_loc, width, h, 1), jnp.float32))

        init = (zero_part(half), zero_part(s_loc - half))
        (lo, hi), _, _ = jax.lax.fori_loop(
            0, n, jax.checkpoint(step), (init, (k, v), pos))

        def finish(part):
            acc, _, l = part
            return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

        return jnp.concatenate([finish(lo), finish(hi)], axis=1)

    out = _ring(q, k, v, positions)
    if pre_permuted:
        return out
    return out[:, jnp.argsort(idx)]


def ulysses_attention(q, k, v, axis_name: str = "seq",
                      mesh=None) -> jax.Array:
    """DeepSpeed-Ulysses-style context parallelism: all_to_all seq↔heads so
    each device holds full sequence for H/n heads, runs local (flash)
    attention, then all_to_all back. Requires H % n == 0 and KH % n == 0."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ulysses_attention needs a mesh")
    n = mesh.shape[axis_name]
    if n == 1:
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=True)

    spec = P(("data", "fsdp"), axis_name, None, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _ulysses(q, k, v):
        # [b, s/n, h, d] -> all_to_all -> [b, s, h/n, d]
        def scatter_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        def gather_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        # Forward via the flash kernel: O(S) memory. NOTE the backward still
        # recomputes through the einsum reference (O(S²) scores) until the
        # Pallas backward lands — see ops/ROADMAP.md; prefer ring_attention
        # for training at very long context.
        from kubeflow_tpu.ops.flash_attention import flash_attention
        out = flash_attention(ql, kl, vl, True)
        return gather_heads(out)

    return _ulysses(q, k, v)
