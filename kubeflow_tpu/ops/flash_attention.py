"""Pallas TPU flash attention (forward kernel + recompute VJP).

The hot op of the flagship model. The reference platform has no kernels at
all (GPU attention lived in user containers: flash-attn/vLLM; SURVEY.md
§2.6) — this is the TPU-native equivalent, written against the Pallas TPU
model (/opt/skills/guides/pallas_guide.md): online-softmax blockwise
attention; Q blocks in VMEM stream over K/V blocks; fp32 accumulators;
causal upper blocks skipped entirely (not masked) so the causal speedup is
real wall-clock, not just masking.

Layout: q [B, S, H, D], k/v [B, T, KH, D] with GQA (H % KH == 0). The grid
is (B*H, Q_blocks); each program owns one q block and loops over its visible
kv blocks. K/V stay sequence-complete in VMEM per (batch, head) program —
fine through ~8k tokens at D=128 in bf16; ring attention (ring_attention.py)
is the path past that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int,
                      block_kv: int, seq_kv: int, causal: bool,
                      sm_scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, D]

    num_kv_blocks = pl.cdiv(seq_kv, block_kv)
    if causal:
        # Highest kv block index any row of this q block may see.
        last = (qi + 1) * block_q - 1
        num_visible = jnp.minimum((last // block_kv) + 1, num_kv_blocks)
    else:
        num_visible = num_kv_blocks

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_kv]
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1) + j * block_kv
        # Mask padded keys (inputs are padded up to a block multiple by the
        # wrapper; without this the pad keys would attend in non-causal mode).
        valid = cols < seq_kv
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0) + qi * block_q
            valid = jnp.logical_and(valid, rows >= cols)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_visible, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q3, k3, v3, *, group: int, causal: bool, block_q: int,
               block_kv: int, seq_kv: int, sm_scale: float, interpret: bool):
    """q3 [B*H, S, D]; k3/v3 [B*KH, T, D], padded to block multiples; GQA is
    served zero-copy by the K/V index_map (q program bh reads kv row
    bh // group, since bh = batch*H + qh and H = KH*group). seq_kv is the
    pre-padding key length used for masking."""
    bh, s, d = q3.shape
    t = k3.shape[1]
    grid = (bh, pl.cdiv(s, block_q))
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_kv=block_kv, seq_kv=seq_kv,
        causal=causal, sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b // group, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        interpret=interpret,
    )(q3, k3, v3)


def _flatten_heads(q, k, v):
    """[B,S,H,D] → q3 [B*H, S, D], k3/v3 [B*KH, T, D] — no GQA repetition;
    the kernel's index_map maps q heads onto shared kv heads."""
    b, s, h, d = q.shape
    t, kh = k.shape[1], k.shape[2]
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * kh, t, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * kh, t, d)
    return q3, k3, v3


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_kv: int = 512, interpret: bool | None = None):
    """Flash attention. q [B,S,H,D]; k,v [B,T,KH,D]; returns [B,S,H,D].

    Forward runs the Pallas kernel (O(S) memory); backward recomputes via
    the einsum formulation under jax.checkpoint semantics — correct, and
    memory-bounded by the backward's own S×T blocks. A fused Pallas
    backward is a planned optimization (tracked in ops/ROADMAP.md)."""
    return _attn_reference(q, k, v, causal, block_q, block_kv, interpret)


def _attn_reference(q, k, v, causal, block_q, block_kv, interpret):
    b, s, h, d = q.shape
    t = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    sm_scale = 1.0 / (d ** 0.5)
    kh = k.shape[2]
    if h % kh:
        raise ValueError(f"q heads {h} not a multiple of kv heads {kh}")
    q3, k3, v3 = _flatten_heads(q, k, v)
    # Pad sequences to block multiples: unpadded dynamic slices would clamp
    # at the boundary and silently misalign kv columns. The kernel masks
    # padded keys via its seq_kv bound; padded q rows are sliced off here.
    block_q = min(block_q, max(s, 1))
    block_kv = min(block_kv, max(t, 1))
    s_pad = -s % block_q
    t_pad = -t % block_kv
    if s_pad:
        q3 = jnp.pad(q3, ((0, 0), (0, s_pad), (0, 0)))
    if t_pad:
        k3 = jnp.pad(k3, ((0, 0), (0, t_pad), (0, 0)))
        v3 = jnp.pad(v3, ((0, 0), (0, t_pad), (0, 0)))
    o3 = _flash_fwd(q3, k3, v3, group=h // kh, causal=causal, block_q=block_q,
                    block_kv=block_kv, seq_kv=t, sm_scale=sm_scale,
                    interpret=interpret)
    o3 = o3[:, :s]
    return o3.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd_rule(q, k, v, causal, block_q, block_kv, interpret):
    out = _attn_reference(q, k, v, causal, block_q, block_kv, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_kv, interpret, res, g):
    q, k, v = res

    def ref(q, k, v):
        from kubeflow_tpu.ops.reference import naive_attention
        return naive_attention(q, k, v, causal=causal)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
