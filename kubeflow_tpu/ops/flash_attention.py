"""Pallas TPU flash attention (fused forward + fused two-pass backward).

The hot op of the flagship model. The reference platform has no kernels at
all (GPU attention lived in user containers: flash-attn/vLLM; SURVEY.md
§2.6) — this is the TPU-native equivalent, written against the Pallas TPU
model (/opt/skills/guides/pallas_guide.md): online-softmax blockwise
attention; Q blocks in VMEM stream over K/V blocks; fp32 accumulators;
causal upper blocks skipped entirely (not masked) so the causal speedup is
real wall-clock, not just masking.

Backward is the standard two-pass flash recipe with saved row stats:
the forward additionally writes LSE (logsumexp per q row); the backward
precomputes delta = rowsum(dO·O), then
  * a dq kernel over (batch·head, q blocks) streaming visible kv blocks,
  * a dk/dv kernel over (batch·kv-head, kv blocks) streaming the visible q
    blocks of every q head in the GQA group (zero-copy: the grouped q/dO
    views are reshapes, never materialized per-head copies).
Neither pass materializes an O(S·T) score matrix in HBM.

Layout: q [B, S, H, D], k/v [B, T, KH, D] with GQA (H % KH == 0). The grid
is (B*H, Q_blocks); each program owns one q block and loops over its visible
kv blocks. K/V stay sequence-complete in VMEM per (batch, head) program —
fine through ~8k tokens at D=128 in bf16; ring attention (ring_attention.py)
is the path past that.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class MaskSpec:
    """Block-sparse attention mask families (the splash-attention mask-spec
    surface, ops/ROADMAP.md item 2). Static per compile; fully-masked
    blocks are SKIPPED by the kernels' visible-block ranges, not masked —
    the sparsity is wall-clock, not cosmetic.

    kind:
      - "causal": rows attend cols <= row (the default).
      - "full": bidirectional (encoder-style).
      - "prefix_lm": bidirectional over the first `prefix` positions,
        causal after (T5/PaLM2-style prefix LM fine-tuning).
      - "sliding_window": causal, but each row sees only the trailing
        `window` keys (Mistral-style local attention).

    Document confinement composes orthogonally via `segment_ids` — a
    window never crosses a segment boundary when both are given (the
    "document-window" mask). Exception: prefix_lm is refused with
    segment_ids (its boundary is an absolute position; packed rows
    restart positions per document).
    """

    kind: str = "causal"
    window: int = 0
    prefix: int = 0

    def __post_init__(self):
        kinds = ("causal", "full", "prefix_lm", "sliding_window")
        if self.kind not in kinds:
            raise ValueError(f"mask kind {self.kind!r}: one of {kinds}")
        if self.kind == "sliding_window" and self.window < 1:
            raise ValueError("sliding_window needs window >= 1")
        if self.kind == "prefix_lm" and self.prefix < 0:
            raise ValueError("prefix_lm needs prefix >= 0")


def _norm_mask(causal: bool, mask) -> MaskSpec:
    if mask is None:
        return MaskSpec("causal" if causal else "full")
    if isinstance(mask, str):
        return MaskSpec(mask)
    return mask


def _apply_mask(valid, rows, cols, mask: MaskSpec):
    """Fold the spec's in-block predicate into `valid` (static dispatch)."""
    if mask.kind == "causal":
        return jnp.logical_and(valid, rows >= cols)
    if mask.kind == "prefix_lm":
        return jnp.logical_and(
            valid, jnp.logical_or(rows >= cols, cols < mask.prefix))
    if mask.kind == "sliding_window":
        return jnp.logical_and(
            valid, jnp.logical_and(rows >= cols,
                                   rows - cols < mask.window))
    return valid  # full


def _q_visible(qi, block_q, block_kv, seq_kv, mask: MaskSpec):
    """(first, bound) kv-block range a q block must visit — blocks outside
    are fully masked and never touched. qi may be traced."""
    num_kv = pl.cdiv(seq_kv, block_kv)
    if mask.kind == "full":
        return 0, num_kv
    last = (qi + 1) * block_q - 1
    causal_bound = jnp.minimum(last // block_kv + 1, num_kv)
    if mask.kind == "causal":
        return 0, causal_bound
    if mask.kind == "prefix_lm":
        # Rows below the prefix see every prefix block (bidirectional).
        prefix_bound = jnp.where(
            qi * block_q < mask.prefix,
            jnp.minimum(pl.cdiv(mask.prefix, block_kv), num_kv), 0)
        return 0, jnp.maximum(causal_bound, prefix_bound)
    # sliding_window: the earliest col any row sees is first_row-window+1.
    first = jnp.maximum((qi * block_q - mask.window + 1) // block_kv, 0)
    return first, causal_bound


def _kv_visible(j, block_q, block_kv, seq_q_pad, mask: MaskSpec):
    """(first, bound) q-block range a kv block contributes gradients to."""
    num_q = seq_q_pad // block_q
    if mask.kind == "full":
        return 0, num_q
    causal_first = jnp.minimum((j * block_kv) // block_q, num_q)
    if mask.kind == "causal":
        return causal_first, num_q
    if mask.kind == "prefix_lm":
        return jnp.where(j * block_kv < mask.prefix, 0, causal_first), num_q
    # sliding_window: the last row that sees col c is c + window - 1.
    bound = jnp.minimum(
        ((j + 1) * block_kv - 1 + mask.window - 1) // block_q + 1, num_q)
    return causal_first, bound


def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, block_q: int,
                      block_kv: int, seq_kv: int, mask: MaskSpec,
                      sm_scale: float, segments: bool = False):
    if segments:
        qs_ref, ks_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, D]

    first_visible, num_visible = _q_visible(qi, block_q, block_kv, seq_kv,
                                            mask)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_kv]
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1) + j * block_kv
        # Mask padded keys (inputs are padded up to a block multiple by the
        # wrapper; without this the pad keys would attend in non-causal mode).
        valid = cols < seq_kv
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0) + qi * block_q
        valid = _apply_mask(valid, rows, cols, mask)
        if segments:
            # Packed sequences: attention confined within equal-id spans
            # (padding carries -1 on the kv side, never equal to real ids).
            qseg = qs_ref[0, :, 0][:, None]
            kseg = ks_ref[0, pl.ds(j * block_kv, block_kv), 0][None, :]
            valid = jnp.logical_and(valid, qseg == kseg)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(first_visible, num_visible, body,
                                  (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # Row logsumexp of the scaled scores — the backward's softmax residual.
    lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _flash_fwd(q3, k3, v3, seg_q3, seg_kv3, *, group: int, heads: int,
               mask: MaskSpec, block_q: int, block_kv: int, seq_kv: int,
               sm_scale: float, interpret: bool):
    """q3 [B*H, S, D]; k3/v3 [B*KH, T, D], padded to block multiples; GQA is
    served zero-copy by the K/V index_map (q program bh reads kv row
    bh // group, since bh = batch*H + qh and H = KH*group). seq_kv is the
    pre-padding key length used for masking. seg_q3/seg_kv3 [B, *, 1] (or
    None) carry packed-sequence segment ids, read zero-copy per batch row
    via b // heads index_maps. Returns (o3, lse [B*H, S])."""
    bh, s, d = q3.shape
    t = k3.shape[1]
    grid = (bh, pl.cdiv(s, block_q))
    segments = seg_q3 is not None
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_kv=block_kv, seq_kv=seq_kv,
        mask=mask, sm_scale=sm_scale, segments=segments)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, t, d), lambda b, i: (b // group, 0, 0)),
        pl.BlockSpec((1, t, d), lambda b, i: (b // group, 0, 0)),
    ]
    args = [q3, k3, v3]
    if segments:
        in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b // heads, i, 0)),
            pl.BlockSpec((1, t, 1), lambda b, i: (b // heads, 0, 0)),
        ]
        args += [seg_q3, seg_kv3]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, block_q: int, block_kv: int, seq_q: int,
                         seq_kv: int, mask: MaskSpec, sm_scale: float,
                         segments: bool = False):
    if segments:
        qs_ref, ks_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale      # [bq, D]
    do = do_ref[0].astype(jnp.float32)               # [bq, D]
    lse = lse_ref[0]                                 # [bq, 1]
    delta = delta_ref[0]                             # [bq, 1]
    rows = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0) + qi * block_q

    first_visible, num_visible = _q_visible(qi, block_q, block_kv, seq_kv,
                                            mask)

    def body(j, acc):
        k = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1) + j * block_kv
        valid = jnp.logical_and(cols < seq_kv, rows < seq_q)
        valid = _apply_mask(valid, rows, cols, mask)
        if segments:
            valid = jnp.logical_and(
                valid,
                qs_ref[0, :, 0][:, None]
                == ks_ref[0, pl.ds(j * block_kv, block_kv), 0][None, :])
        # p from saved row stats; masked (incl. padded q rows, whose lse is
        # garbage) to exactly zero so no NaN/inf leaks into the matmuls.
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    acc = jax.lax.fori_loop(first_visible, num_visible, body,
                            jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (acc * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                          *rest, block_q: int, block_kv: int,
                          seq_q: int, seq_kv: int, seq_q_pad: int, group: int,
                          mask: MaskSpec, sm_scale: float,
                          segments: bool = False):
    if segments:
        qs_ref, ks_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    j = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                 # [bkv, D]
    v = v_ref[0].astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1) + j * block_kv
    kv_valid = cols < seq_kv

    first, num_q_blocks = _kv_visible(j, block_q, block_kv, seq_q_pad, mask)

    d = q_ref.shape[-1]
    dk0 = jnp.zeros((block_kv, d), jnp.float32)
    dv0 = jnp.zeros((block_kv, d), jnp.float32)

    def make_body(g):
        base = g * seq_q_pad

        def body(qi, carry):
            dk, dv = carry
            off = base + qi * block_q
            q = q_ref[0, pl.ds(off, block_q), :].astype(
                jnp.float32) * sm_scale
            do = do_ref[0, pl.ds(off, block_q), :].astype(jnp.float32)
            lse = lse_ref[0, pl.ds(off, block_q), :]
            delta = delta_ref[0, pl.ds(off, block_q), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0) + qi * block_q
            valid = jnp.logical_and(kv_valid, rows < seq_q)
            valid = _apply_mask(valid, rows, cols, mask)
            if segments:
                valid = jnp.logical_and(
                    valid,
                    qs_ref[0, pl.ds(qi * block_q, block_q), 0][:, None]
                    == ks_ref[0, :, 0][None, :])
            p = jnp.where(valid, jnp.exp(s - lse), 0.0)
            dv_new = dv + jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dk_new = dk + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk_new, dv_new

        return body

    dk, dv = dk0, dv0
    for g in range(group):  # static, small (GQA group)
        dk, dv = jax.lax.fori_loop(first, num_q_blocks, make_body(g),
                                   (dk, dv))
    # q in the score matmul carried sm_scale; dk restores the q-side factor
    # so dk is d/dk of (q·k·scale): ds already includes the scale via q.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flatten_heads(q, k, v):
    """[B,S,H,D] → q3 [B*H, S, D], k3/v3 [B*KH, T, D] — no GQA repetition;
    the kernel's index_map maps q heads onto shared kv heads."""
    b, s, h, d = q.shape
    t, kh = k.shape[1], k.shape[2]
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * kh, t, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * kh, t, d)
    return q3, k3, v3


def _pad_seq(x3, block):
    pad = -x3.shape[1] % block
    if pad:
        x3 = jnp.pad(x3, ((0, 0), (0, pad), (0, 0)))
    return x3


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 8))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_kv: int = 512, interpret: bool | None = None,
                    segment_ids: jax.Array | None = None,
                    mask: MaskSpec | str | None = None):
    """Flash attention. q [B,S,H,D]; k,v [B,T,KH,D]; returns [B,S,H,D].

    Forward and backward both run fused Pallas kernels (O(S) memory); the
    backward uses the saved LSE row stats (two-pass dq then dk/dv).

    `segment_ids` [B,S] int (self-attention only) confines attention
    within equal-id spans — packed-sequence training with the fused
    kernels (the splash-style mask, ops/ROADMAP.md item 3).

    `mask` (a MaskSpec or kind string) selects the block-sparse mask
    family — causal / full / prefix_lm / sliding_window — overriding
    `causal`; fully-masked blocks are skipped in all three kernels.
    causal/full/sliding_window compose with `segment_ids` (document-window
    masks: in-document index distance equals position distance, so the
    window is per-document automatically). prefix_lm does NOT — its
    boundary is an absolute position, which packed rows restart per
    document — and is refused with segment_ids rather than silently
    masking only the first document's prefix."""
    out, _ = _attn_impl(q, k, v, causal, block_q, block_kv, interpret,
                        segment_ids, mask)
    return out


def _resolve(q, k, block_q, block_kv, interpret):
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    s, t = q.shape[1], k.shape[1]
    block_q = min(block_q, max(s, 1))
    block_kv = min(block_kv, max(t, 1))
    return block_q, block_kv, interpret


def _seg3(segment_ids, block, b, s, t):
    """[B,S] segment ids → padded [B, S_pad, 1]. NOT replicated per head —
    the BlockSpec index_maps (b // heads) read the shared batch row
    zero-copy. Trailing unit dim: Mosaic needs the last two block dims to
    be (8k, 128k)-divisible or array-equal; (block, 1) satisfies that."""
    if segment_ids is None:
        return None
    if segment_ids.shape != (b, s) or t != s:
        raise ValueError(
            f"segment_ids must be [B,S]={b, s} for self-attention "
            f"(got {segment_ids.shape}, T={t})")
    seg = jnp.asarray(segment_ids, jnp.int32)
    pad = -seg.shape[1] % block
    if pad:
        seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-1)
    return seg[:, :, None]


def _attn_impl(q, k, v, causal, block_q, block_kv, interpret,
               segment_ids=None, mask=None):
    b, s, h, d = q.shape
    t, kh = k.shape[1], k.shape[2]
    if h % kh:
        raise ValueError(f"q heads {h} not a multiple of kv heads {kh}")
    block_q, block_kv, interpret = _resolve(q, k, block_q, block_kv,
                                            interpret)
    spec = _norm_mask(causal, mask)
    if spec.kind == "prefix_lm" and segment_ids is not None:
        raise ValueError(
            "prefix_lm does not compose with segment_ids: packed rows "
            "restart positions per document, but the prefix boundary is "
            "an absolute index — only the first document would get a "
            "bidirectional prefix. Pack prefix-LM data unsegmented.")
    sm_scale = 1.0 / (d ** 0.5)
    q3, k3, v3 = _flatten_heads(q, k, v)
    # Pad sequences to block multiples: unpadded dynamic slices would clamp
    # at the boundary and silently misalign kv columns. The kernel masks
    # padded keys via its seq_kv bound; padded q rows are sliced off here.
    q3 = _pad_seq(q3, block_q)
    k3 = _pad_seq(k3, block_kv)
    v3 = _pad_seq(v3, block_kv)
    sq3 = _seg3(segment_ids, block_q, b, s, t)
    skv3 = _seg3(segment_ids, block_kv, b, s, t)
    o3, lse = _flash_fwd(q3, k3, v3, sq3, skv3, group=h // kh, heads=h,
                         mask=spec, block_q=block_q, block_kv=block_kv,
                         seq_kv=t, sm_scale=sm_scale, interpret=interpret)
    out = o3[:, :s].reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out, (o3, lse)


def _float0_like(x):
    """Cotangent for integer-dtype primals (segment ids)."""
    if x is None:
        return None
    import numpy as np
    return np.zeros(x.shape, dtype=jax.dtypes.float0)


def _flash_fwd_rule(q, k, v, causal, block_q, block_kv, interpret,
                    segment_ids=None, mask=None):
    out, (o3, lse) = _attn_impl(q, k, v, causal, block_q, block_kv,
                                interpret, segment_ids, mask)
    return out, (q, k, v, o3, lse, segment_ids)


def _flash_bwd_rule(causal, block_q, block_kv, interpret, mask, res, g):
    q, k, v, o3, lse, segment_ids = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o3, lse, g, None, causal, block_q,
                                 block_kv, interpret, segment_ids, mask)
    return dq, dk, dv, _float0_like(segment_ids)


def _flash_bwd_impl(q, k, v, o3, lse, g, g_lse, causal, block_q, block_kv,
                    interpret, segment_ids=None, mask=None):
    """Shared two-pass backward. `g_lse` [B,S,H,1] (or None) is the LSE
    cotangent: d lse_i/d s_ij = p_ij, so it folds into the delta term —
    ds = p·(dp - (delta - g_lse)) — at zero extra kernel cost."""
    b, s, h, d = q.shape
    t, kh = k.shape[1], k.shape[2]
    group = h // kh
    block_q, block_kv, interpret = _resolve(q, k, block_q, block_kv,
                                            interpret)
    spec = _norm_mask(causal, mask)
    sm_scale = 1.0 / (d ** 0.5)

    q3, k3, v3 = _flatten_heads(q, k, v)
    q3 = _pad_seq(q3, block_q)
    k3 = _pad_seq(k3, block_kv)
    v3 = _pad_seq(v3, block_kv)
    do3 = _pad_seq(g.transpose(0, 2, 1, 3).reshape(b * h, s, d), block_q)
    s_pad, t_pad = q3.shape[1], k3.shape[1]
    bh, bkh = b * h, b * kh

    # delta_i = rowsum(dO_i · O_i) — the softmax-normalization term.
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if g_lse is not None:
        gl3 = _pad_seq(
            g_lse.astype(jnp.float32).transpose(0, 2, 1, 3).reshape(
                b * h, s, 1), block_q)
        delta = delta - gl3

    segments = segment_ids is not None
    sq3 = _seg3(segment_ids, block_q, b, s, t)
    skv3 = _seg3(segment_ids, block_kv, b, s, t)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_q=block_q, block_kv=block_kv, seq_q=s,
        seq_kv=t, mask=spec, sm_scale=sm_scale, segments=segments)
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
        pl.BlockSpec((1, t_pad, d), lambda bi, i: (bi // group, 0, 0)),
        pl.BlockSpec((1, t_pad, d), lambda bi, i: (bi // group, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bi, i: (bi, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bi, i: (bi, i, 0)),
    ]
    dq_args = [q3, k3, v3, do3, lse, delta]
    if segments:
        dq_specs += [
            pl.BlockSpec((1, block_q, 1), lambda bi, i: (bi // h, i, 0)),
            pl.BlockSpec((1, t_pad, 1), lambda bi, i: (bi // h, 0, 0)),
        ]
        dq_args += [sq3, skv3]
    dq3 = pl.pallas_call(
        dq_kernel,
        grid=(bh, s_pad // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_pad, d), q.dtype),
        interpret=interpret,
    )(*dq_args)

    # Grouped (per kv head) views of the q-side tensors: pure reshapes of the
    # [B*H, ...] layout since q head h serves kv head h // group.
    qg = q3.reshape(bkh, group * s_pad, d)
    dog = do3.reshape(bkh, group * s_pad, d)
    lseg = lse.reshape(bkh, group * s_pad, 1)
    deltag = delta.reshape(bkh, group * s_pad, 1)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_q=block_q, block_kv=block_kv, seq_q=s,
        seq_kv=t, seq_q_pad=s_pad, group=group, mask=spec,
        sm_scale=sm_scale, segments=segments)
    dkv_specs = [
        pl.BlockSpec((1, group * s_pad, d), lambda bi, j: (bi, 0, 0)),
        pl.BlockSpec((1, group * s_pad, d), lambda bi, j: (bi, 0, 0)),
        pl.BlockSpec((1, group * s_pad, 1), lambda bi, j: (bi, 0, 0)),
        pl.BlockSpec((1, group * s_pad, 1), lambda bi, j: (bi, 0, 0)),
        pl.BlockSpec((1, block_kv, d), lambda bi, j: (bi, j, 0)),
        pl.BlockSpec((1, block_kv, d), lambda bi, j: (bi, j, 0)),
    ]
    dkv_args = [qg, dog, lseg, deltag, k3, v3]
    if segments:
        dkv_specs += [
            pl.BlockSpec((1, sq3.shape[1], 1), lambda bi, j: (bi // kh, 0, 0)),
            pl.BlockSpec((1, block_kv, 1), lambda bi, j: (bi // kh, j, 0)),
        ]
        dkv_args += [sq3, skv3]
    dk3, dv3 = pl.pallas_call(
        dkv_kernel,
        grid=(bkh, t_pad // block_kv),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_kv, d), lambda bi, j: (bi, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda bi, j: (bi, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkh, t_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bkh, t_pad, d), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_args)

    dq = dq3[:, :s].reshape(b, h, s, d).transpose(0, 2, 1, 3)
    dk = dk3[:, :t].reshape(b, kh, t, d).transpose(0, 2, 1, 3)
    dv = dv3[:, :t].reshape(b, kh, t, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# -- (out, lse) variant: the ring-attention inner block ----------------------
# Ring attention merges per-step partial results by their row logsumexp, so
# the inner op must EXPOSE lse and be differentiable in it. The backward is
# the same two kernels with delta := delta - g_lse (see _flash_bwd_impl).


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(q, k, v, causal: bool = True, block_q: int = 512,
                        block_kv: int = 512, interpret: bool | None = None):
    """Flash attention returning (out [B,S,H,D], lse [B,S,H,1] fp32).

    lse is the per-row logsumexp of the scaled scores — the online-softmax
    merge statistic. Both outputs are differentiable."""
    out, (o3, lse) = _attn_impl(q, k, v, causal, block_q, block_kv,
                                interpret)
    return out, _lse_bshl(lse, q.shape)


def _lse_bshl(lse3, qshape):
    b, s, h, d = qshape
    return lse3[:, :s].reshape(b, h, s, 1).transpose(0, 2, 1, 3)


def _flash_lse_fwd_rule(q, k, v, causal, block_q, block_kv, interpret):
    out, (o3, lse) = _attn_impl(q, k, v, causal, block_q, block_kv,
                                interpret)
    return (out, _lse_bshl(lse, q.shape)), (q, k, v, o3, lse)


def _flash_lse_bwd_rule(causal, block_q, block_kv, interpret, res, g):
    q, k, v, o3, lse = res
    g_out, g_lse = g
    return _flash_bwd_impl(q, k, v, o3, lse, g_out, g_lse, causal, block_q,
                           block_kv, interpret)


flash_attention_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)
