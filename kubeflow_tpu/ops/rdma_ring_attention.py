"""RDMA ring attention — K/V rotation by remote DMA inside one kernel.

The lax-level rings (ops/ring_attention.py) rotate K/V with
`jax.lax.ppermute` between per-step compute calls and rely on XLA to
overlap the collective with compute. This kernel makes the overlap
EXPLICIT (pallas_guide.md ring-collectives pattern, ops/ROADMAP.md item):
one Pallas program per device owns a double-buffered K/V scratch, STARTS
the remote copy of the current buffer to the right neighbour, computes
attention against it while the DMA flies, then waits the incoming buffer.

Backpressure is DMA-based: after finishing compute on a slot, a device
sends a tiny "slot free" ack to its LEFT neighbour (the one that writes
into its buffers); a sender waits that ack before overwriting a slot the
receiver may still be reading. Two slots + acks give lockstep-free
pipelining with bounded VMEM — the kernel never materialises more than
2 K/V shards.

Causality is masked by global positions (shard offset + row index), so
every ring step is one masked flash-style block — no cross-step state
besides the online-softmax partials.

The backward is fused too (ops/ROADMAP.md item 1, landed round 3): a
two-pass design where every traveling payload is READ-ONLY, so the DMA
overlaps compute exactly like the forward —
  * pass 1 (dq): K/V rotate (read-only), each device accumulates its
    resident dq from saved (lse, delta) row stats;
  * pass 2 (dk/dv): q/dout/lse/delta rotate (read-only), each device
    accumulates its RESIDENT dk/dv — no traveling accumulator, so no
    post-compute copy serialization and no final homing rotation.
Forward saves lse when under AD (`save_lse`); delta = rowsum(dout·out) is
computed at the lax level inside the shard_map region.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P
from jax import shard_map

from kubeflow_tpu.parallel.mesh import current_mesh

NEG_INF = -1e30


def _rdma_kernel(q_ref, k_ref, v_ref, o_ref, *rest, n: int, axis: str,
                 bkh: int, group: int, s: int, d: int, sm_scale: float,
                 save_lse: bool = False):
    """q_ref [bkh*group, s, d]; k/v_ref [bkh, s, d]; o_ref like q.
    kvbuf [2, 2, bkh, s, d] (slot, k|v, head, row, d); ackbuf [2, 1, 128].
    All VMEM. n = ring size (static); unrolled python loop. With
    `save_lse`, also writes lse [bkh, group*s, 1] f32 (AD residual)."""
    if save_lse:
        lse_ref, kvbuf, ackbuf, dsend, drecv, asend, arecv = rest
    else:
        kvbuf, ackbuf, dsend, drecv, asend, arecv = rest
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me + n - 1, n)

    # Slot 0 starts as the local shard.
    kvbuf[0, 0] = k_ref[...]
    kvbuf[0, 1] = v_ref[...]

    rows = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 0)
    rows = jax.lax.rem(rows, s) + me * s  # global q positions per head row
    cols_local = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 1)

    # Per-kv-head online-softmax partials (python lists: bkh is static).
    accs = [jnp.zeros((group * s, d), jnp.float32) for _ in range(bkh)]
    ms = [jnp.full((group * s, 1), NEG_INF, jnp.float32) for _ in range(bkh)]
    ls = [jnp.zeros((group * s, 1), jnp.float32) for _ in range(bkh)]

    for i in range(n):
        cur, nxt = i % 2, (i + 1) % 2
        data_copy = None
        if i < n - 1:
            if i >= 1:
                # Right must have freed slot `nxt` (its compute i-1 done).
                pltpu.make_async_remote_copy(
                    src_ref=ackbuf.at[nxt], dst_ref=ackbuf.at[nxt],
                    send_sem=asend.at[nxt], recv_sem=arecv.at[nxt],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL).wait_recv()
            data_copy = pltpu.make_async_remote_copy(
                src_ref=kvbuf.at[cur], dst_ref=kvbuf.at[nxt],
                send_sem=dsend.at[nxt], recv_sem=drecv.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            data_copy.start()

        # The resident buffer originated at shard (me - i) mod n.
        src = jax.lax.rem(me + n - i, n)
        cols = cols_local + src * s
        mask = rows >= cols
        # q is laid out [bkh, group*s, d] (_rdma_fwd), so each kv head's
        # queries are one contiguous 2-D block.
        for h in range(bkh):
            qh = q_ref[h].astype(jnp.float32) * sm_scale      # [group*s, d]
            kh = kvbuf[cur, 0, h].astype(jnp.float32)         # [s, d]
            vh = kvbuf[cur, 1, h].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)           # [group*s, s]
            sc = jnp.where(mask, sc, NEG_INF)
            m_new = jnp.maximum(ms[h], jnp.max(sc, axis=1, keepdims=True))
            p = jnp.exp(sc - m_new)
            alpha = jnp.exp(ms[h] - m_new)
            ls[h] = ls[h] * alpha + jnp.sum(p, axis=1, keepdims=True)
            accs[h] = accs[h] * alpha + jax.lax.dot_general(
                p, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ms[h] = m_new

        if i < n - 1:
            data_copy.wait_send()  # outgoing read of `cur` complete...
        if i <= n - 3:
            # ...so LEFT may now overwrite my `cur` slot: ack it.
            ack = pltpu.make_async_remote_copy(
                src_ref=ackbuf.at[cur], dst_ref=ackbuf.at[cur],
                send_sem=asend.at[cur], recv_sem=arecv.at[cur],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            ack.start()
            ack.wait_send()
        if i < n - 1:
            data_copy.wait_recv()  # incoming `nxt` from LEFT has landed

    for h in range(bkh):
        o_ref[h] = (accs[h] / jnp.maximum(ls[h], 1e-30)).astype(o_ref.dtype)
        if save_lse:
            lse_ref[h] = ms[h] + jnp.log(jnp.maximum(ls[h], 1e-30))


def _rdma_fwd(q, k, v, axis_name, mesh, n, interpret, save_lse=False):
    b, s_glob, h, d = q.shape
    kh = k.shape[2]
    group = h // kh

    # Specs adapt to the mesh's axes (shared rule with the lax-level
    # rings): the full framework mesh shards batch over (data, fsdp); a
    # dedicated single-axis ring mesh (the only shape the INTERPRET
    # path's DMA discharge supports — compiled Mosaic has no such limit)
    # leaves batch replicated.
    from kubeflow_tpu.ops.ring_attention import _batch_spec

    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)
    spec3 = P(_batch_spec(mesh, axis_name), axis_name, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=((spec, spec3) if save_lse else spec), check_vma=False)
    def _run(q, k, v):
        bl, s, _, _ = q.shape  # local shapes
        bkh = bl * kh
        # Layout: one contiguous [group*s, d] q block per kv head.
        q3 = q.transpose(0, 2, 1, 3).reshape(bl, kh, group, s, d)
        q3 = q3.reshape(bkh, group * s, d)
        k3 = k.transpose(0, 2, 1, 3).reshape(bkh, s, d)
        v3 = v.transpose(0, 2, 1, 3).reshape(bkh, s, d)
        kernel = functools.partial(
            _rdma_kernel, n=n, axis=axis_name, bkh=bkh, group=group, s=s,
            d=d, sm_scale=1.0 / (d ** 0.5), save_lse=save_lse)
        out_shape = jax.ShapeDtypeStruct((bkh, group * s, d), q.dtype)
        if save_lse:
            out_shape = (out_shape, jax.ShapeDtypeStruct(
                (bkh, group * s, 1), jnp.float32))
        res = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((2, 2, bkh, s, d), k.dtype),
                pltpu.VMEM((2, 1, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(collective_id=7),
        )(q3, k3, v3)
        o3 = res[0] if save_lse else res
        out = o3.reshape(bl, kh, group, s, d).transpose(0, 3, 1, 2, 4)
        out = out.reshape(bl, s, h, d)
        if not save_lse:
            return out
        lse = res[1].reshape(bl, kh, group, s).transpose(0, 3, 1, 2)
        return out, lse.reshape(bl, s, h)

    return _run(q, k, v)


def _rdma_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, kvbuf, ackbuf, dsend, drecv, asend, arecv,
                        *, n, axis, bkh, group, s, d, sm_scale):
    """Backward pass 1: K/V rotate exactly as in the forward (read-only
    payload ⇒ full DMA/compute overlap); resident dq accumulates from the
    saved row stats. q/do [bkh, group*s, d]; lse/delta [bkh, group*s, 1]."""
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me + n - 1, n)
    kvbuf[0, 0] = k_ref[...]
    kvbuf[0, 1] = v_ref[...]

    rows = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 0)
    rows = jax.lax.rem(rows, s) + me * s
    cols_local = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 1)
    dqs = [jnp.zeros((group * s, d), jnp.float32) for _ in range(bkh)]

    for i in range(n):
        cur, nxt = i % 2, (i + 1) % 2
        data_copy = None
        if i < n - 1:
            if i >= 1:
                pltpu.make_async_remote_copy(
                    src_ref=ackbuf.at[nxt], dst_ref=ackbuf.at[nxt],
                    send_sem=asend.at[nxt], recv_sem=arecv.at[nxt],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL).wait_recv()
            data_copy = pltpu.make_async_remote_copy(
                src_ref=kvbuf.at[cur], dst_ref=kvbuf.at[nxt],
                send_sem=dsend.at[nxt], recv_sem=drecv.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            data_copy.start()

        src = jax.lax.rem(me + n - i, n)
        mask = rows >= cols_local + src * s
        for h in range(bkh):
            qh = q_ref[h].astype(jnp.float32) * sm_scale
            doh = do_ref[h].astype(jnp.float32)
            kh = kvbuf[cur, 0, h].astype(jnp.float32)
            vh = kvbuf[cur, 1, h].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            sc = jnp.where(mask, sc, NEG_INF)
            p = jnp.exp(sc - lse_ref[h])                       # [gs, s]
            dp = jax.lax.dot_general(
                doh, vh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta_ref[h])
            dqs[h] = dqs[h] + jax.lax.dot_general(
                ds, kh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale

        if i < n - 1:
            data_copy.wait_send()
        if i <= n - 3:
            ack = pltpu.make_async_remote_copy(
                src_ref=ackbuf.at[cur], dst_ref=ackbuf.at[cur],
                send_sem=asend.at[cur], recv_sem=arecv.at[cur],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            ack.start()
            ack.wait_send()
        if i < n - 1:
            data_copy.wait_recv()

    for h in range(bkh):
        dq_ref[h] = dqs[h].astype(dq_ref.dtype)


def _rdma_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, qbuf, statbuf, ackbuf,
                         qsend, qrecv, ssend, srecv, asend, arecv,
                         *, n, axis, bkh, group, s, d, sm_scale):
    """Backward pass 2: q/dout (qbuf) and lse/delta (statbuf) rotate —
    all read-only — while RESIDENT dk/dv accumulate. No traveling
    accumulator ⇒ no post-compute copy serialization, no homing step."""
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me + n - 1, n)
    qbuf[0, 0] = q_ref[...]
    qbuf[0, 1] = do_ref[...]
    statbuf[0, 0] = lse_ref[...]
    statbuf[0, 1] = delta_ref[...]

    qrows_local = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 0)
    qrows_local = jax.lax.rem(qrows_local, s)
    cols = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 1) + me * s
    dks = [jnp.zeros((s, d), jnp.float32) for _ in range(bkh)]
    dvs = [jnp.zeros((s, d), jnp.float32) for _ in range(bkh)]

    for i in range(n):
        cur, nxt = i % 2, (i + 1) % 2
        q_copy = s_copy = None
        if i < n - 1:
            if i >= 1:
                pltpu.make_async_remote_copy(
                    src_ref=ackbuf.at[nxt], dst_ref=ackbuf.at[nxt],
                    send_sem=asend.at[nxt], recv_sem=arecv.at[nxt],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL).wait_recv()
            q_copy = pltpu.make_async_remote_copy(
                src_ref=qbuf.at[cur], dst_ref=qbuf.at[nxt],
                send_sem=qsend.at[nxt], recv_sem=qrecv.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            s_copy = pltpu.make_async_remote_copy(
                src_ref=statbuf.at[cur], dst_ref=statbuf.at[nxt],
                send_sem=ssend.at[nxt], recv_sem=srecv.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            q_copy.start()
            s_copy.start()

        # The resident q/do block originated at shard (me - i) mod n.
        src = jax.lax.rem(me + n - i, n)
        mask = (qrows_local + src * s) >= cols
        for h in range(bkh):
            qh = qbuf[cur, 0, h].astype(jnp.float32) * sm_scale
            doh = qbuf[cur, 1, h].astype(jnp.float32)
            lse = statbuf[cur, 0, h]
            delta = statbuf[cur, 1, h]
            kh = k_ref[h].astype(jnp.float32)
            vh = v_ref[h].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            sc = jnp.where(mask, sc, NEG_INF)
            p = jnp.exp(sc - lse)                              # [gs, s]
            dvs[h] = dvs[h] + jax.lax.dot_general(
                p, doh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                doh, vh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dks[h] = dks[h] + jax.lax.dot_general(
                ds, qh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if i < n - 1:
            q_copy.wait_send()
            s_copy.wait_send()
        if i <= n - 3:
            ack = pltpu.make_async_remote_copy(
                src_ref=ackbuf.at[cur], dst_ref=ackbuf.at[cur],
                send_sem=asend.at[cur], recv_sem=arecv.at[cur],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            ack.start()
            ack.wait_send()
        if i < n - 1:
            q_copy.wait_recv()
            s_copy.wait_recv()

    for h in range(bkh):
        dk_ref[h] = dks[h].astype(dk_ref.dtype)
        dv_ref[h] = dvs[h].astype(dv_ref.dtype)


def _rdma_bwd(q, k, v, o, lse, g, axis_name, mesh, n, interpret):
    """Fused two-pass backward driver: both passes mirror the forward's
    double-buffered rotation with DMA-ack backpressure; delta is the lax-
    level rowsum(dout·out) computed inside the shard_map region."""
    b, s_glob, h, d = q.shape
    kh = k.shape[2]
    group = h // kh
    from kubeflow_tpu.ops.ring_attention import _batch_spec

    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)
    spec3 = P(_batch_spec(mesh, axis_name), axis_name, None)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec3, spec),
        out_specs=(spec, spec, spec), check_vma=False)
    def _run(q, k, v, o, lse, g):
        bl, s, _, _ = q.shape
        bkh = bl * kh

        def qlayout(x):
            x3 = x.transpose(0, 2, 1, 3).reshape(bl, kh, group, s, d)
            return x3.reshape(bkh, group * s, d)

        q3, do3, o3 = qlayout(q), qlayout(g), qlayout(o)
        k3 = k.transpose(0, 2, 1, 3).reshape(bkh, s, d)
        v3 = v.transpose(0, 2, 1, 3).reshape(bkh, s, d)
        lse3 = lse.transpose(0, 2, 1).reshape(bl, kh, group, s)
        lse3 = lse3.reshape(bkh, group * s, 1)
        delta3 = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                         axis=-1, keepdims=True)

        common = dict(n=n, axis=axis_name, bkh=bkh, group=group, s=s, d=d,
                      sm_scale=1.0 / (d ** 0.5))
        sems = [pltpu.SemaphoreType.DMA((2,))] * 6
        dq3 = pl.pallas_call(
            functools.partial(_rdma_bwd_dq_kernel, **common),
            out_shape=jax.ShapeDtypeStruct((bkh, group * s, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((2, 2, bkh, s, d), k.dtype),
                pltpu.VMEM((2, 1, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(collective_id=8),
        )(q3, k3, v3, do3, lse3, delta3)
        dk3, dv3 = pl.pallas_call(
            functools.partial(_rdma_bwd_dkv_kernel, **common),
            out_shape=(jax.ShapeDtypeStruct((bkh, s, d), k.dtype),
                       jax.ShapeDtypeStruct((bkh, s, d), v.dtype)),
            scratch_shapes=[
                pltpu.VMEM((2, 2, bkh, group * s, d), q.dtype),
                pltpu.VMEM((2, 2, bkh, group * s, 1), jnp.float32),
                pltpu.VMEM((2, 1, 128), jnp.float32),
                *sems,
            ],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(collective_id=9),
        )(q3, k3, v3, do3, lse3, delta3)

        def unq(x3):
            x = x3.reshape(bl, kh, group, s, d).transpose(0, 3, 1, 2, 4)
            return x.reshape(bl, s, h, d)

        def unkv(x3):
            return x3.reshape(bl, kh, s, d).transpose(0, 2, 1, 3)

        return unq(dq3), unkv(dk3), unkv(dv3)

    return _run(q, k, v, o, lse, g)


def _resolve_ring(axis_name, mesh, interpret):
    """Shared (mesh, n, interpret) resolution for the primal and both VJP
    rules — one place for the backend heuristic and the mesh requirement,
    so forward and backward can't desynchronize."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("rdma_ring_attention needs a mesh")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    return mesh, mesh.shape[axis_name], interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def rdma_ring_attention(q, k, v, axis_name: str = "seq", mesh=None,
                        interpret: bool | None = None):
    """Causal ring attention with in-kernel remote-DMA K/V rotation.
    q [B,S,H,D], k/v [B,S,KH,D] over the `axis_name` ring (contiguous
    layout). Forward runs the fused RDMA kernel (saving lse under AD);
    the backward is the fused two-pass RDMA design as well — K/V rotate
    for resident dq, then q/dout/lse/delta rotate for resident dk/dv —
    so CP training steady-state stays on the in-kernel rotation path."""
    mesh, n, interpret = _resolve_ring(axis_name, mesh, interpret)
    if n == 1:
        from kubeflow_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, True)
    return _rdma_fwd(q, k, v, axis_name, mesh, n, interpret)


def _vjp_fwd(q, k, v, axis_name, mesh, interpret):
    mesh, n, interpret = _resolve_ring(axis_name, mesh, interpret)
    if n == 1:
        from kubeflow_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, True), (q, k, v, None, None)
    out, lse = _rdma_fwd(q, k, v, axis_name, mesh, n, interpret,
                         save_lse=True)
    return out, (q, k, v, out, lse)


def _vjp_bwd(axis_name, mesh, interpret, res, g):
    q, k, v, o, lse = res
    if o is None:  # single-member ring: plain flash attention
        from kubeflow_tpu.ops.flash_attention import flash_attention
        _, pullback = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, True), q, k, v)
        return pullback(g)
    mesh, n, interpret = _resolve_ring(axis_name, mesh, interpret)
    return _rdma_bwd(q, k, v, o, lse, g, axis_name, mesh, n, interpret)


rdma_ring_attention.defvjp(_vjp_fwd, _vjp_bwd)
