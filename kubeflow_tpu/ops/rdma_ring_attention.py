"""RDMA ring attention — K/V rotation by remote DMA inside one kernel.

The lax-level rings (ops/ring_attention.py) rotate K/V with
`jax.lax.ppermute` between per-step compute calls and rely on XLA to
overlap the collective with compute. This kernel makes the overlap
EXPLICIT (pallas_guide.md ring-collectives pattern, ops/ROADMAP.md item):
one Pallas program per device owns a double-buffered K/V scratch, STARTS
the remote copy of the current buffer to the right neighbour, computes
attention against it while the DMA flies, then waits the incoming buffer.

Backpressure is DMA-based: after finishing compute on a slot, a device
sends a tiny "slot free" ack to its LEFT neighbour (the one that writes
into its buffers); a sender waits that ack before overwriting a slot the
receiver may still be reading. Two slots + acks give lockstep-free
pipelining with bounded VMEM — the kernel never materialises more than
2 K/V shards.

Causality is masked by global positions (shard offset + row index), so
every ring step is one masked flash-style block — no cross-step state
besides the online-softmax partials.

Forward-only kernel: the backward runs through the lax-level flash ring
(`ring_attention(inner="flash")`) via a custom VJP — any correct gradient
of the same math; the RDMA win is a forward/serving/inference-time and
steady-state-throughput property.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P
from jax import shard_map

from kubeflow_tpu.parallel.mesh import current_mesh

NEG_INF = -1e30


def _rdma_kernel(q_ref, k_ref, v_ref, o_ref, kvbuf, ackbuf,
                 dsend, drecv, asend, arecv, *, n: int, axis: str,
                 bkh: int, group: int, s: int, d: int, sm_scale: float):
    """q_ref [bkh*group, s, d]; k/v_ref [bkh, s, d]; o_ref like q.
    kvbuf [2, 2, bkh, s, d] (slot, k|v, head, row, d); ackbuf [2, 1, 128].
    All VMEM. n = ring size (static); unrolled python loop."""
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me + n - 1, n)

    # Slot 0 starts as the local shard.
    kvbuf[0, 0] = k_ref[...]
    kvbuf[0, 1] = v_ref[...]

    rows = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 0)
    rows = jax.lax.rem(rows, s) + me * s  # global q positions per head row
    cols_local = jax.lax.broadcasted_iota(jnp.int32, (group * s, s), 1)

    # Per-kv-head online-softmax partials (python lists: bkh is static).
    accs = [jnp.zeros((group * s, d), jnp.float32) for _ in range(bkh)]
    ms = [jnp.full((group * s, 1), NEG_INF, jnp.float32) for _ in range(bkh)]
    ls = [jnp.zeros((group * s, 1), jnp.float32) for _ in range(bkh)]

    for i in range(n):
        cur, nxt = i % 2, (i + 1) % 2
        data_copy = None
        if i < n - 1:
            if i >= 1:
                # Right must have freed slot `nxt` (its compute i-1 done).
                pltpu.make_async_remote_copy(
                    src_ref=ackbuf.at[nxt], dst_ref=ackbuf.at[nxt],
                    send_sem=asend.at[nxt], recv_sem=arecv.at[nxt],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL).wait_recv()
            data_copy = pltpu.make_async_remote_copy(
                src_ref=kvbuf.at[cur], dst_ref=kvbuf.at[nxt],
                send_sem=dsend.at[nxt], recv_sem=drecv.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            data_copy.start()

        # The resident buffer originated at shard (me - i) mod n.
        src = jax.lax.rem(me + n - i, n)
        cols = cols_local + src * s
        mask = rows >= cols
        # q is laid out [bkh, group*s, d] (_rdma_fwd), so each kv head's
        # queries are one contiguous 2-D block.
        for h in range(bkh):
            qh = q_ref[h].astype(jnp.float32) * sm_scale      # [group*s, d]
            kh = kvbuf[cur, 0, h].astype(jnp.float32)         # [s, d]
            vh = kvbuf[cur, 1, h].astype(jnp.float32)
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)           # [group*s, s]
            sc = jnp.where(mask, sc, NEG_INF)
            m_new = jnp.maximum(ms[h], jnp.max(sc, axis=1, keepdims=True))
            p = jnp.exp(sc - m_new)
            alpha = jnp.exp(ms[h] - m_new)
            ls[h] = ls[h] * alpha + jnp.sum(p, axis=1, keepdims=True)
            accs[h] = accs[h] * alpha + jax.lax.dot_general(
                p, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ms[h] = m_new

        if i < n - 1:
            data_copy.wait_send()  # outgoing read of `cur` complete...
        if i <= n - 3:
            # ...so LEFT may now overwrite my `cur` slot: ack it.
            ack = pltpu.make_async_remote_copy(
                src_ref=ackbuf.at[cur], dst_ref=ackbuf.at[cur],
                send_sem=asend.at[cur], recv_sem=arecv.at[cur],
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            ack.start()
            ack.wait_send()
        if i < n - 1:
            data_copy.wait_recv()  # incoming `nxt` from LEFT has landed

    for h in range(bkh):
        o_ref[h] = (accs[h] / jnp.maximum(ls[h], 1e-30)).astype(o_ref.dtype)


def _rdma_fwd(q, k, v, axis_name, mesh, n, interpret):
    b, s_glob, h, d = q.shape
    kh = k.shape[2]
    group = h // kh

    # Specs adapt to the mesh's axes (shared rule with the lax-level
    # rings): the full framework mesh shards batch over (data, fsdp); a
    # dedicated single-axis ring mesh (the only shape the INTERPRET
    # path's DMA discharge supports — compiled Mosaic has no such limit)
    # leaves batch replicated.
    from kubeflow_tpu.ops.ring_attention import _batch_spec

    spec = P(_batch_spec(mesh, axis_name), axis_name, None, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _run(q, k, v):
        bl, s, _, _ = q.shape  # local shapes
        bkh = bl * kh
        # Layout: one contiguous [group*s, d] q block per kv head.
        q3 = q.transpose(0, 2, 1, 3).reshape(bl, kh, group, s, d)
        q3 = q3.reshape(bkh, group * s, d)
        k3 = k.transpose(0, 2, 1, 3).reshape(bkh, s, d)
        v3 = v.transpose(0, 2, 1, 3).reshape(bkh, s, d)
        kernel = functools.partial(
            _rdma_kernel, n=n, axis=axis_name, bkh=bkh, group=group, s=s,
            d=d, sm_scale=1.0 / (d ** 0.5))
        o3 = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((bkh, group * s, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((2, 2, bkh, s, d), k.dtype),
                pltpu.VMEM((2, 1, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(collective_id=7),
        )(q3, k3, v3)
        out = o3.reshape(bl, kh, group, s, d).transpose(0, 3, 1, 2, 4)
        return out.reshape(bl, s, h, d)

    return _run(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def rdma_ring_attention(q, k, v, axis_name: str = "seq", mesh=None,
                        interpret: bool | None = None):
    """Causal ring attention with in-kernel remote-DMA K/V rotation.
    q [B,S,H,D], k/v [B,S,KH,D] over the `axis_name` ring (contiguous
    layout). Forward runs the fused RDMA kernel; gradients route through
    the lax-level flash ring (same math)."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("rdma_ring_attention needs a mesh")
    n = mesh.shape[axis_name]
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    if n == 1:
        from kubeflow_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, True)
    return _rdma_fwd(q, k, v, axis_name, mesh, n, interpret)


def _vjp_fwd(q, k, v, axis_name, mesh, interpret):
    return rdma_ring_attention(q, k, v, axis_name, mesh, interpret), (q, k, v)


def _vjp_bwd(axis_name, mesh, interpret, res, g):
    from kubeflow_tpu.ops.ring_attention import ring_attention

    q, k, v = res
    mesh = mesh or current_mesh()
    _, pullback = jax.vjp(
        lambda q, k, v: ring_attention(q, k, v, axis_name=axis_name,
                                       mesh=mesh, inner="flash"), q, k, v)
    return pullback(g)


rdma_ring_attention.defvjp(_vjp_fwd, _vjp_bwd)
