"""kubeflow_tpu — a TPU-native ML platform with Kubeflow's capabilities.

A brand-new, TPU-first framework with the capabilities of the Kubeflow
platform (training-operator, KServe, Katib, Pipelines), designed natively
for JAX/XLA on TPU rather than ported from the reference's Go/Kubernetes/
NCCL architecture.

Layer map (see SURVEY.md §7.1):
  parallel/     mesh builder + sharding-rule engine (DP/FSDP/TP/SP/EP)
  models/       flax model zoo (MLP, Llama-class decoder, BERT encoder)
  ops/          Pallas TPU kernels (flash attention, ring attention)
  train/        train-step factory, trainer loop, MFU meter, checkpointing
  data/         input pipelines (synthetic + grain)
  comms/        process bootstrap (jax.distributed) + ICI/DCN mesh contract
  serve/        model server (AOT compile, batching) — KServe equivalent
  tune/         HPO engine (random/grid/TPE, median stop) — Katib equivalent
  pipelines/    DSL → IR → DAG executor with caching — KFP equivalent
  controlplane/ Python client for the C++ control plane (cpp/)
"""

__version__ = "0.1.0"
