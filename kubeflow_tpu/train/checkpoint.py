"""Checkpoint/auto-resume on orbax — first-class, unlike the reference.

Kubeflow leaves checkpointing to user code on PVCs (SURVEY.md §5.4); the
platform's only resume stories are Katib's DB resume and KFP's step cache.
Here every training job checkpoints through this manager (async, sharded,
multi-host-safe via orbax), and the JAXJob controller restarts processes
with `restore=latest` — checkpoint-restart IS the elasticity mechanism
(§5.3: world-resize in JAX means recompile, so v1 elasticity = resume).
"""

from __future__ import annotations

from typing import Any

import orbax.checkpoint as ocp

from kubeflow_tpu.utils import faults

_FP_SAVE = faults.register_point(
    "checkpoint.save", "before a checkpoint save lands; ctx: step")
_FP_RESTORE = faults.register_point(
    "checkpoint.restore", "before a checkpoint restore; ctx: step")


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str, *, interval: int = 100,
                 keep: int = 3, async_save: bool = True):
        self.directory = str(directory)
        self.interval = interval
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=interval,
            max_to_keep=keep,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def maybe_save(self, step: int, state: Any, *, data_state: Any = None,
                   force: bool = False) -> bool:
        """Save if `step` hits the interval (orbax enforces the schedule).
        `data_state` is the input iterator's resume state (a small JSON
        dict from grain get_state()) saved alongside the TrainState so
        resume continues the exact data stream (SURVEY.md §5.4)."""
        faults.fire(_FP_SAVE, step=step)
        items = {"state": ocp.args.StandardSave(state)}
        if data_state is not None:
            items["data"] = ocp.args.JsonSave(data_state)
        return self._mgr.save(step, args=ocp.args.Composite(**items),
                              force=force)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def should_save(self, step: int) -> bool:
        """Whether `step` is on the save schedule — lets the trainer skip
        collecting iterator state on the steps that won't save."""
        return self._mgr.should_save(step)

    def _items(self, step: int) -> list:
        """Item names in a step's checkpoint. Legacy (single-item) layouts
        yield None metadata → []; real metadata errors propagate so a
        transient failure doesn't silently misroute restore()."""
        meta = self._mgr.item_metadata(step)
        if meta is None:
            return []
        return list(getattr(meta, "keys", lambda: [])())

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        """Restore into the (possibly abstract/sharded) template. Returns the
        template untouched when no checkpoint exists. Checkpoints written
        before the composite (state+data) layout restore via the legacy
        single-item path, so an upgraded runtime still resumes older jobs."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return state_template
        faults.fire(_FP_RESTORE, step=step)
        if "state" not in self._items(step):
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(state_template))
        out = self._mgr.restore(step, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(state_template)))
        return out["state"]

    def restore_data_state(self, step: int | None = None) -> Any | None:
        """The saved input-iterator state, or None when the checkpoint
        predates it (plain-generator jobs)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        try:
            has_data = "data" in self._items(step)
        except Exception:
            return None  # worst case: the trainer falls back to replay
        if not has_data:
            return None
        out = self._mgr.restore(
            step, args=ocp.args.Composite(data=ocp.args.JsonRestore()))
        return out["data"]

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
