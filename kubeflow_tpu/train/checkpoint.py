"""Checkpoint/auto-resume on orbax — first-class, unlike the reference.

Kubeflow leaves checkpointing to user code on PVCs (SURVEY.md §5.4); the
platform's only resume stories are Katib's DB resume and KFP's step cache.
Here every training job checkpoints through this manager (async, sharded,
multi-host-safe via orbax), and the JAXJob controller restarts processes
with `restore=latest` — checkpoint-restart IS the elasticity mechanism
(§5.3: world-resize in JAX means recompile, so v1 elasticity = resume).
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any

import orbax.checkpoint as ocp

from kubeflow_tpu.utils import faults, resilience

_FP_SAVE = faults.register_point(
    "checkpoint.save", "before a checkpoint save lands; ctx: step")
_FP_RESTORE = faults.register_point(
    "checkpoint.restore", "before a checkpoint restore; ctx: step")

_LOG = logging.getLogger(__name__)

#: Subdirectory (inside the checkpoint root) where corrupt step dirs are
#: moved. Non-numeric, so orbax's step scan ignores it; kept on disk (not
#: deleted) so an operator can post-mortem the torn write.
QUARANTINE_DIR = "quarantine"

#: Marker orbax puts in its in-flight save directories
#: (`<step>.orbax-checkpoint-tmp-<n>`). One left on disk at manager init
#: is torn garbage from a killed attempt.
_TMP_MARKER = ".orbax-checkpoint-tmp-"


def _sweep_stale_tmp(directory: str) -> list[str]:
    """Delete torn `*.orbax-checkpoint-tmp-*` dirs under `directory`.

    A kill mid-async-save (the elastic-downsize SIGKILL path) leaves the
    in-flight tmp dir behind; the relaunched attempt then re-saves the
    same step and the collision can abort the writer natively — no
    Python traceback, just a signal exit that the controller reads as
    yet another worker failure and answers with a second (spurious)
    downsize. At manager init no save can be in flight — the gang
    restarts as a unit — so anything matching the marker is garbage.
    Per-entry errors are swallowed: gang peers may sweep concurrently,
    and a tmp dir we cannot remove only costs what it always did."""
    swept: list[str] = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return swept
    for name in entries:
        if _TMP_MARKER not in name:
            continue
        try:
            shutil.rmtree(os.path.join(directory, name))
        except OSError:
            continue
        swept.append(name)
    return swept


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str, *, interval: int = 100,
                 keep: int = 3, async_save: bool = True):
        self.directory = str(directory)
        self.interval = interval
        self._keep = keep
        self._async_save = async_save
        swept = _sweep_stale_tmp(self.directory)
        if swept:
            resilience.metrics.inc("tpk_checkpoint_tmp_swept_total",
                                   float(len(swept)), component="train")
            _LOG.warning(
                "swept %d torn orbax tmp dir(s) under %s: %s",
                len(swept), self.directory, ", ".join(sorted(swept)))
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=interval,
            max_to_keep=keep,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def maybe_save(self, step: int, state: Any, *, data_state: Any = None,
                   force: bool = False) -> bool:
        """Save if `step` hits the interval (orbax enforces the schedule).
        `data_state` is the input iterator's resume state (a small JSON
        dict from grain get_state()) saved alongside the TrainState so
        resume continues the exact data stream (SURVEY.md §5.4)."""
        faults.fire(_FP_SAVE, step=step)
        items = {"state": ocp.args.StandardSave(state)}
        if data_state is not None:
            items["data"] = ocp.args.JsonSave(data_state)
        return self._mgr.save(step, args=ocp.args.Composite(**items),
                              force=force)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def should_save(self, step: int) -> bool:
        """Whether `step` is on the save schedule — lets the trainer skip
        collecting iterator state on the steps that won't save."""
        return self._mgr.should_save(step)

    def _items(self, step: int) -> list:
        """Item names in a step's checkpoint. Legacy (single-item) layouts
        yield None metadata → []; real metadata errors propagate so a
        transient failure doesn't silently misroute restore()."""
        meta = self._mgr.item_metadata(step)
        if meta is None:
            return []
        return list(getattr(meta, "keys", lambda: [])())

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        """Restore into the (possibly abstract/sharded) template. Returns the
        template untouched when no checkpoint exists. Checkpoints written
        before the composite (state+data) layout restore via the legacy
        single-item path, so an upgraded runtime still resumes older jobs."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return state_template
        faults.fire(_FP_RESTORE, step=step)
        if "state" not in self._items(step):
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(state_template))
        out = self._mgr.restore(step, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(state_template)))
        return out["state"]

    def quarantine_step(self, step: int) -> str | None:
        """Move `step`'s directory into `<root>/quarantine/` so the next
        latest_step() skips it — a partial orbax write (SIGKILL mid-save,
        torn disk) must cost one checkpoint interval, not wedge every
        restart on the same poisoned restore. Returns the new path."""
        src = os.path.join(self.directory, str(step))
        if not os.path.isdir(src):
            return None
        qdir = os.path.join(self.directory, QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, str(step))
        n = 1
        while os.path.exists(dst):
            dst = os.path.join(qdir, f"{step}.{n}")
            n += 1
        os.rename(src, dst)
        resilience.metrics.inc("tpk_checkpoint_quarantined_total",
                               component="train")
        # Refresh the manager's cached step list; older orbax without
        # reload() gets a rebuilt manager (same options).
        try:
            self._mgr.reload()
        except AttributeError:
            self._mgr.close()
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    save_interval_steps=self.interval,
                    max_to_keep=self._keep,
                    enable_async_checkpointing=self._async_save,
                ))
        return dst

    def restore_latest_good(self, state_template: Any
                            ) -> tuple[Any, int | None, list[int]]:
        """Restore the newest step that actually restores, quarantining
        any that raise (partial write, bad metadata) and falling back to
        the next-newest — so a torn checkpoint costs one interval of
        recompute instead of burning the whole backoff budget on a
        permanently poisoned restore. Returns (state, step, quarantined);
        (template, None, [...]) when nothing restorable remains.

        Elastic-resize contract: steps on disk may have been written by
        a DIFFERENT fsdp topology — orbax saves logical arrays and
        restores into whatever shardings `state_template` carries, so
        the template's (current) mesh governs and the fallback chain is
        topology-agnostic. A SIGKILL mid-save of the first post-resize
        checkpoint therefore quarantines that torn step and lands on the
        last good PRE-resize step, resharding it on the way in
        (tests/test_faults.py pins the crash-during-resize case)."""
        quarantined: list[int] = []
        while True:
            step = self.latest_step()
            if step is None:
                return state_template, None, quarantined
            try:
                return self.restore(state_template, step), step, quarantined
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                resilience.metrics.inc("tpk_checkpoint_fallback_total",
                                       component="train")
                dst = self.quarantine_step(step)
                if self.latest_step() == step:
                    # Quarantine didn't remove the step from the scan
                    # (non-local storage, unexpected step-dir layout):
                    # surfacing the restore error beats looping on the
                    # same poisoned step forever.
                    raise RuntimeError(
                        f"checkpoint step {step} failed to restore and "
                        f"could not be quarantined under "
                        f"{self.directory}") from e
                quarantined.append(int(step))
                _LOG.warning(
                    "checkpoint step %s failed to restore (%s: %s); "
                    "quarantined to %s, falling back to the next-newest "
                    "step", step, type(e).__name__, e, dst)

    def restore_data_state(self, step: int | None = None) -> Any | None:
        """The saved input-iterator state, or None when the checkpoint
        predates it (plain-generator jobs) or the item is unreadable
        (the trainer then falls back to replaying the stream)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        try:
            has_data = "data" in self._items(step)
        except Exception:
            return None  # worst case: the trainer falls back to replay
        if not has_data:
            return None
        try:
            out = self._mgr.restore(
                step, args=ocp.args.Composite(data=ocp.args.JsonRestore()))
        except Exception:
            # A torn `data` item must not kill a resume whose TrainState
            # already restored — replaying the stream is the safe floor.
            return None
        return out["data"]

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
