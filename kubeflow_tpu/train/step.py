"""Train-step factory: one jitted SPMD step on a named mesh.

Replaces the reference's 🔥 in-container DDP/NCCL step loop (SURVEY.md §3.1:
`torchrun → DDP fwd/bwd/allreduce`) with a single `jit`-compiled function —
gradient collectives are emitted by XLA from sharding annotations rather than
invoked via NCCL, and the whole step (fwd+bwd+optimizer) fuses into one
executable with donated buffers.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict

from kubeflow_tpu.parallel.sharding import Rules, DEFAULT_RULES


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: jax.Array | None = None) -> jax.Array:
    """Mean token-level cross entropy in fp32. logits [..., V], targets [...]"""
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def chunked_cross_entropy(hidden: jax.Array, head: jax.Array,
                          targets: jax.Array,
                          mask: jax.Array | None = None, *,
                          chunk: int = 1024,
                          head_is_vocab_major: bool = False,
                          final_softcap: float = 0.0) -> jax.Array:
    """Fused blockwise cross entropy (ops/ROADMAP.md item 1): logits are
    computed per token-chunk against the unembedding and never
    materialized as the [B·S, V] fp32 buffer that dominates peak memory at
    the bench point (PROFILE.md §3). `jax.checkpoint` on the chunk body
    makes the backward recompute each chunk's logits — FLOPs traded for
    the logits buffer, the same deal as flash attention.

    hidden [B,S,D]; head [D,V] (lm_head kernel) or [V,D] with
    `head_is_vocab_major` (tied embedding); targets [B,S].
    `final_softcap` applies Gemma-2's logit cap tanh(l/cap)*cap inside
    each chunk — the return_hidden path skips the model's own cap, so
    omitting it here would train against uncapped logits.
    """
    b, s, d = hidden.shape
    n = b * s
    h = hidden.reshape(n, d)
    t = targets.reshape(n)
    m = (jnp.ones((n,), jnp.float32) if mask is None
         else mask.reshape(n).astype(jnp.float32))
    chunk = min(chunk, n)
    pad = (-n) % chunk
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        t = jnp.pad(t, (0, pad))
        m = jnp.pad(m, (0, pad))  # padded rows carry mask 0
    nblk = (n + pad) // chunk
    hb = h.reshape(nblk, chunk, d)
    tb = t.reshape(nblk, chunk)
    mb = m.reshape(nblk, chunk)

    spec = "cd,vd->cv" if head_is_vocab_major else "cd,dv->cv"

    def block(carry, xs):
        hx, tx, mx = xs
        logits = jnp.einsum(spec, hx, head.astype(hx.dtype)).astype(
            jnp.float32)
        if final_softcap:
            logits = jnp.tanh(logits / final_softcap) * final_softcap
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tx[:, None], axis=-1)[:, 0]
        tot, cnt = carry
        return (tot + jnp.sum((logz - gold) * mx), cnt + jnp.sum(mx)), None

    (tot, cnt), _ = jax.lax.scan(
        jax.checkpoint(block), (jnp.zeros((), jnp.float32),
                                jnp.zeros((), jnp.float32)), (hb, tb, mb))
    return tot / jnp.maximum(cnt, 1.0)


def _unembed_head(params: Any) -> tuple[jax.Array, bool]:
    """(head weights, vocab_major) for the chunked-CE path: the lm_head
    kernel [D,V], or the tied embedding [V,D]."""
    if "lm_head" in params:
        return params["lm_head"]["kernel"], False
    if "embed" in params:
        return params["embed"], True
    raise ValueError(
        "chunked loss needs an 'lm_head' or tied 'embed' param "
        f"(have {sorted(params)})")


def abstract_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    example_inputs: tuple,
    mesh: jax.sharding.Mesh,
    rules: Rules = DEFAULT_RULES,
    example_kwargs: dict | None = None,
    trainable: str | None = None,
    fsdp=None,
):
    """(init_fn, abstract_state, shardings): the sharding-layout derivation
    shared by real initialization (init_train_state) and AOT scale proofs
    (utils/scaleproof.py) — eval_shape the init, map flax logical metadata
    through the rules to NamedShardings. `abstract_state` is unboxed
    ShapeDtypeStructs; `shardings` is the matching NamedSharding tree.
    Callers must be inside `with mesh, nn.logical_axis_rules(rules)` when
    tracing `init_fn`.

    `fsdp` (a parallel/fsdp.FSDP plan) rewrites the STATE shardings to the
    ZeRO-style master layout — every param/moment leaf gains the fsdp
    axis — and records the (compute, master) layout pair on the plan for
    make_train_step's gather-for-compute."""
    example_kwargs = example_kwargs or {}

    def _init(rng):
        variables = model.init(rng, *example_inputs, **example_kwargs)
        params = variables["params"]
        opt_target = params
        if trainable == "lora":
            # LoRA memory win: optimizer state covers ONLY the adapter
            # leaves (fp32 Adam moments for the frozen base would
            # dominate the budget, defeating the point).
            from kubeflow_tpu.train.lora import partition

            opt_target, _ = partition(dict(params))
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=tx.init(opt_target), tx=tx)

    with mesh, nn.logical_axis_rules(rules):
        abstract = jax.eval_shape(_init, jax.random.key(0))
        logical_specs = nn.get_partition_spec(abstract)
        shardings = nn.logical_to_mesh_sharding(logical_specs, mesh, rules)
    abstract = nn.meta.unbox(abstract)
    if fsdp is not None:
        if trainable == "lora":
            raise ValueError(
                "fsdp master sharding doesn't compose with trainable="
                "'lora' (the adapter-only optimizer state is the memory "
                "win there)")
        fsdp.prepare(abstract.params, shardings.params)
        shardings = fsdp.master_state_shardings(abstract, shardings)
    return _init, abstract, shardings


def init_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    example_inputs: tuple,
    mesh: jax.sharding.Mesh,
    rules: Rules = DEFAULT_RULES,
    example_kwargs: dict | None = None,
    trainable: str | None = None,
    fsdp=None,
) -> TrainState:
    """Initialize params already laid out per the sharding rules: we eval_shape
    the init, derive NamedShardings from logical metadata, then run the real
    init jitted with those out_shardings — params are born sharded, never
    materialized replicated (essential at 8B scale).

    `example_kwargs` rides into model.init for impls whose trace needs the
    full call contract (e.g. zigzag attention requires explicit positions).
    `trainable="lora"` restricts the optimizer state to adapter leaves.
    `fsdp` (parallel/fsdp.FSDP) births the state in the ZeRO-style master
    layout — fp32 params + Adam moments sharded over the fsdp axis."""
    _init, _, shardings = abstract_train_state(
        model, tx, example_inputs, mesh, rules, example_kwargs, trainable,
        fsdp=fsdp)
    # Partitionable threefry for the init trace: the legacy generator's
    # bits depend on how XLA partitions the RNG op, so born-sharded
    # params would differ BY LAYOUT — fsdp=K could never equal fsdp=1,
    # and a topology change would be a silent reseed. Value-semantics
    # threefry makes init a function of (key, shape) alone; restored to
    # the ambient setting right after (serving RNG is untouched).
    old_threefry = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        with mesh, nn.logical_axis_rules(rules):
            state = jax.jit(_init, out_shardings=shardings)(rng)
            # Unbox flax logical-partitioning metadata for downstream use.
            return nn.meta.unbox(state)
    finally:
        jax.config.update("jax_threefry_partitionable", old_threefry)


def make_train_step(
    model: nn.Module,
    mesh: jax.sharding.Mesh,
    rules: Rules = DEFAULT_RULES,
    loss_fn: Callable | None = None,
    model_kwargs: dict | None = None,
    loss_impl: str = "full",
    loss_chunk: int = 1024,
    pipeline: dict | None = None,
    accum_steps: int = 1,
    trainable: str | None = None,
    fsdp=None,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Build the jitted train step for a causal-LM-style batch:
      batch = {"inputs": [B,S] int32, "targets": [B,S] int32,
               "mask": optional [B,S]}
    Returns (new_state, metrics) with donated state.

    loss_impl="chunked" computes cross entropy blockwise against the
    unembedding (model must support return_hidden) — the [B·S, V] fp32
    logits buffer never materializes; backward recomputes per chunk.

    pipeline={"microbatches": M, "chunks": C}: run the trunk through the
    compiled pipeline schedule over the `pipe` mesh axis
    (models/llama_pp.py) instead of model.apply — params stay in the
    scanned-Llama layout (leading `layers` dim, sharded over `pipe` by the
    "pipeline" rules); GPipe when C == 1, interleaved circular otherwise.

    accum_steps > 1 scans the loss+grad over accum_steps row-slices of the
    batch, averaging grads before the (single) optimizer update — identical
    optimizer math to the full batch at 1/accum_steps the activation
    memory (the reference SDK's gradient_accumulation_steps). The
    accumulator carries the master dtype (fp32) and the scan adds in
    microbatch order — deterministic, so K x (B/K) reproduces 1 x B.

    fsdp (a prepared parallel/fsdp.FSDP plan): the state holds fp32
    master shards; each (micro)batch's forward starts from
    fsdp.gather_params — cast to the compute dtype, then all-gather into
    the rules-derived compute layout, both inside the jitted step so XLA
    overlaps the gathers with compute — and grads flow back through the
    same pair as master-layout fp32 reduce(-scatter)s."""
    model_kwargs = model_kwargs or {}
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if loss_impl not in ("full", "chunked"):
        raise ValueError(f"loss_impl {loss_impl!r}: full | chunked")
    if loss_impl == "chunked" and loss_fn is not None:
        raise ValueError("loss_impl='chunked' implies the built-in LM loss")
    if loss_chunk < 1:
        raise ValueError(f"loss_chunk must be >= 1, got {loss_chunk}")
    if pipeline is not None:
        if mesh.shape["pipe"] < 2:
            raise ValueError(
                "pipeline train step needs a mesh with pipe >= 2 "
                f"(got {mesh.shape['pipe']})")
        if not hasattr(model, "cfg") or not getattr(
                model.cfg, "scan_layers", False):
            raise ValueError(
                "pipeline parallelism needs the scanned Llama-family "
                "model (params with a leading 'layers' dim)")
        if loss_fn is not None:
            raise ValueError("pipeline implies the built-in LM loss")
        if model_kwargs.get("ring_axis") is not None:
            raise ValueError(
                "pipeline parallelism doesn't take ring_axis — pass "
                "pipeline={'seq_axis': ...} for context parallelism "
                "inside the pipeline")
        static_packed = {"segment_ids", "positions"} & set(model_kwargs)
        if any(model_kwargs.get(k) is not None for k in static_packed):
            # The pipeline path reads packed metadata from the BATCH
            # (pipeline_loss); silently ignoring static model_kwargs here
            # would train with arange positions and no document masking.
            raise ValueError(
                f"pipeline parallelism takes {static_packed} from the "
                "batch (packed_lm loader), not from model_kwargs")

    def pipeline_loss(params, batch):
        from kubeflow_tpu.models.llama_pp import pipeline_forward

        hidden = loss_impl == "chunked"
        # Packed batches (data/loader.py) carry per-document restarting
        # positions + segment ids; they travel the pipeline ring with the
        # activations so stage attention masks within documents.
        out = pipeline_forward(
            model.cfg, params, batch["inputs"], mesh=mesh,
            num_microbatches=int(pipeline["microbatches"]),
            num_chunks=int(pipeline.get("chunks", 1)),
            return_hidden=hidden,
            positions=batch.get("positions"),
            segment_ids=batch.get("segment_ids"),
            seq_axis=pipeline.get("seq_axis"))
        aux = jnp.zeros((), jnp.float32)
        if isinstance(out, tuple):
            # MoE-PP: the Switch load-balance aux rides out of the
            # pipeline (per-microbatch statistic, see pipeline_forward).
            out, raw_aux = out
            aux = model.cfg.router_aux_coef * raw_aux
        if hidden:
            head, vocab_major = _unembed_head(params)
            main = chunked_cross_entropy(
                out, head, batch["targets"], batch.get("mask"),
                chunk=loss_chunk, head_is_vocab_major=vocab_major,
                final_softcap=getattr(model.cfg, "final_softcap", 0.0))
        else:
            main = cross_entropy_loss(out, batch["targets"],
                                      batch.get("mask"))
        return main + aux, aux

    def compute_loss(params, batch):
        # mutable=["aux_loss"]: MoE routers sow load-balance penalties there
        # (models/moe.py); dense models leave it empty.
        kwargs = dict(model_kwargs)
        # Packed-sequence batches carry their own segment ids and
        # per-segment restarting positions (models honor both; the fused
        # kernel masks across segment boundaries).
        if "segment_ids" in batch:
            kwargs["segment_ids"] = batch["segment_ids"]
        if "positions" in batch:
            kwargs["positions"] = batch["positions"]
        if loss_impl == "chunked":
            kwargs["return_hidden"] = True
        out, mutated = model.apply(
            {"params": params}, batch["inputs"], mutable=["aux_loss"],
            **kwargs)
        if loss_impl == "chunked":
            head, vocab_major = _unembed_head(params)
            main = chunked_cross_entropy(
                out, head, batch["targets"], batch.get("mask"),
                chunk=loss_chunk, head_is_vocab_major=vocab_major,
                final_softcap=getattr(model.cfg, "final_softcap", 0.0))
        else:
            logits = out
            if isinstance(logits, tuple):  # models returning (hidden, logits)
                logits = logits[-1]
            if loss_fn is not None:
                main = loss_fn(logits, batch)
            else:
                main = cross_entropy_loss(logits, batch["targets"],
                                          batch.get("mask"))
        aux = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
            aux = aux + jnp.sum(leaf)
        return main + aux, aux

    def constrain_batch(x):
        # dim 0 is always the batch; dim 1 is the sequence only for
        # token-like integer arrays — float features (e.g. MLP inputs
        # [B, 784]) must not be sharded over the seq axis.
        axes: tuple = ("batch",)
        if x.ndim >= 2 and jnp.issubdtype(x.dtype, jnp.integer):
            axes = ("batch", "act_seq")
        return nn.with_logical_constraint(x, axes + (None,) * (x.ndim - len(axes)))

    loss_impl_fn = pipeline_loss if pipeline is not None else compute_loss
    if trainable not in (None, "lora"):
        raise ValueError(f"trainable {trainable!r}: None | 'lora'")
    if trainable == "lora" and pipeline is not None:
        raise ValueError(
            "LoRA doesn't compose with pipeline parallelism (the stage "
            "forward has no adapter path)")
    if fsdp is not None:
        if pipeline is not None:
            raise ValueError(
                "fsdp master sharding doesn't compose with pipeline "
                "parallelism (stage params keep the scanned pipe layout)")
        if trainable == "lora":
            raise ValueError(
                "fsdp master sharding doesn't compose with trainable="
                "'lora' (the adapter-only optimizer state is the memory "
                "win there)")
        fsdp._require_prepared()
        inner_loss_fn = loss_impl_fn

        def loss_impl_fn(master, b):  # noqa: F811 — deliberate rebind
            return inner_loss_fn(fsdp.gather_params(master), b)

    def loss_and_grads(loss_fn, target, batch):
        """(loss, aux, grads) w.r.t. `target`, with the gradient-
        accumulation scan when accum_steps > 1 — ONE copy of the
        microbatching machinery shared by full fine-tune and LoRA."""
        if accum_steps > 1:
            # Scan over row-slices; the grad carry costs one extra
            # target-sized buffer.
            def split(x):
                if x.shape[0] % accum_steps:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"accum_steps {accum_steps}")
                return x.reshape(
                    (accum_steps, x.shape[0] // accum_steps) + x.shape[1:])

            micro = jax.tree.map(split, batch)

            def body(carry, mb):
                mb = jax.tree.map(constrain_batch, mb)
                (mloss, maux), mgrads = jax.value_and_grad(
                    loss_fn, has_aux=True)(target, mb)
                if fsdp is not None:
                    # Keep every partial grad — and therefore the fp32
                    # accumulator carry — in the sharded master layout;
                    # a replicated grad tree would undo the state's
                    # memory win for the duration of the scan.
                    mgrads = fsdp.constrain_master_grads(mgrads)
                gsum, lsum, asum = carry
                return (jax.tree.map(jnp.add, gsum, mgrads), lsum + mloss,
                        asum + maux), None

            zeros = jax.tree.map(jnp.zeros_like, target)
            (gsum, lsum, asum), _ = jax.lax.scan(
                body, (zeros, jnp.zeros((), jnp.float32),
                       jnp.zeros((), jnp.float32)), micro)
            grads = jax.tree.map(lambda g: g / accum_steps, gsum)
            return lsum / accum_steps, asum / accum_steps, grads
        batch = jax.tree.map(constrain_batch, batch)
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(target, batch)
        if fsdp is not None:
            grads = fsdp.constrain_master_grads(grads)
        return loss, aux, grads

    def lora_step(state: TrainState, batch: dict):
        """Differentiate and update ONLY the adapter leaves: grads and
        optimizer state are adapter-sized (the frozen base never gets a
        grad buffer or Adam moments — the LoRA memory win)."""
        from kubeflow_tpu.train.lora import combine, partition

        train_sub, frozen = partition(dict(state.params))

        def sub_loss(tr, b):
            return loss_impl_fn(combine(tr, frozen), b)

        loss, aux, grads = loss_and_grads(sub_loss, train_sub, batch)
        updates, new_opt = state.tx.update(grads, state.opt_state,
                                           train_sub)
        new_train = optax.apply_updates(train_sub, updates)
        new_state = state.replace(
            step=state.step + 1, params=combine(new_train, frozen),
            opt_state=new_opt)
        return new_state, {"loss": loss, "aux_loss": aux,
                           "grad_norm": optax.global_norm(grads),
                           "step": new_state.step}

    def step(state: TrainState, batch: dict):
        loss, aux, grads = loss_and_grads(loss_impl_fn, state.params, batch)
        new_state = state.apply_gradients(grads)
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "aux_loss": aux,
                           "grad_norm": gnorm, "step": new_state.step}

    jitted = jax.jit(lora_step if trainable == "lora" else step,
                     donate_argnums=(0,))

    def wrapped(state, batch):
        # Tracing happens on first call, under the mesh + logical-rules
        # contexts so constraints resolve; later calls hit the jit cache.
        with mesh, nn.logical_axis_rules(rules):
            return jitted(state, batch)

    wrapped.jitted = jitted
    return wrapped


def make_eval_step(model: nn.Module, mesh: jax.sharding.Mesh,
                   rules: Rules = DEFAULT_RULES,
                   model_kwargs: dict | None = None):
    model_kwargs = model_kwargs or {}

    def step(params, batch):
        logits = model.apply({"params": params}, batch["inputs"], **model_kwargs)
        if isinstance(logits, tuple):
            logits = logits[-1]
        mask = batch.get("mask")
        loss = cross_entropy_loss(logits, batch["targets"], mask)
        hits = (jnp.argmax(logits, -1) == batch["targets"]).astype(jnp.float32)
        if mask is not None:
            m = mask.astype(jnp.float32)
            acc = jnp.sum(hits * m) / jnp.maximum(jnp.sum(m), 1.0)
        else:
            acc = jnp.mean(hits)
        return {"loss": loss, "accuracy": acc}

    jitted = jax.jit(step)

    def wrapped(params, batch):
        with mesh, nn.logical_axis_rules(rules):
            return jitted(params, batch)

    return wrapped
