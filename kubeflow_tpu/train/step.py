"""Train-step factory: one jitted SPMD step on a named mesh.

Replaces the reference's 🔥 in-container DDP/NCCL step loop (SURVEY.md §3.1:
`torchrun → DDP fwd/bwd/allreduce`) with a single `jit`-compiled function —
gradient collectives are emitted by XLA from sharding annotations rather than
invoked via NCCL, and the whole step (fwd+bwd+optimizer) fuses into one
executable with donated buffers.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict

from kubeflow_tpu.parallel.sharding import Rules, DEFAULT_RULES


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: jax.Array | None = None) -> jax.Array:
    """Mean token-level cross entropy in fp32. logits [..., V], targets [...]"""
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def init_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    example_inputs: tuple,
    mesh: jax.sharding.Mesh,
    rules: Rules = DEFAULT_RULES,
) -> TrainState:
    """Initialize params already laid out per the sharding rules: we eval_shape
    the init, derive NamedShardings from logical metadata, then run the real
    init jitted with those out_shardings — params are born sharded, never
    materialized replicated (essential at 8B scale)."""

    def _init(rng):
        variables = model.init(rng, *example_inputs)
        params = variables["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=tx.init(params), tx=tx)

    with mesh, nn.logical_axis_rules(rules):
        abstract = jax.eval_shape(_init, rng)
        logical_specs = nn.get_partition_spec(abstract)
        shardings = nn.logical_to_mesh_sharding(logical_specs, mesh, rules)
        state = jax.jit(_init, out_shardings=shardings)(rng)
        # Unbox flax logical-partitioning metadata for downstream use.
        return nn.meta.unbox(state)


def make_train_step(
    model: nn.Module,
    mesh: jax.sharding.Mesh,
    rules: Rules = DEFAULT_RULES,
    loss_fn: Callable | None = None,
    model_kwargs: dict | None = None,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Build the jitted train step for a causal-LM-style batch:
      batch = {"inputs": [B,S] int32, "targets": [B,S] int32,
               "mask": optional [B,S]}
    Returns (new_state, metrics) with donated state."""
    model_kwargs = model_kwargs or {}

    def compute_loss(params, batch):
        # mutable=["aux_loss"]: MoE routers sow load-balance penalties there
        # (models/moe.py); dense models leave it empty.
        logits, mutated = model.apply(
            {"params": params}, batch["inputs"], mutable=["aux_loss"],
            **model_kwargs)
        if isinstance(logits, tuple):  # models returning (hidden, logits)
            logits = logits[-1]
        if loss_fn is not None:
            main = loss_fn(logits, batch)
        else:
            main = cross_entropy_loss(logits, batch["targets"],
                                      batch.get("mask"))
        aux = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
            aux = aux + jnp.sum(leaf)
        return main + aux, aux

    def constrain_batch(x):
        # dim 0 is always the batch; dim 1 is the sequence only for
        # token-like integer arrays — float features (e.g. MLP inputs
        # [B, 784]) must not be sharded over the seq axis.
        axes: tuple = ("batch",)
        if x.ndim >= 2 and jnp.issubdtype(x.dtype, jnp.integer):
            axes = ("batch", "act_seq")
        return nn.with_logical_constraint(x, axes + (None,) * (x.ndim - len(axes)))

    def step(state: TrainState, batch: dict):
        batch = jax.tree.map(constrain_batch, batch)
        (loss, aux), grads = jax.value_and_grad(compute_loss, has_aux=True)(
            state.params, batch)
        new_state = state.apply_gradients(grads)
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "aux_loss": aux,
                           "grad_norm": gnorm, "step": new_state.step}

    jitted = jax.jit(step, donate_argnums=(0,))

    def wrapped(state, batch):
        # Tracing happens on first call, under the mesh + logical-rules
        # contexts so constraints resolve; later calls hit the jit cache.
        with mesh, nn.logical_axis_rules(rules):
            return jitted(state, batch)

    wrapped.jitted = jitted
    return wrapped


def make_eval_step(model: nn.Module, mesh: jax.sharding.Mesh,
                   rules: Rules = DEFAULT_RULES,
                   model_kwargs: dict | None = None):
    model_kwargs = model_kwargs or {}

    def step(params, batch):
        logits = model.apply({"params": params}, batch["inputs"], **model_kwargs)
        if isinstance(logits, tuple):
            logits = logits[-1]
        mask = batch.get("mask")
        loss = cross_entropy_loss(logits, batch["targets"], mask)
        hits = (jnp.argmax(logits, -1) == batch["targets"]).astype(jnp.float32)
        if mask is not None:
            m = mask.astype(jnp.float32)
            acc = jnp.sum(hits * m) / jnp.maximum(jnp.sum(m), 1.0)
        else:
            acc = jnp.mean(hits)
        return {"loss": loss, "accuracy": acc}

    jitted = jax.jit(step)

    def wrapped(params, batch):
        with mesh, nn.logical_axis_rules(rules):
            return jitted(params, batch)

    return wrapped
