"""The training runtime: what a JAXJob worker process actually runs.

The reference's equivalent is user-image code launched by torchrun with env
injected by the operator (SURVEY.md §3.1) — the platform owns nothing inside
the pod. Here the runtime is first-class: mesh + sharding rules from the job
spec, jitted SPMD step, metrics/MFU stream, orbax checkpoint/auto-resume,
and an optional `jax.profiler` trace window (§5.1).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kubeflow_tpu.comms.bootstrap import ProcessEnv, initialize, read_env
from kubeflow_tpu.data.prefetch import Prefetcher
from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
from kubeflow_tpu.parallel.sharding import rules_for
from kubeflow_tpu.train.checkpoint import CheckpointManager
from kubeflow_tpu.train.metrics import MetricsLogger, StepTimer
from kubeflow_tpu.train.step import init_train_state, make_train_step
from kubeflow_tpu.utils import faults, obs, resilience

#: Fires at the top of every training step (ctx: step) — arming FailN
#: with match={"step": K} is the in-process analog of the controller's
#: TPK_FAULT step-precise process kill.
_FP_STEP = faults.register_point(
    "train.step", "top of each training step; ctx: step")


@dataclasses.dataclass
class TrainJobSpec:
    """Declarative training job — the in-process analog of a JAXJob CR's
    `spec.runtime` section. Controllers serialize this as JSON."""

    model: str = "llama_tiny"
    model_kwargs: dict = dataclasses.field(default_factory=dict)
    dataset: str = "synthetic_lm"
    dataset_kwargs: dict = dataclasses.field(default_factory=dict)
    strategy: str = "hybrid"  # preset name resolved by rules_for()
    mesh: dict = dataclasses.field(default_factory=dict)  # MeshConfig fields
    steps: int = 100
    batch_size: int = 8
    seq_len: int = 64
    learning_rate: float = 1e-3
    warmup_steps: int = 0
    weight_decay: float = 0.0
    # Peak-LR decay after warmup: "constant" | "cosine" | "linear", decaying
    # to lr_final over the remaining spec.steps (the reference SDK's HF
    # trainer exposes the same three families).
    lr_schedule: str = "constant"
    lr_final: float = 0.0
    # 0 disables clipping; > 0 wires optax.clip_by_global_norm ahead of
    # adamw (the reported grad_norm metric stays pre-clip).
    max_grad_norm: float = 0.0
    # > 1 splits each global batch into accum_steps microbatches scanned
    # inside the jitted step, averaging grads — same optimizer math at
    # 1/accum_steps the activation memory.
    accum_steps: int = 1
    # Canonical name for the same knob (the reference SDK's
    # gradient_accumulation_steps): 0 defers to accum_steps; setting both
    # to different values is refused. fp32 accumulator, ordered adds —
    # grad_accum=K on batch B reproduces K=1 on batch B (test-pinned).
    grad_accum: int = 0
    # FSDP master-state sharding (parallel/fsdp.py): 0 = off (today's
    # rules-only layout); N >= 1 shards fp32 master params + both Adam
    # moments N-way over the `fsdp` mesh axis on every state leaf,
    # filling mesh.fsdp = N when the mesh doesn't set it. Checkpoints
    # stay topology-portable: save on N-way, restore on M-way.
    fsdp: int = 0
    # Compute dtype of the gathered per-use param copies when fsdp >= 1:
    # null keeps the master dtype (bit-exact escape hatch); "bfloat16"
    # halves all-gather bytes and compute-copy memory. The master state
    # and the grad accumulator stay fp32 either way.
    param_dtype: str | None = None
    seed: int = 0
    # False | True/"ring" (contiguous ring CP) | "ring_flash" (fused Pallas
    # inner block) | "zigzag"/"zigzag_flash" (balanced causal schedule: the
    # trainer permutes batches + positions to match; _flash = fused inner).
    ring_attention: bool | str = False
    # "full" materializes [B,S,V] logits; "chunked" is the fused blockwise
    # CE (no logits buffer — the long-context/large-vocab memory saver).
    loss_impl: str = "full"
    loss_chunk: int = 1024
    # Pipeline parallelism: set mesh.pipe >= 2 and optionally
    # {"microbatches": M (default: pipe), "chunks": C (default 1; >1 runs
    # the interleaved circular schedule)}. The trunk runs the compiled
    # GPipe/circular schedule (models/llama_pp.py); params keep the
    # scanned layout, sharded over `pipe` via the "pipeline" rules.
    pipeline: dict = dataclasses.field(default_factory=dict)
    # LoRA fine-tuning (the reference SDK's PEFT LoraConfig):
    # {"rank": r, "alpha": a (default 16), "targets": "attn"|"attn_mlp"}.
    # Adapters are trained, the base is frozen (no base grads or optimizer
    # state); merge for serving via train/lora.py merge().
    lora: dict = dataclasses.field(default_factory=dict)
    checkpoint: dict = dataclasses.field(default_factory=dict)
    # {"dir": str, "interval": int, "keep": int}
    # In-process supervision (training-operator restartPolicy/backoffLimit
    # semantics, SURVEY.md §3.2): "Never" propagates the first failure;
    # "OnFailure" restarts immediately; "ExponentialBackoff" restarts
    # with jittered exponential delays. Each restart re-enters the run
    # loop through the checkpoint auto-resume path (latest step + saved
    # data-iterator state), so a mid-run failure costs at most one
    # checkpoint interval of recompute. backoff_limit counts RESTARTS:
    # the (backoff_limit+1)-th failure raises BackoffLimitExceeded.
    restart_policy: str = "Never"
    backoff_limit: int = 3
    # Async input pipeline depth: the trainer stages up to `prefetch`
    # device-resident batches ahead of compute on a background thread
    # (pull + zigzag permute + H2D placement all off the critical path —
    # data/prefetch.py). 0 = fully synchronous; every depth trains the
    # identical batch sequence with identical numerics, and checkpoints
    # under prefetch save the state of the batch actually trained, not
    # the read-ahead position.
    prefetch: int = 2
    metrics_path: str | None = None
    profile: dict = dataclasses.field(default_factory=dict)
    # {"dir": str, "start_step": int, "num_steps": int}
    # Flat jax.profiler window keyed off the job spec (SURVEY.md §5.1
    # rebuild item): steps [profile_start_step, profile_stop_step) run
    # under jax.profiler.start_trace/stop_trace, writing to
    # $TPK_WORKDIR/profile (the job's workdir under the control plane)
    # — or next to metrics_path, or ./tpk-profile — unless profile.dir
    # overrides. stop <= start disables (the default). The dict-style
    # `profile` knob wins when both are set.
    profile_start_step: int = 0
    profile_stop_step: int = 0
    log_every: int = 10
    # In-run validation stream: every eval_every steps (0 = off), run
    # eval_batches batches of eval_dataset (default: the train dataset with
    # a disjoint seed) through make_eval_step and log eval_loss/accuracy.
    eval_dataset: str | None = None
    eval_dataset_kwargs: dict = dataclasses.field(default_factory=dict)
    eval_every: int = 0
    eval_batches: int = 8

    @classmethod
    def from_json(cls, text: str) -> "TrainJobSpec":
        data = json.loads(text)
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - fields
        if unknown:
            raise ValueError(f"unknown TrainJobSpec fields: {sorted(unknown)}")
        return cls(**data)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


class Trainer:
    def __init__(self, spec: TrainJobSpec, penv: ProcessEnv | None = None):
        self.spec = spec
        self.penv = penv or read_env()
        initialize(self.penv)

        from kubeflow_tpu.utils import registry

        valid_ring = (False, True, "ring", "ring_flash", "zigzag",
                      "zigzag_flash")
        if spec.ring_attention not in valid_ring:
            raise ValueError(
                f"ring_attention {spec.ring_attention!r}: one of "
                f"{valid_ring}")
        model_kwargs = dict(spec.model_kwargs)
        if spec.ring_attention in ("zigzag", "zigzag_flash", "ring_flash"):
            # Keep the kernel and the data contract in lockstep: the spec
            # is the single switch, the model impl follows. Derived locally
            # — the caller's spec must stay as submitted (it gets
            # re-serialized for resume/retry).
            model_kwargs["attention_impl"] = spec.ring_attention
        mesh_fields = dict(spec.mesh)
        mesh_fields.setdefault("num_slices", self.penv.num_slices)
        if spec.fsdp < 0:
            raise ValueError(f"fsdp must be >= 0, got {spec.fsdp}")
        if spec.fsdp:
            declared = mesh_fields.get("fsdp")
            if declared not in (None, spec.fsdp):
                raise ValueError(
                    f"spec.fsdp={spec.fsdp} conflicts with "
                    f"mesh.fsdp={declared} — set one (fsdp is the "
                    "shorthand that fills the mesh axis)")
            mesh_fields["fsdp"] = spec.fsdp
        from kubeflow_tpu.parallel.fsdp import parse_compute_dtype

        if spec.param_dtype is not None and not spec.fsdp:
            raise ValueError(
                "param_dtype configures the fsdp runtime's gathered "
                "compute copies — set fsdp >= 1 (fsdp=1 is the "
                "single-shard escape hatch)")
        self._fsdp_dtype = parse_compute_dtype(spec.param_dtype)
        self.mesh = build_mesh(MeshConfig(**mesh_fields))
        strategy = spec.strategy
        if self.mesh.shape["pipe"] > 1:
            # pipe in the mesh IS the pipeline switch; the rules must put
            # the scanned `layers` dim on `pipe` or init would replicate
            # the trunk over the pipeline stages.
            if strategy == "hybrid":
                strategy = "pipeline"
            elif strategy != "pipeline":
                raise ValueError(
                    f"mesh.pipe={self.mesh.shape['pipe']} needs strategy "
                    f"'pipeline' (or the default), not {strategy!r}")
            if spec.ring_attention:
                # With PP, mesh.seq IS the CP switch (CP-inside-PP rides
                # the pipeline shard_map region); the scanned-model
                # ring_attention spec knob is the wrong mechanism.
                raise ValueError(
                    "pipeline parallelism doesn't take ring_attention — "
                    "set mesh.seq > 1 for context parallelism inside the "
                    "pipeline")
            if self.mesh.shape["tensor"] > 1:
                # The pipeline shard_map would silently REPLICATE the
                # trunk over this axis (full weights + redundant compute
                # on every rank) — refuse rather than quietly burn 2x the
                # provisioned HBM/FLOPs. PP composes with data/fsdp (DP
                # rows), seq (CP inside the stage region), and expert
                # (MoE-PP; checked against the model below).
                raise ValueError(
                    "pipeline parallelism doesn't compose with mesh axes "
                    "['tensor'] (PP composes with data/fsdp/seq/expert)")
            unknown = set(spec.pipeline) - {"microbatches", "chunks"}
            if unknown:
                raise ValueError(
                    f"unknown spec.pipeline keys {sorted(unknown)}; "
                    "valid: microbatches, chunks")
        elif spec.pipeline:
            raise ValueError("spec.pipeline set but mesh.pipe <= 1")
        self.rules = rules_for(strategy)
        self._pipeline = None
        if self.mesh.shape["pipe"] > 1:
            self._pipeline = {
                "microbatches": int(spec.pipeline.get(
                    "microbatches", self.mesh.shape["pipe"])),
                "chunks": int(spec.pipeline.get("chunks", 1)),
            }
            if self.mesh.shape["seq"] > 1:
                self._pipeline["seq_axis"] = "seq"
        self._trainable = None
        if spec.lora:
            unknown = set(spec.lora) - {"rank", "alpha", "targets"}
            if unknown:
                raise ValueError(
                    f"unknown spec.lora keys {sorted(unknown)}; valid: "
                    "rank, alpha, targets")
            rank = int(spec.lora.get("rank", 0))
            if rank < 1:
                raise ValueError(f"lora.rank must be >= 1, got {rank}")
            targets = spec.lora.get("targets", "attn")
            if targets not in ("attn", "attn_mlp"):
                raise ValueError(
                    f"lora.targets {targets!r}: attn | attn_mlp")
            if self._pipeline is not None:
                raise ValueError(
                    "LoRA doesn't compose with pipeline parallelism "
                    "(the stage forward has no adapter path)")
            model_kwargs["lora_rank"] = rank
            model_kwargs["lora_alpha"] = float(spec.lora.get("alpha", 16.0))
            model_kwargs["lora_targets"] = targets
            self._trainable = "lora"
        try:
            self.model, self.info = registry.build_model(
                spec.model, **model_kwargs)
        except TypeError as e:
            # A non-Llama registry entry chokes on the injected lora_*
            # kwargs with an opaque TypeError from its config dataclass
            # (every builder takes **kw, so a signature pre-check can't
            # see it). Translate ONLY the unexpected-keyword error for
            # the exact kwargs WE injected — a TypeError that merely
            # mentions a lora_* name (e.g. the user's own lora_rnk typo
            # in model_kwargs) keeps its type, and the original traceback
            # rides along as __cause__ either way.
            msg = str(e)
            injected = ("lora_rank", "lora_alpha", "lora_targets")
            if (self._trainable == "lora"
                    and "unexpected keyword argument" in msg
                    and any(f"'{k}'" in msg for k in injected)):
                raise ValueError(
                    f"spec.lora needs a Llama-family model; "
                    f"{spec.model!r} has no adapter path") from e
            raise
        if self._trainable == "lora":
            from kubeflow_tpu.models.llama import LlamaConfig
            from kubeflow_tpu.models.moe import MoEConfig

            mcfg = getattr(self.model, "cfg", None)
            if not isinstance(mcfg, LlamaConfig):
                raise ValueError(
                    f"spec.lora needs a Llama-family model; "
                    f"{spec.model!r} has no adapter path")
            if (isinstance(mcfg, MoEConfig)
                    and mcfg.lora_targets == "attn_mlp"):
                # MoEBlock's routed experts have no adapter path — the
                # user asked for FFN adapters and would silently get
                # attention-only ones.
                raise ValueError(
                    "lora.targets='attn_mlp' is not supported on MoE "
                    "models (expert FFNs have no adapter path); use "
                    "targets='attn'")
        if (self._pipeline is not None
                and self.mesh.shape["expert"] > 1):
            from kubeflow_tpu.models.moe import MoEConfig

            if not isinstance(getattr(self.model, "cfg", None), MoEConfig):
                # A dense trunk would silently replicate over `expert`.
                raise ValueError(
                    "mesh.expert with pipeline parallelism needs a "
                    "MoE model (routed-expert FFNs)")

        if spec.accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got "
                             f"{spec.accum_steps}")
        if spec.grad_accum < 0:
            raise ValueError(f"grad_accum must be >= 0, got "
                             f"{spec.grad_accum}")
        if (spec.grad_accum and spec.accum_steps > 1
                and spec.grad_accum != spec.accum_steps):
            raise ValueError(
                f"grad_accum={spec.grad_accum} and its legacy alias "
                f"accum_steps={spec.accum_steps} disagree — set one")
        # The effective microbatch count (grad_accum is canonical,
        # accum_steps the legacy alias).
        self.grad_accum = spec.grad_accum or spec.accum_steps
        if spec.batch_size % self.grad_accum:
            raise ValueError(
                f"batch_size {spec.batch_size} not divisible by "
                f"grad_accum/accum_steps {self.grad_accum}")
        if spec.fsdp:
            if self._pipeline is not None:
                raise ValueError(
                    "fsdp master sharding doesn't compose with pipeline "
                    "parallelism (stage params keep the scanned pipe "
                    "layout)")
            if self._trainable == "lora":
                raise ValueError(
                    "fsdp master sharding doesn't compose with LoRA "
                    "(the adapter-only optimizer state is the memory "
                    "win there)")
        if spec.eval_every < 0 or spec.eval_batches < 1:
            raise ValueError("eval_every must be >= 0 and eval_batches "
                             ">= 1")
        if spec.restart_policy not in ("Never", "OnFailure",
                                       "ExponentialBackoff"):
            raise ValueError(
                f"restart_policy {spec.restart_policy!r}: Never | "
                "OnFailure | ExponentialBackoff")
        if spec.backoff_limit < 0:
            raise ValueError(f"backoff_limit must be >= 0, got "
                             f"{spec.backoff_limit}")
        if spec.prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {spec.prefetch}")
        if spec.profile_start_step < 0 or spec.profile_stop_step < 0:
            raise ValueError(
                "profile_start_step/profile_stop_step must be >= 0, got "
                f"{spec.profile_start_step}/{spec.profile_stop_step}")
        # Trace identity for this worker's spans: the job name under a
        # control plane, a fixed label standalone.
        self._trace = os.environ.get("TPK_JOB_NAME", "") or "train"
        self._event_client = None
        self.tx = optax.adamw(self._lr_schedule(),
                              weight_decay=spec.weight_decay)
        if spec.max_grad_norm:
            if spec.max_grad_norm < 0:
                raise ValueError(f"max_grad_norm must be >= 0, got "
                                 f"{spec.max_grad_norm}")
            self.tx = optax.chain(
                optax.clip_by_global_norm(spec.max_grad_norm), self.tx)

        self._ckpt = None
        if spec.checkpoint.get("dir"):
            self._ckpt = CheckpointManager(
                spec.checkpoint["dir"],
                interval=spec.checkpoint.get("interval", 50),
                keep=spec.checkpoint.get("keep", 3),
                async_save=spec.checkpoint.get("async_save", True))
        self.logger = MetricsLogger(spec.metrics_path)

    def _post_event(self, reason: str, message: str = "") -> None:
        """Best-effort event into the job's control-plane event log
        (CheckpointSaved & co.): only when launched by the control plane
        (TPK_SOCKET + TPK_JOB_NAME injected), only from process 0, and
        never fatal — a missing/slow control plane must not fail
        training."""
        sock = os.environ.get("TPK_SOCKET")
        job = os.environ.get("TPK_JOB_NAME")
        if not sock or not job or jax.process_index() != 0:
            return
        try:
            if self._event_client is None:
                from kubeflow_tpu.controlplane.client import Client

                self._event_client = Client(sock, timeout=2.0,
                                            max_attempts=1, deadline_s=2.0,
                                            trace_id=job)
            self._event_client.post_event(job, reason, message)
        except Exception:
            self._event_client = None  # reconnect on the next event

    def _lr_schedule(self) -> optax.Schedule | float:
        spec = self.spec
        peak, warm = spec.learning_rate, spec.warmup_steps
        if spec.lr_schedule == "constant":
            if warm:
                return optax.linear_schedule(0.0, peak, warm)
            return peak
        # Decay horizon is the full run: warmup then decay to lr_final at
        # spec.steps (resume keeps the schedule aligned since opt step
        # count rides in the checkpointed opt_state).
        decay_steps = max(spec.steps - warm, 1)
        if spec.lr_schedule == "cosine":
            return optax.warmup_cosine_decay_schedule(
                0.0, peak, warm, warm + decay_steps,
                end_value=spec.lr_final)
        if spec.lr_schedule == "linear":
            decay = optax.linear_schedule(peak, spec.lr_final, decay_steps)
            if not warm:
                return decay
            return optax.join_schedules(
                [optax.linear_schedule(0.0, peak, warm), decay], [warm])
        raise ValueError(
            f"lr_schedule {spec.lr_schedule!r}: constant | cosine | linear")

    # -- data ---------------------------------------------------------------

    @property
    def _dp_shards(self) -> int:
        """Extent of the batch-sharding axes (data × fsdp)."""
        return self.mesh.shape["data"] * self.mesh.shape["fsdp"]

    @property
    def _batch_groups(self) -> int:
        """How many DISTINCT per-process data streams the mesh admits.

        The batch dim shards over the leading (data, fsdp) mesh axes, so a
        process's devices cover dp·n_proc-relative shard spans: with
        dp >= n_proc each process owns exclusive shards (n distinct
        streams); with dp < n_proc each shard is replicated across
        n_proc/dp processes, which must feed IDENTICAL data (dp streams);
        pure CP/TP (dp == 1) replicates the whole batch everywhere."""
        n = jax.process_count()
        dp = self._dp_shards
        if dp % n and n % dp:
            raise ValueError(
                f"batch shards ({dp} = data*fsdp) and processes ({n}) "
                "must divide one another for process-aligned data loading")
        return min(dp, n)

    @property
    def local_batch_size(self) -> int:
        """spec.batch_size is the GLOBAL batch; each process loads the
        share of its batch replica group (the reference's per-worker
        DataLoader sharding, done for the user)."""
        g = self._batch_groups
        if self.spec.batch_size % g:
            raise ValueError(
                f"global batch {self.spec.batch_size} not divisible by "
                f"{g} batch replica groups")
        return self.spec.batch_size // g

    def _make_stream(self, name: str, kwargs: dict,
                     seed_base: int) -> Iterator[dict]:
        """Shared dataset-builder: model-derived defaults plus the batch
        replica-group contract — processes sharing a batch shard (or a
        fully replicated batch) must load IDENTICAL data: same seed AND
        the same grain row shard (the loader's sharding is group-indexed,
        not process-indexed)."""
        from kubeflow_tpu.utils import registry

        kwargs = dict(kwargs)
        kwargs.setdefault("batch_size", self.local_batch_size)
        if self.info.get("task") == "lm":
            kwargs.setdefault("seq_len", self.spec.seq_len)
            kwargs.setdefault("vocab_size", self.info["vocab_size"])
        n = jax.process_count()
        group = jax.process_index() * self._batch_groups // n
        kwargs.setdefault("seed", seed_base + 7919 * group)
        kwargs.setdefault("process_index", group)
        kwargs.setdefault("process_count", self._batch_groups)
        return registry.build_dataset(name, **kwargs)

    def _data(self) -> Iterator[dict]:
        return self._make_stream(self.spec.dataset,
                                 self.spec.dataset_kwargs, self.spec.seed)

    def _eval_data(self) -> Iterator[dict]:
        """Validation stream. Defaults to the train dataset family —
        INCLUDING its kwargs (a token_file corpus path must carry over) —
        with a disjoint seed so synthetic/eval-less corpora still get a
        held-out-like stream."""
        if self.spec.eval_dataset:
            name, kwargs = self.spec.eval_dataset, self.spec.eval_dataset_kwargs
        else:
            name = self.spec.dataset
            kwargs = {**self.spec.dataset_kwargs,
                      **self.spec.eval_dataset_kwargs}
        return self._make_stream(name, kwargs, self.spec.seed + 104729)

    def _globalize(self, batch: dict) -> dict:
        """Assemble process-local numpy batches into global jax.Arrays
        sharded over the dp axes (multi-host path; no-op single-process)."""
        if jax.process_count() == 1:
            return batch
        from jax.sharding import NamedSharding, PartitionSpec as P

        def conv(x):
            spec = P(("data", "fsdp"), *([None] * (x.ndim - 1)))
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), np.asarray(x))

        return jax.tree.map(conv, batch)

    def _place_on_device(self, batch: dict) -> dict:
        """Explicit H2D staging for the prefetch path: each leaf lands on
        device BEFORE the trainer thread sees it, so the transfer
        overlaps device compute instead of riding implicitly inside the
        next step's dispatch. Multi-host goes through `_globalize`
        (make_array_from_process_local_data with the dp sharding — the
        per-process shards ARE the placement). Single-process places
        with the replicated layout the jitted step resolves for
        uncommitted batch inputs anyway: the same bytes land on the same
        devices as the numpy path, just off the critical path — which
        keeps every prefetch depth bit-identical to the synchronous
        loop (a dp-sharded committed input would compile a different —
        cheaper to transfer but numerically reordered — program)."""
        if jax.process_count() > 1:
            return self._globalize(batch)
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P())

        def conv(x):
            return jax.device_put(np.asarray(x), sharding)

        return jax.tree.map(conv, batch)

    def _example_inputs(self) -> tuple:
        if self.info.get("task") == "lm":
            return (jnp.zeros((self.spec.batch_size, self.spec.seq_len),
                              jnp.int32),)
        shape = (self.spec.batch_size,) + tuple(
            self.info["example_shape"][1:])
        return (jnp.zeros(shape, self.info["example_dtype"]),)

    def _loss_fn(self):
        if self.info.get("task") == "classify":
            def loss_fn(logits, batch):
                if isinstance(logits, tuple):
                    logits = logits[-1]
                onehot = jax.nn.one_hot(batch["targets"], logits.shape[-1])
                return optax.softmax_cross_entropy(logits, onehot).mean()
            return loss_fn
        return None  # default causal-LM loss

    # -- run ----------------------------------------------------------------

    def run(self) -> dict:
        """Supervised entry point: runs the training loop under the
        spec's restart policy (training-operator restartPolicy/
        backoffLimit, in-process). Every restart flows through
        `_run_once`'s checkpoint auto-resume — latest TrainState AND the
        saved data-iterator position — so the run converges to the same
        final step a fault-free run reaches."""
        spec = self.spec
        if spec.restart_policy == "Never":
            return self._run_once()
        backoff = resilience.BackoffPolicy(initial_s=0.05, max_s=10.0)
        restarts = 0
        while True:
            try:
                return self._run_once()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if self._ckpt is not None:
                    # An async save may be mid-flight; restarting before
                    # it lands could resume from the previous (older)
                    # step. Failures inside wait() itself mean the ckpt
                    # dir is suspect — surface the original error.
                    try:
                        self._ckpt.wait()
                    except Exception:
                        pass
                restarts += 1
                if restarts > spec.backoff_limit:
                    resilience.metrics.inc("tpk_retry_exhausted_total",
                                           component="train")
                    raise resilience.BackoffLimitExceeded(
                        f"training failed {restarts} times "
                        f"(backoff_limit={spec.backoff_limit}, "
                        f"restart_policy={spec.restart_policy}): "
                        f"{type(e).__name__}: {e}") from e
                # Counted only when a restart actually happens — the
                # terminal failure above is an exhaustion, not a restart.
                resilience.metrics.inc("tpk_restarts_total",
                                       component="train")
                delay = (backoff.delay(restarts - 1)
                         if spec.restart_policy == "ExponentialBackoff"
                         else 0.0)
                self.logger.log(0, {
                    "event": "restarting", "attempt": restarts,
                    "backoff_s": round(delay, 3),
                    "error": f"{type(e).__name__}: {e}"})
                if delay:
                    time.sleep(delay)

    def _run_once(self) -> dict:
        spec = self.spec

        model_kwargs = {}
        if spec.ring_attention:
            model_kwargs["ring_axis"] = "seq"
        # Zigzag context parallelism (SURVEY.md §5.7 causal load balance):
        # spec.ring_attention == "zigzag" is the single switch — the
        # trainer lays batches out in zigzag order and passes the matching
        # absolute positions for RoPE; the LM loss is invariant (inputs
        # and targets move together). Model-side impl is forced to match
        # in __init__ so spec and kernel can't drift.
        zigzag_idx = None
        init_kwargs = None
        if spec.ring_attention in ("zigzag", "zigzag_flash"):
            from kubeflow_tpu.ops.ring_attention import zigzag_indices

            n_seq = self.mesh.shape["seq"]
            zigzag_idx = np.asarray(zigzag_indices(spec.seq_len, n_seq))
            model_kwargs["positions"] = jnp.broadcast_to(
                jnp.asarray(zigzag_idx, jnp.int32)[None],
                (spec.batch_size, spec.seq_len))
            init_kwargs = model_kwargs  # zigzag's init needs positions too

        fsdp_plan = None
        if spec.fsdp:
            from kubeflow_tpu.parallel.fsdp import FSDP

            fsdp_plan = FSDP(self.mesh, compute_dtype=self._fsdp_dtype)

        state = init_train_state(
            self.model, self.tx, jax.random.key(spec.seed),
            self._example_inputs(), self.mesh, self.rules,
            example_kwargs=init_kwargs, trainable=self._trainable,
            fsdp=fsdp_plan)

        start_step = 0
        if self._ckpt is not None:
            # Corrupt-latest fallback: a torn orbax write (SIGKILL
            # mid-save) quarantines that step and resumes from the
            # next-newest good one instead of wedging every restart of
            # the backoff loop on the same poisoned restore.
            with obs.span("train.restore", trace_id=self._trace):
                state, latest, quarantined = \
                    self._ckpt.restore_latest_good(state)
            for bad in quarantined:
                self.logger.log(int(bad), {
                    "event": "checkpoint_quarantined", "step": int(bad)})
            if latest is not None:
                start_step = int(latest)
                self.logger.log(start_step, {"event": "restored"})

        # State-layout accounting (pure sharding metadata — no device
        # sync): how many bytes of params/optimizer state each chip
        # actually holds, the number the fsdp knob exists to divide.
        from kubeflow_tpu.parallel.fsdp import tree_bytes_per_device

        param_bytes = tree_bytes_per_device(state.params)
        opt_bytes = tree_bytes_per_device(state.opt_state)
        resilience.metrics.set_gauge("tpk_train_param_bytes_per_chip",
                                     param_bytes, component="train")
        resilience.metrics.set_gauge("tpk_train_opt_state_bytes_per_chip",
                                     opt_bytes, component="train")
        resilience.metrics.set_gauge("tpk_train_grad_accum_steps",
                                     self.grad_accum, component="train")
        # The live fsdp topology this attempt is training at — under an
        # elastic resize the controller rewrites runtime.json, so this
        # gauge is how dashboards see the post-resize mesh.
        resilience.metrics.set_gauge("tpk_train_fsdp_size",
                                     spec.fsdp, component="train")
        self.logger.log(start_step, {
            "event": "state_sharding", "fsdp": spec.fsdp,
            "param_bytes_per_chip": param_bytes,
            "opt_state_bytes_per_chip": opt_bytes,
            "grad_accum_steps": self.grad_accum})

        step_fn = make_train_step(self.model, self.mesh, self.rules,
                                  loss_fn=self._loss_fn(),
                                  model_kwargs=model_kwargs,
                                  loss_impl=spec.loss_impl,
                                  loss_chunk=spec.loss_chunk,
                                  pipeline=self._pipeline,
                                  accum_steps=self.grad_accum,
                                  trainable=self._trainable,
                                  fsdp=fsdp_plan)

        eval_step = None
        if spec.eval_every:
            from kubeflow_tpu.train.step import make_eval_step

            eval_step = make_eval_step(self.model, self.mesh, self.rules,
                                       model_kwargs=model_kwargs)

        # One persistent eval stream for the whole run: file-backed
        # corpora pay their tokenize/pack cost in the constructor, so
        # rebuilding per window would stall training every eval_every
        # steps. Rebuilt only when exhausted.
        eval_iter_box: list = [None]

        def next_eval_batch():
            for _ in range(2):
                if eval_iter_box[0] is None:
                    eval_iter_box[0] = iter(self._eval_data())
                try:
                    return next(eval_iter_box[0])
                except StopIteration:
                    eval_iter_box[0] = None  # exhausted: fresh pass
            return None

        def run_eval(params, at_step):
            # Accumulate DEVICE scalars and fetch once per eval window:
            # a float() per batch would pay one full host sync each
            # (~66 ms on the tunnel backend, PROFILE.md §1) — an
            # eval_batches-deep stall inside the training timeline.
            loss_sum = acc_sum = None
            seen = 0
            for _ in range(spec.eval_batches):
                raw = next_eval_batch()
                if raw is None:
                    break
                if zigzag_idx is not None:
                    raw = {k: np.asarray(v)[:, zigzag_idx]
                           for k, v in raw.items()}
                m = eval_step(params, self._globalize(raw))
                loss_sum = (m["loss"] if loss_sum is None
                            else loss_sum + m["loss"])
                acc_sum = (m["accuracy"] if acc_sum is None
                           else acc_sum + m["accuracy"])
                seen += 1
            if not seen:
                return {}
            totals = np.asarray(jnp.stack([loss_sum, acc_sum]))  # 1 fetch
            out = {"eval_loss": float(totals[0]) / seen,
                   "eval_accuracy": float(totals[1]) / seen,
                   "eval_batches": seen}
            self.logger.log(at_step, out)
            return out

        tokens_per_step = spec.batch_size * (
            spec.seq_len if self.info.get("task") == "lm" else 1)
        timer = StepTimer(
            num_params=self.info.get("num_params") or 0,
            tokens_per_step=tokens_per_step)

        # Profile window [prof_start, prof_stop): the dict-style knob
        # (dir + start_step + num_steps) or the flat spec knobs
        # (profile_start_step/profile_stop_step, trace dir defaulting to
        # the job workdir); clamped so the trace always closes before
        # the loop ends.
        prof = spec.profile
        prof_start = prof_stop = None
        prof_dir = prof.get("dir")
        if prof_dir and prof.get("start_step") is not None:
            prof_start = max(int(prof["start_step"]), start_step)
            prof_stop = min(prof_start + int(prof.get("num_steps", 3)),
                            spec.steps)
            if prof_start >= spec.steps:
                prof_start = prof_stop = None
        elif spec.profile_stop_step > spec.profile_start_step:
            base = (os.environ.get("TPK_WORKDIR")
                    or (os.path.dirname(spec.metrics_path)
                        if spec.metrics_path else "")
                    or ".")
            prof_dir = prof_dir or os.path.join(base, "profile")
            prof_start = max(spec.profile_start_step, start_step)
            prof_stop = min(spec.profile_stop_step, spec.steps)
            if prof_start >= prof_stop:
                prof_start = prof_stop = None
        prof_active = False

        from kubeflow_tpu.data.loader import restore_iterator

        def pack_data_state():
            # Under prefetch the iterator runs ahead of training;
            # consumed_state() is the snapshot paired with the batch the
            # checkpoint step actually trained on, so resume replays
            # exactly the right rows.
            st = prefetch.consumed_state()
            if st is None:
                return None
            # The iterator state is only valid for the same per-process
            # shard layout; tag it so an elastic resize (different world
            # size) restarts the stream instead of mis-seeking. The fsdp
            # tag records the mesh the checkpoint trained at — resize
            # detection on resume, not a seek invalidator (the stream is
            # process-sharded, so a same-process-count fsdp resize seeks
            # the exact trajectory).
            return {"process_count": jax.process_count(), "state": st,
                    "fsdp": spec.fsdp}

        dataset = self._data()
        data = iter(dataset)
        if start_step:
            saved = self._ckpt.restore_data_state()
            if saved is None:
                # Plain generators: replay consumed batches.
                for _ in range(start_step):
                    next(data)
            elif (isinstance(saved, dict) and "process_count" in saved):
                saved_fsdp = saved.get("fsdp")
                if saved_fsdp is not None and saved_fsdp != spec.fsdp:
                    # Elastic resize: the checkpoint was written by a
                    # different fsdp topology and orbax just resharded it
                    # into this one (restore_latest_good above). Record
                    # the transition — the trajectory itself must not
                    # notice (fp32 fsdp=N trains the replicated
                    # trajectory exactly; PROFILE §14/§15).
                    resilience.metrics.inc("tpk_train_reshard_restores_total",
                                           component="train")
                    self.logger.log(start_step, {
                        "event": "resharded",
                        "from_fsdp": int(saved_fsdp),
                        "to_fsdp": int(spec.fsdp), "step": start_step})
                    self._post_event(
                        "Resharded",
                        f"fsdp {int(saved_fsdp)} -> {int(spec.fsdp)} "
                        f"at step {start_step}")
                if saved["process_count"] == jax.process_count():
                    # Checkpointable iterators (grain) seek in O(1).
                    restore_iterator(data, saved.get("state"))
                else:
                    # Resized world: per-process shards changed; a fresh
                    # stream is the correct (and standard) resume behavior.
                    self.logger.log(start_step, {
                        "event": "data_stream_restarted",
                        "reason": "world size changed"})
            else:
                # Pre-tag checkpoint: raw iterator state, same-world by
                # assumption (the tag didn't exist to say otherwise).
                restore_iterator(data, saved)

        # The async input pipeline: pull + zigzag + H2D staged up to
        # `spec.prefetch` batches ahead on a worker thread (depth 0 runs
        # the same ops inline — the synchronous escape hatch). Created
        # AFTER the iterator seek above so read-ahead starts at the
        # resume position.
        transform = None
        if zigzag_idx is not None:
            def transform(raw):
                return {k: np.asarray(v)[:, zigzag_idx]
                        for k, v in raw.items()}

        # Fault injection (SURVEY.md §5.3): the controller sets
        # TPK_FAULT="step=K;signal=S" on one worker; it kills itself at the
        # top of step K — the deterministic, step-precise chaos fixture.
        fault_step = fault_signal = None
        fault = os.environ.get("TPK_FAULT", "")
        if fault:
            kv = dict(part.split("=", 1) for part in fault.split(";") if "=" in part)
            fault_step = int(kv.get("step", -1))
            fault_signal = int(kv.get("signal", 9))

        prefetch = Prefetcher(
            data, depth=spec.prefetch, transform=transform,
            place=(self._globalize if spec.prefetch == 0
                   else self._place_on_device))

        last_metrics: dict = {}
        last_eval: dict = {}
        # CheckpointSaved events are deferred one save boundary: orbax
        # saves asynchronously, and a WAL-persisted event must never
        # claim a checkpoint that a kill-9 then tore. Starting save k+1
        # blocks on save k's commit, so at the next boundary (and after
        # the final wait()) the previous save is known durable.
        ckpt_event_pending: int | None = None
        # Per-window data-starvation accounting: how much of the window's
        # wall the training thread spent waiting on input (data_wait_frac
        # ≈ 0 when the prefetcher keeps up; → 1 when the pipeline is the
        # bottleneck and depth/host work needs attention).
        win = {"t0": 0.0, "wait": 0.0, "h2d": 0.0}
        # Per-window span rollup (tentpole: "span summaries in the JSONL
        # stream"): host-side wall spent in step dispatch / boundary
        # fetches / checkpoint saves / eval, summed between log
        # boundaries — the coarse where-did-the-window-go view; the full
        # per-span timeline lives in the obs tracer ring.
        span_win: dict[str, list] = {}

        def acc_span(key: str, sp) -> None:
            if sp is obs.NOP_SPAN:
                # Tracing disabled (TPK_TRACE=0): omit the span_* keys
                # entirely rather than emitting constant 0.0 — "not
                # measured" must not read as "zero host time".
                return
            w = span_win.setdefault(key, [0, 0.0])
            w[0] += 1
            w[1] += sp.dur_s

        def win_reset():
            win["t0"] = time.perf_counter()
            win["wait"] = prefetch.data_wait_s
            win["h2d"] = prefetch.h2d_s
            span_win.clear()

        def win_metrics() -> dict:
            wall = time.perf_counter() - win["t0"]
            dw = prefetch.data_wait_s - win["wait"]
            out = {
                "data_wait_s": round(dw, 6),
                "data_wait_frac": round(dw / wall, 4) if wall > 0 else 0.0,
                "data_h2d_s": round(prefetch.h2d_s - win["h2d"], 6),
                "tpk_data_wait_seconds_total": round(
                    resilience.metrics.get("tpk_data_wait_seconds_total",
                                           component="train"), 6),
            }
            for key, (_, total) in sorted(span_win.items()):
                out[f"span_{key}_ms"] = round(total * 1e3, 3)
            return out

        try:
            timer.start()
            win_reset()
            window = 0
            # The hot loop: between log/eval boundaries nothing below
            # may touch a device value — host data prep and device
            # compute only overlap while the dispatch queue stays full.
            # The tpk-hot region makes that reviewable-by-machine; the
            # runtime sync-budget guard test pins the same invariant
            # dynamically. Every deliberate boundary fetch below carries
            # its reason inline.
            # tpk-hot: begin trainer-step-loop
            for step in range(start_step, spec.steps):
                faults.fire(_FP_STEP, step=step)
                if fault_step is not None and step == fault_step:
                    if self._ckpt is not None:
                        self._ckpt.wait()  # die w/ a consistent checkpoint
                    self.logger.log(step, {"event": "fault_injected",
                                           "signal": fault_signal})
                    os.kill(os.getpid(), fault_signal)
                if prof_start is not None and step == prof_start:
                    jax.profiler.start_trace(prof_dir)
                    prof_active = True
                # The step span measures HOST dispatch wall (data wait +
                # enqueue) — the device executes asynchronously, and the
                # span never touches a device value, so tracing adds
                # zero host syncs to the hot loop (the span-overhead
                # guard test pins this).
                with obs.span("train.step", trace_id=self._trace,
                              step=step) as sp:
                    batch = next(prefetch)
                    state, metrics = step_fn(state, batch)
                acc_span("step", sp)
                window += 1
                if prof_active and step + 1 == prof_stop:
                    # tpk-lint: allow(host-sync) reason=profiler window close must drain the device or the trace tail is lost; runs only on the configured profile_stop_step
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    prof_active = False
                if self._ckpt is not None:
                    # Only collect iterator state on steps that will save
                    # — consumed_state() may walk the grain pipeline
                    # (depth 0) and doesn't belong in the non-blocking
                    # hot loop.
                    if self._ckpt.should_save(step + 1):
                        with obs.span("train.checkpoint_save",
                                      trace_id=self._trace,
                                      step=step + 1) as sp:
                            self._ckpt.maybe_save(
                                step + 1, state,
                                data_state=pack_data_state())
                        acc_span("ckpt", sp)
                        if ckpt_event_pending is not None:
                            self._post_event(
                                "CheckpointSaved",
                                f"step {ckpt_event_pending}")
                        ckpt_event_pending = step + 1
                    else:
                        self._ckpt.maybe_save(step + 1, state)
                if (eval_step is not None
                        and (step + 1) % spec.eval_every == 0):
                    # Close the timing window first so eval wall time
                    # never pollutes the train tokens/sec / MFU averages.
                    sp_fetch = None
                    if window:
                        with obs.span("train.fetch",
                                      trace_id=self._trace) as sp_fetch:
                            # tpk-lint: allow(host-sync) reason=eval boundary closes the timing window so eval wall never pollutes tokens/sec (designed per-eval_every fetch)
                            jax.block_until_ready(metrics["loss"])
                        timer.stop(n_steps=window)
                        window = 0
                    with obs.span("train.eval", trace_id=self._trace,
                                  step=step + 1) as sp:
                        last_eval = run_eval(state.params, step + 1)
                    timer.start()
                    win_reset()
                    # Recorded AFTER the reset so the boundary costs
                    # show on the next window's line instead of
                    # vanishing with the window they closed.
                    if sp_fetch is not None:
                        acc_span("fetch", sp_fetch)
                    acc_span("eval", sp)
                if ((step + 1) % spec.log_every == 0
                        or step + 1 == spec.steps):
                    # Block only at logging boundaries — keeping the
                    # dispatch queue full between them lets host data prep
                    # overlap device compute (per-step numbers are window
                    # averages).
                    with obs.span("train.fetch",
                                  trace_id=self._trace) as sp:
                        # tpk-lint: allow(host-sync) reason=the designed per-log_every window boundary; the runtime guard budgets exactly one fetch here
                        jax.block_until_ready(metrics["loss"])
                    acc_span("fetch", sp)
                    if window:
                        perf = timer.stop(n_steps=window)
                        window = 0
                    else:  # an eval just flushed this window
                        perf = timer.snapshot()
                    last_metrics = {
                        # tpk-lint: allow(host-sync) reason=already on host after the boundary block_until_ready above; free fetch
                        "loss": float(metrics["loss"]),
                        # tpk-lint: allow(host-sync) reason=already on host after the boundary block_until_ready above; free fetch
                        "grad_norm": float(metrics["grad_norm"]),
                        "tokens_per_sec": perf["tokens_per_sec"],
                        "mfu": perf["mfu"],
                        "step_time_s": perf["step_time_s"],
                        **win_metrics(),
                    }
                    # MoE models report the router balance penalty too.
                    # tpk-lint: allow(host-sync) reason=log-boundary only, value already on host after the window fetch above
                    if float(metrics.get("aux_loss", 0.0)) > 0:
                        # tpk-lint: allow(host-sync) reason=log-boundary only, value already on host after the window fetch
                        last_metrics["aux_loss"] = float(
                            metrics["aux_loss"])
                    self.logger.log(step + 1, last_metrics)
                    timer.start()
                    win_reset()
            # tpk-hot: end trainer-step-loop

            if self._ckpt is not None:
                if self._ckpt.latest_step() != spec.steps:
                    with obs.span("train.checkpoint_save",
                                  trace_id=self._trace, step=spec.steps):
                        self._ckpt.maybe_save(spec.steps, state,
                                              data_state=pack_data_state(),
                                              force=True)
                self._ckpt.wait()
                # Everything is durable now: flush the deferred interior
                # event (it never met its "next boundary"), then the
                # final step's (the two merge into one aggregated row).
                if (ckpt_event_pending is not None
                        and ckpt_event_pending != self._ckpt.latest_step()):
                    self._post_event("CheckpointSaved",
                                     f"step {ckpt_event_pending}")
                self._post_event("CheckpointSaved",
                                 f"step {self._ckpt.latest_step()}")
            self.logger.log(spec.steps,
                            {"event": "done", **last_metrics, **last_eval})
            return {"final_step": spec.steps, **last_metrics, **last_eval}
        finally:
            # Every exit path of the supervised restart loop lands here:
            # normal completion, a raising step (restart policies rebuild
            # the stream), KeyboardInterrupt — the worker thread must
            # never outlive its run.
            prefetch.close()


def main(argv: list[str] | None = None) -> int:
    """`python -m kubeflow_tpu.train.trainer --spec job.json` — the worker
    entrypoint the JAXJob executor launches (with TPK_* env injected)."""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--spec", required=True,
                        help="path to TrainJobSpec JSON")
    parser.add_argument("--cpu-devices", type=int, default=0,
                        help="force N virtual CPU devices (test mode)")
    args = parser.parse_args(argv)

    if args.cpu_devices:
        from kubeflow_tpu.utils.devices import force_cpu_device_count
        force_cpu_device_count(args.cpu_devices)

    with open(args.spec) as fh:
        spec = TrainJobSpec.from_json(fh.read())
    result = Trainer(spec).run()
    print(json.dumps({"result": result}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
