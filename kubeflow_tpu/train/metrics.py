"""Step metrics and MFU accounting.

The reference delegates training metrics to user containers and scrapes them
back via Katib's stdout-regex sidecar (SURVEY.md §5.5); here the runtime owns
a metrics channel directly: per-step wall time, tokens/sec, and MFU computed
with the BASELINE.md convention MFU = 6·N·tok/s ÷ (chips · peak BF16 FLOP/s).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax

# Peak dense BF16 FLOP/s per chip (public spec sheets).
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal, so MFU math stays finite in CPU tests
}


def peak_flops_per_chip() -> float:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return PEAK_FLOPS["cpu"]


@dataclasses.dataclass
class StepTimer:
    """Tracks smoothed step time / tokens/s / MFU across the training loop."""

    num_params: int
    tokens_per_step: int
    num_chips: int = 0
    warmup_steps: int = 2  # exclude compile steps from averages
    _count: int = 0
    _total_time: float = 0.0
    _last: float | None = None

    def __post_init__(self):
        self.num_chips = self.num_chips or jax.device_count()
        self.peak = peak_flops_per_chip()

    def start(self):
        self._last = time.perf_counter()

    def stop(self, n_steps: int = 1) -> dict:
        """Close a timing window covering `n_steps` device steps (the trainer
        only blocks on logging steps, so a window spans several steps)."""
        now = time.perf_counter()
        dt = now - (self._last if self._last is not None else now)
        prev = self._count
        self._count += n_steps
        # Steps beyond the warmup threshold count toward the average.
        counted = self._count - max(prev, self.warmup_steps)
        if counted > 0:
            self._total_time += dt * (counted / n_steps)
        return self.snapshot(step_time=dt / max(n_steps, 1))

    def snapshot(self, step_time: float | None = None) -> dict:
        counted = max(self._count - self.warmup_steps, 0)
        avg = self._total_time / counted if counted else (step_time or 0.0)
        tps = self.tokens_per_step / avg if avg else 0.0
        model_flops = 6.0 * self.num_params * tps  # fwd+bwd matmul FLOPs
        mfu = model_flops / (self.num_chips * self.peak) if avg else 0.0
        return {
            "step_time_s": step_time if step_time is not None else avg,
            "avg_step_time_s": avg,
            "tokens_per_sec": tps,
            "tokens_per_sec_per_chip": tps / self.num_chips,
            "mfu": mfu,
        }


class MetricsLogger:
    """JSONL metrics stream — consumed by the CLI (`tpukit logs -f`), the HPO
    metrics collector (tune/), and humans. One JSON object per line, always
    with "step"."""

    def __init__(self, path: str | None = None, stream=None):
        self._fh = open(path, "a", buffering=1) if path else None
        self._stream = stream if stream is not None else sys.stdout

    def log(self, step: int, payload: dict):
        rec = {"step": int(step)}
        for k, v in payload.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                tolist = getattr(v, "tolist", None)
                rec[k] = tolist() if tolist is not None else str(v)
        line = json.dumps(rec)
        if self._fh:
            self._fh.write(line + "\n")
        if self._stream:
            print(line, file=self._stream, flush=True)

    def close(self):
        if self._fh:
            self._fh.close()
