"""Train-plane chaos harness (ISSUE 17) → TRAINCHAOS.json.

The serve plane got its chaos harness in ISSUE 14 (serve/chaosbench.py);
this is the train-plane arm: REAL trainer workers (each its own
subprocess, launched by the REAL tpk-controlplane binary) under a seeded
SIGKILL/SIGSTOP schedule, measuring **goodput** — useful (non-redone)
training steps per wall-second — for three arms at identical corpus,
seed, and fault schedule:

  * **control** — fault-free run at the submitted 4-way fsdp topology;
    the goodput ceiling everything else is read against.
  * **elastic** — the job carries `elastic.min_fsdp`; the worker is
    SIGKILLed at a seeded step threshold (condition-triggered off the
    live metrics JSONL, so the kill lands mid-training, not mid-compile)
    and the controller downsizes 4 -> 2 unattended: next-divisor
    topology, runtime.json rewrite, relaunch, checkpoint reshard. A
    later SIGSTOP/SIGCONT window stalls the post-resize worker
    (slow-but-alive straggler) without killing it.
  * **restart_scratch** — the no-checkpoint baseline: same kill, same
    stall, and the SAME capacity loss (the controller downsizes this
    gang 4 -> 2 too — the fault is a capacity event, identical across
    arms), but no checkpointing: the relaunch starts from step 0 and
    every pre-kill step is redone at the degraded topology. Holding the
    capacity trajectory fixed makes the goodput delta the value of
    checkpoint-resume-with-reshard alone, not of having more devices.

Pinned claims (tests/test_trainchaos.py): the resize event chain is
OBSERVED (ElasticDownsize naming old -> new topology, then the worker's
Resharded once the restored state landed), ZERO acked checkpoints are
lost (every step the trainer durably acked via CheckpointSaved is <= the
step the resumed attempt restored), and elastic goodput is STRICTLY
above restart-from-scratch (the redone-work gap is the mechanism).
Absolute rates are 1-CPU tiny-model numbers — the artifact says so, and
the claims are arm DELTAS plus mechanism facts, never absolute speed.

Harness discipline (PROFILE §11/§15): the fault schedule is seeded and
recorded; kills are condition-triggered at step thresholds read from the
worker's own metrics stream; the persistent XLA compile cache is
disabled (a post-resize attempt loading a cache entry written at the
other topology segfaults this jaxlib's cache deserialization) — compile
cost stays symmetric instead: every arm compiles 4-way at launch, and
the two compared arms each pay exactly one 2-way recompile after the
identical downsize.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np

#: Shared trainer shape for every arm (tiny llama, fp32 CPU mesh — the
#: trajectory math must be exact, and the harness runs on 1 CPU).
#: batch/seq are sized so one step costs ~1s of real compute: the
#: goodput A/B measures REDONE WORK, and the redone-prefix gap has to
#: dominate the (symmetric) compile + restore overheads, not drown in
#: them — all the capacity on a CPU mesh is one physical CPU, so
#: per-step cost, not device count, is what the kill puts at stake.
TRAIN_KW = dict(model="llama_tiny", model_kwargs={"dtype": "float32"},
                dataset="token_file", batch_size=32, seq_len=64,
                learning_rate=1e-3, log_every=1, prefetch=2)

#: The submitted (maximum) fsdp topology every arm starts at.
FSDP = 4


def make_schedule(seed: int, steps: int, interval: int) -> dict:
    """Seeded fault schedule, RECORDED in the artifact. The kill step is
    pinned to `ckpt_interval*k + 1` — one step past a save boundary, so
    the elastic arm's redo is minimal (the checkpoint just landed) while
    restart-from-scratch redoes everything before it: the honest shape
    of 'a checkpoint existed and only one arm could use it'."""
    rng = np.random.default_rng(seed + 6211)
    kill = int(rng.uniform(0.55, 0.70) * steps)
    kill = (kill // interval) * interval + 1
    stall = min(steps - 2, (kill + steps) // 2)
    return {
        "kill_step": kill,
        "stall_step": stall,
        "stall_s": round(float(rng.uniform(1.5, 2.5)), 2),
    }


class _StepMonitor(threading.Thread):
    """Tails a trainer's metrics JSONL and exposes its live progress to
    the fault driver — the condition-triggered kill ('SIGKILL once the
    worker has really passed step K') reads this, never wall-clock."""

    def __init__(self, path: str):
        super().__init__(daemon=True, name="tpk-trainchaos-monitor")
        self.path = path
        self._lock = threading.Lock()
        self._halt = threading.Event()
        self.max_step = 0  # guarded-by: _lock
        self.events: list[dict] = []  # guarded-by: _lock

    def run(self):
        fh = None
        buf = ""
        try:
            while not self._halt.is_set():
                if fh is None:
                    if not os.path.exists(self.path):
                        time.sleep(0.05)
                        continue
                    fh = open(self.path)
                chunk = fh.read()
                if not chunk:
                    time.sleep(0.05)
                    continue
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    with self._lock:
                        if "loss" in rec:
                            self.max_step = max(self.max_step,
                                                int(rec["step"]))
                        if "event" in rec:
                            self.events.append(rec)
        finally:
            if fh is not None:
                fh.close()

    def step(self) -> int:
        with self._lock:
            return self.max_step

    def snapshot_events(self) -> list[dict]:
        with self._lock:
            return list(self.events)

    def wait_step(self, threshold: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.step() >= threshold:
                return True
            time.sleep(0.05)
        return False

    def stop(self):
        self._halt.set()


class _FaultDriver(threading.Thread):
    """Runs the seeded kill/stall schedule against a live job's worker,
    gating each action on the monitor's observed step. Fired actions are
    recorded (with the step they actually landed at) for the artifact —
    the bench reports outcomes, not injector intent."""

    def __init__(self, client, job: str, monitor: _StepMonitor,
                 schedule: dict, *, timeout_s: float):
        super().__init__(daemon=True, name="tpk-trainchaos-faults")
        self.client = client
        self.job = job
        self.monitor = monitor
        self.schedule = schedule
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self.fired: list[dict] = []  # guarded-by: _lock

    def _pid(self) -> int | None:
        try:
            pids = self.client.get("JAXJob", self.job)["status"].get(
                "pids") or []
            return int(pids[0]) if pids else None
        except Exception:
            return None

    def _record(self, what: str, **kw):
        with self._lock:
            self.fired.append(dict({"action": what}, **kw))

    def run(self):
        sched = self.schedule
        # SIGKILL once the worker has genuinely trained past kill_step.
        if self.monitor.wait_step(sched["kill_step"], self.timeout_s):
            pid = self._pid()
            if pid is not None:
                try:
                    os.kill(pid, signal.SIGKILL)
                    self._record("kill", step=self.monitor.step(),
                                 pid=pid)
                except ProcessLookupError:
                    self._record("kill_missed", pid=pid)
        # SIGSTOP/SIGCONT stall on the (relaunched) worker once it has
        # passed stall_step: slow-but-alive, not dead — the controller
        # must NOT resize again; the run just stretches by ~stall_s.
        if self.monitor.wait_step(sched["stall_step"], self.timeout_s):
            pid = self._pid()
            if pid is not None:
                try:
                    os.kill(pid, signal.SIGSTOP)
                    try:
                        time.sleep(sched["stall_s"])
                    finally:
                        os.kill(pid, signal.SIGCONT)
                    self._record("stall", step=self.monitor.step(),
                                 pid=pid, stall_s=sched["stall_s"])
                except ProcessLookupError:
                    self._record("stall_missed", pid=pid)

    def snapshot_fired(self) -> list[dict]:
        with self._lock:
            return list(self.fired)


# -- arms -------------------------------------------------------------------


def _runtime(corpus: str, steps: int, interval: int | None,
             metrics_path: str) -> dict:
    rt = dict(TRAIN_KW, dataset_kwargs={"path": corpus}, fsdp=FSDP,
              steps=steps, metrics_path=metrics_path)
    if interval is not None:
        rt["checkpoint"] = {
            "dir": os.path.join(os.path.dirname(metrics_path),
                                "ck-" + os.path.basename(metrics_path)),
            "interval": interval,
        }
    return rt


def _base_spec(runtime: dict) -> dict:
    return {
        "replicas": 1, "devices_per_proc": FSDP,
        "cpu_devices_per_proc": FSDP, "restart_policy": "OnFailure",
        "runtime": runtime,
    }


def _run_job(client, name: str, spec: dict, *, schedule: dict | None,
             timeout_s: float) -> dict:
    """Submit one job, optionally drive the fault schedule against it,
    and block to a terminal phase. Returns wall time + observability."""
    monitor = _StepMonitor(spec["runtime"]["metrics_path"])
    monitor.start()
    t0 = time.monotonic()
    client.submit_jaxjob(name, spec)
    driver = None
    if schedule is not None:
        driver = _FaultDriver(client, name, monitor, schedule,
                              timeout_s=timeout_s)
        driver.start()
    phase = client.wait_for_phase(name, timeout=timeout_s, poll=0.2)
    wall = time.monotonic() - t0
    if driver is not None:
        driver.join(timeout=schedule["stall_s"] + 10)
    monitor.stop()
    monitor.join(timeout=5)
    status = client.get("JAXJob", name)["status"]
    ctl_events = client.events(name)["events"]
    return {
        "phase": phase,
        "wall_s": round(wall, 2),
        "status": status,
        "ctl_events": ctl_events,
        "jsonl_events": monitor.snapshot_events(),
        "fired": driver.snapshot_fired() if driver else [],
    }


def _acked_steps(ctl_events: list[dict], before_unix: float) -> list[int]:
    """Steps the trainer durably acked via CheckpointSaved before
    `before_unix` (the trainer defers the ack one save boundary, so an
    acked step is known committed — never a torn write)."""
    out = []
    for e in ctl_events:
        if e["reason"] != "CheckpointSaved":
            continue
        if e["unix"] > before_unix:
            continue
        try:
            out.append(int(e["message"].split()[-1]))
        except (ValueError, IndexError):
            pass
    return sorted(out)


def _summarize(run: dict, steps: int, kill_step: int | None) -> dict:
    ev = run["jsonl_events"]
    restored = [e for e in ev if e.get("event") == "restored"]
    resharded = [e for e in ev if e.get("event") == "resharded"]
    restored_step = int(restored[-1]["step"]) if restored else 0
    # Useful steps = distinct steps of the final trajectory; redone =
    # work the schedule forced the arm to repeat.
    redone = max(0, (kill_step or 0) - restored_step) if kill_step \
        else 0
    kills = [f for f in run["fired"] if f["action"] == "kill"]
    kill_unix = None
    downs = [e for e in run["ctl_events"]
             if e["reason"] == "ElasticDownsize"]
    if downs:
        kill_unix = downs[0]["unix"]
    acked = _acked_steps(run["ctl_events"],
                         kill_unix if kill_unix is not None
                         else float("inf"))
    pre_kill_acked = [s for s in acked
                      if kill_step is None or s <= kill_step]
    return {
        "phase": run["phase"],
        "wall_s": run["wall_s"],
        "final_step": steps if run["phase"] == "Succeeded" else
        max((int(e["step"]) for e in ev), default=0),
        "goodput_steps_per_s": round(steps / run["wall_s"], 4),
        "restarts": int(run["status"].get("restarts", 0)),
        "effective_fsdp_final": run["status"].get("effectiveFsdp"),
        "kill_fired": kills[0] if kills else None,
        "stalls_fired": [f for f in run["fired"]
                         if f["action"] == "stall"],
        "restored_step": restored_step if restored else None,
        "resharded": [{"from": int(e["from_fsdp"]),
                       "to": int(e["to_fsdp"]),
                       "step": int(e["step"])} for e in resharded],
        "redone_steps": redone,
        "acked_checkpoints_before_kill": pre_kill_acked,
        # Only meaningful when a kill happened AND a restore ran: an
        # un-killed arm loses nothing, a no-checkpoint arm acks nothing.
        "lost_acked_checkpoints": ([s for s in pre_kill_acked
                                    if s > restored_step]
                                   if kill_step and restored else []),
        "resize_events": [e["message"] for e in downs],
    }


# -- entrypoint -------------------------------------------------------------


def run_trainchaos(quick: bool = False, seed: int = 0,
                   workdir: str | None = None) -> dict:
    import shutil
    import tempfile

    from kubeflow_tpu.controlplane.client import Client, start_controlplane

    # Full mode is long enough that the restart arm's redone prefix
    # (~0.55-0.70 of the run) dwarfs the symmetric per-attempt
    # overheads — on a CPU mesh the downsized topology is actually
    # FASTER per step (fewer fake devices = less sharding overhead, the
    # physical CPU is the same), so redone work is the ONLY cost the
    # kill imposes and the prefix has to be long to measure it; quick
    # mode only shakes out the mechanism chain.
    steps = 12 if quick else 48
    interval = 2 if quick else 4
    timeout_s = 600.0 if quick else 1200.0
    sched = make_schedule(seed, steps, interval)

    base = workdir or tempfile.mkdtemp(prefix="tpk-trainchaos-")
    own_dir = workdir is None
    os.makedirs(base, exist_ok=True)
    # NO persistent XLA compile cache: on this jaxlib, a post-resize
    # attempt that loads a cache entry written at the other topology
    # segfaults natively in cache deserialization (reproduced 3/3 with
    # the cache, 0/3 without) — the controller then reads the SIGSEGV
    # as one more worker death and downsizes AGAIN. Workers inherit env
    # through the controller, so scrub it here. Compile cost stays fair
    # without warm caches: every arm compiles 4-way at launch, and
    # elastic and restart-from-scratch each pay exactly one 2-way
    # recompile after the (identical) downsize.
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    corpus = os.path.join(base, "corpus.npy")
    np.save(corpus, np.random.default_rng(seed + 11).integers(
        0, 64, 200000, dtype=np.int32))

    sock = os.path.join(base, "cp.sock")
    work = os.path.join(base, "work")
    proc = start_controlplane(sock, work)
    # Generous socket timeout: harness gets are cheap reads, but a CI
    # host under the arms' own CPU load can stall the event loop well
    # past a tight budget, and a timed-out poll aborts the whole bench.
    client = Client(sock, timeout=60)
    result: dict = {
        "metric": "trainchaos",
        "mode": "real-trainer-subprocess-controlplane",
        "note": ("workers are the REAL trainer (tiny llama, fp32, CPU "
                 "mesh) in their OWN subprocesses, launched and "
                 "relaunched by the REAL tpk-controlplane binary, so "
                 "SIGKILL/SIGSTOP and the elastic resize are the real "
                 "thing; absolute rates are 1-CPU tiny-model numbers — "
                 "the artifact is the mechanism claims (resize chain "
                 "observed, zero lost acked checkpoints) and the arm "
                 "goodput deltas, computed from per-run provenance "
                 "(controller events + the worker's own JSONL stream)"),
        "params": {"steps": steps, "ckpt_interval": interval,
                   "fsdp": FSDP, "seed": seed, "quick": bool(quick),
                   "train_kw": {k: v for k, v in TRAIN_KW.items()
                                if k != "model_kwargs"}},
        "schedule": sched,
        "arms": {},
    }
    try:
        # Arm 1: fault-free control at the submitted topology.
        ctl = _run_job(
            client, "tc-control",
            _base_spec(_runtime(corpus, steps, interval,
                                os.path.join(base, "control.jsonl"))),
            schedule=None, timeout_s=timeout_s)
        result["arms"]["control"] = _summarize(ctl, steps, None)

        # Arm 2: elastic — kill past backoff forces the 4 -> 2 resize;
        # the later stall is a straggler, not a death.
        el_spec = _base_spec(_runtime(
            corpus, steps, interval, os.path.join(base, "elastic.jsonl")))
        el_spec["backoff_limit"] = 0
        # upsize_cooldown_s >> arm runtime: the probe must not regrow
        # the gang mid-measurement.
        el_spec["elastic"] = {"min_fsdp": 1, "upsize_cooldown_s": 3600}
        el = _run_job(client, "tc-elastic", el_spec, schedule=sched,
                      timeout_s=timeout_s)
        result["arms"]["elastic"] = _summarize(el, steps,
                                               sched["kill_step"])

        # Arm 3: restart-from-scratch — same kill, same stall, same
        # elastic downsize (the capacity loss is the fault, identical
        # across arms), but NO checkpoint dir: the relaunch starts at
        # step 0 and redoes the whole pre-kill prefix at the degraded
        # topology. The elastic-vs-restart delta is therefore the
        # checkpoint-resume-with-reshard mechanism, nothing else.
        rs_spec = _base_spec(_runtime(
            corpus, steps, None, os.path.join(base, "restart.jsonl")))
        rs_spec["backoff_limit"] = 0
        rs_spec["elastic"] = {"min_fsdp": 1, "upsize_cooldown_s": 3600}
        rs = _run_job(client, "tc-restart", rs_spec, schedule=sched,
                      timeout_s=timeout_s)
        summary = _summarize(rs, steps, sched["kill_step"])
        # No checkpoint -> nothing restorable: the whole pre-kill
        # prefix is redone work.
        summary["redone_steps"] = sched["kill_step"]
        result["arms"]["restart_scratch"] = summary

        e, r = result["arms"]["elastic"], result["arms"]["restart_scratch"]
        result["claims"] = {
            "resize_event_observed": bool(e["resize_events"]),
            "resharded_observed": bool(e["resharded"]),
            "zero_lost_acked_checkpoints":
                e["lost_acked_checkpoints"] == [],
            "goodput_elastic_over_restart": round(
                e["goodput_steps_per_s"]
                / max(r["goodput_steps_per_s"], 1e-9), 3),
        }
        return result
    finally:
        client.close()
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        if own_dir:
            shutil.rmtree(base, ignore_errors=True)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="tpk-trainchaos")
    p.add_argument("--quick", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    out = run_trainchaos(quick=args.quick, seed=args.seed)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
