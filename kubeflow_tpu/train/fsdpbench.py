"""Sharded-training A/B harness (`python bench.py --train-fsdp`).

The claims the fsdp runtime makes (ISSUE 15) are mechanism claims, so —
like the serve benches — the harness runs REAL train steps through the
production factories (init_train_state / make_train_step with an
parallel/fsdp.FSDP plan) and records both the equivalence and the layout
arithmetic:

  * `equivalence`: replicated (mesh data=N) vs fsdp master layout
    (mesh fsdp=N, exact escape hatch) on the SAME seeded batch stream —
    per-step loss trajectories and the max relative delta (fp32 compute,
    so the only residual is cross-layout reduction order, ~1e-7);
    plus grad_accum=K on the same global batch vs K=1.
  * `memory`: param/opt-state bytes per chip from the actual shardings
    (the tpk_train_*_bytes_per_chip arithmetic) — the fsdp arm must
    divide the replicated arm by the shard degree.
  * `bf16` arm: param_dtype="bfloat16" gathered compute copies — same
    master bytes, loss finite (numeric delta reported, never hidden).
  * step wall-clock per arm. On CPU these are MECHANISM numbers (the
    harness shape); the chip measurement is recorded skipped-with-reason
    while the tunnel is down (pipelined_vs_sync convention, BENCH_r05).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any


def _arm(model, mesh, rules, batches, *, fsdp_plan=None, accum=1,
         timed_from=2):
    """One A/B arm: init + step the shared batch stream; returns losses,
    per-chip state bytes, and ms/step over the steady-state window."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.parallel.fsdp import tree_bytes_per_device
    from kubeflow_tpu.train.step import init_train_state, make_train_step

    batch, seq = batches[0]["inputs"].shape
    tx = optax.adamw(1e-3)
    state = init_train_state(
        model, tx, jax.random.key(0),
        (jnp.zeros((batch, seq), jnp.int32),), mesh,
        rules, fsdp=fsdp_plan)
    step = make_train_step(model, mesh, rules, fsdp=fsdp_plan,
                           accum_steps=accum)
    losses = []
    t0 = None
    m = None
    for i, b in enumerate(batches):
        if i == timed_from:
            if m is not None:
                # Drain the warmup dispatches BEFORE the clock opens —
                # queued warmup compute must not be charged to the
                # timed window (PROFILE §1 fetch-sync hygiene).
                float(m["loss"])
            t0 = time.perf_counter()
        state, m = step(state, b)
        losses.append(m["loss"])
    losses = [float(x) for x in losses]  # one sync closes the clock
    wall = time.perf_counter() - (t0 if t0 is not None else time.perf_counter())
    timed = max(len(batches) - timed_from, 1)
    return {
        # Full precision: the equivalence deltas are computed FROM these
        # — display rounding would quantize ~1e-7 deltas to 0.0.
        "losses": losses,
        "final_loss": round(losses[-1], 6),
        "ms_per_step": round(wall / timed * 1e3, 2),
        "param_bytes_per_chip": tree_bytes_per_device(state.params),
        "opt_state_bytes_per_chip": tree_bytes_per_device(state.opt_state),
    }


def _rel_delta(a: list[float], b: list[float]) -> float:
    return max(abs(x - y) / max(abs(x), 1e-9) for x, y in zip(a, b))


def run_trainbench(quick: bool = False) -> dict[str, Any]:
    """The A/B rows. Shard degree adapts to the device count (1 chip
    degenerates to degree 1 — the harness still proves the mechanism
    shape; the CPU tier runs it at 4)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.llama import Llama, llama_tiny
    from kubeflow_tpu.parallel.fsdp import FSDP
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES

    devices = jax.devices()
    degree = 1
    for cand in (4, 2):
        if len(devices) % cand == 0 and len(devices) >= cand:
            degree = cand
            break
    devices = devices[:degree]

    # fp32 compute: the equivalence rows measure LAYOUT-induced deltas;
    # bf16 rounding would drown them (the bf16 arm is separate).
    cfg = dataclasses.replace(llama_tiny(), num_layers=2,
                              dtype=jnp.float32)
    model = Llama(cfg)
    batch, seq = 8, 16
    steps = 4 if quick else 8
    rng = np.random.default_rng(0)
    batches = [
        {"inputs": rng.integers(0, cfg.vocab_size, (batch, seq),
                                dtype=np.int32),
         "targets": rng.integers(0, cfg.vocab_size, (batch, seq),
                                 dtype=np.int32)}
        for _ in range(steps)
    ]

    mesh_repl = build_mesh(MeshConfig(data=degree), devices)
    mesh_fsdp = build_mesh(MeshConfig(data=1, fsdp=degree), devices)

    repl = _arm(model, mesh_repl, DEFAULT_RULES, batches)
    fsdp = _arm(model, mesh_fsdp, DEFAULT_RULES, batches,
                fsdp_plan=FSDP(mesh_fsdp))
    accum = _arm(model, mesh_fsdp, DEFAULT_RULES, batches,
                 fsdp_plan=FSDP(mesh_fsdp), accum=2)
    bf16 = _arm(model, mesh_fsdp, DEFAULT_RULES, batches,
                fsdp_plan=FSDP(mesh_fsdp,
                               compute_dtype=jnp.bfloat16))

    result = {
        "method": (
            "real init_train_state/make_train_step arms over one seeded "
            "batch stream; fp32 compute so equivalence rows see only "
            "layout-induced reduction order; clock opened after 2 "
            "warmup steps, closed by the final loss fetch"),
        "model": "llama_tiny(layers=2, fp32)",
        "shard_degree": degree,
        "global_batch": batch,
        "seq_len": seq,
        "timed_steps": steps - 2,
        "replicated": repl,
        "fsdp_master": fsdp,
        "fsdp_grad_accum2": accum,
        "fsdp_bf16_compute": bf16,
        "equivalence": {
            "fsdp_vs_replicated_max_rel_delta": _rel_delta(
                repl["losses"], fsdp["losses"]),
            "grad_accum2_vs_1_max_rel_delta": _rel_delta(
                fsdp["losses"], accum["losses"]),
            "bf16_vs_fp32_max_rel_delta": _rel_delta(
                fsdp["losses"], bf16["losses"]),
        },
        "memory": {
            "opt_state_ratio_replicated_over_fsdp": round(
                repl["opt_state_bytes_per_chip"]
                / max(fsdp["opt_state_bytes_per_chip"], 1), 4),
            "param_ratio_replicated_over_fsdp": round(
                repl["param_bytes_per_chip"]
                / max(fsdp["param_bytes_per_chip"], 1), 4),
        },
    }
    return result
