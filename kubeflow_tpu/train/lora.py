"""LoRA fine-tuning utilities — the reference SDK's PEFT LoraConfig
(⟨kubeflow training SDK: train(..., LoraConfig)⟩; SURVEY.md §2.1 train
API), TPU-shaped.

The model side lives in models/llama.py (`cfg.lora_rank` adds
`*_lora_a`/`*_lora_b` leaves to the target projections; B zero-init so
step 0 equals the base). This module owns the tree plumbing:

  * `partition(params)` — split the tree into (trainable adapters, frozen
    base) flat dicts. The train step differentiates ONLY the adapter
    subtree and the optimizer state covers ONLY adapters — that's the
    LoRA memory win (no fp32 grads / Adam moments for the base, which
    dominate the full-fine-tune HBM budget).
  * `merge(params, cfg)` — fold every adapter pair into its base kernel
    (W += alpha/r * A @ B, cast back to the kernel dtype) and STRIP the
    lora leaves: the result is a standard base-model tree any serving
    path loads with zero engine changes.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import traverse_util


def is_lora_path(path: tuple) -> bool:
    return any("_lora_" in str(p) for p in path)


def partition(params: Any) -> tuple[dict, dict]:
    """params (nested dict) -> (trainable, frozen) NESTED sub-trees.

    Nested (string-keyed) rather than flat tuple-keyed dicts on purpose:
    the trainable tree becomes the optimizer-state target and rides
    through orbax checkpointing, whose name-based tree serialization
    expects ordinary nested containers."""
    flat = traverse_util.flatten_dict(params)
    train = {k: v for k, v in flat.items() if is_lora_path(k)}
    frozen = {k: v for k, v in flat.items() if not is_lora_path(k)}
    if not train:
        raise ValueError(
            "no *_lora_* parameters found — build the model with "
            "lora_rank > 0")
    return (traverse_util.unflatten_dict(train),
            traverse_util.unflatten_dict(frozen))


def combine(train: Any, frozen: Any) -> Any:
    return traverse_util.unflatten_dict(
        {**traverse_util.flatten_dict(frozen),
         **traverse_util.flatten_dict(train)})


def merge(params: Any, cfg: Any) -> Any:
    """Fold adapters into base kernels and strip lora leaves. Exact math:
    the adapted forward computes x@W + (x@A)@B * s, and the merged kernel
    W + s * reshape(A@B) reproduces it (contraction over the rank dim is
    associative); verified against the adapted model in
    tests/test_lora.py."""
    scale = cfg.lora_alpha / cfg.lora_rank
    scanned = bool(getattr(cfg, "scan_layers", True))
    flat = traverse_util.flatten_dict(params)
    out = {k: v for k, v in flat.items() if not is_lora_path(k)}
    r = cfg.lora_rank
    for k in flat:
        if not str(k[-1]).endswith("_lora_a"):
            continue
        base_name = str(k[-1])[: -len("_lora_a")]
        bk = k[:-1] + (f"{base_name}_lora_b",)
        kernel_key = k[:-1] + (base_name, "kernel")
        a = np.asarray(flat[k], np.float32)     # [(L,) *in, r]
        b = np.asarray(flat[bk], np.float32)    # [(L,) r, *out]
        w = np.asarray(out[kernel_key])         # [(L,) *in, *out]
        if scanned:
            L = a.shape[0]
            delta = np.matmul(a.reshape(L, -1, r), b.reshape(L, r, -1))
        else:
            delta = np.matmul(a.reshape(-1, r), b.reshape(r, -1))
        merged = (w.astype(np.float32)
                  + scale * delta.reshape(w.shape))
        out[kernel_key] = jnp.asarray(merged.astype(w.dtype))
    return traverse_util.unflatten_dict(out)
