"""Task launcher — the per-step executor inside the worker process.

The KFP v2 launcher analog (⟨pipelines: backend/src/v2/component — launcher⟩,
SURVEY.md §2.4/§3.5): the C++ pipeline controller resolves a task's inputs
and writes a task-spec JSON; this process materializes output directories,
runs the user step (packaged python function or raw command with
placeholders), and exits 0 only if every declared output was produced.
Artifact upload/download collapses to filesystem paths (local artifact
store); lineage recording stays in the controller, which digests the
outputs on success.

Task spec:
    {"component": {...component IR...},
     "params":  {"n": 100},                  # fully resolved values
     "inputs":  {"data": "/.../artifacts/preprocess/out"},
     "outputs": {"model": "/.../artifacts/train/model"}}
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


class LauncherError(RuntimeError):
    pass


def _resolve_placeholders(text: str, params: dict, inputs: dict,
                          outputs: dict) -> str:
    for key, val in params.items():
        if isinstance(val, (list, dict)):
            val = json.dumps(val)
        text = text.replace("{{params.%s}}" % key, str(val))
    for key, val in inputs.items():
        text = text.replace("{{inputs.%s}}" % key, val)
    for key, val in outputs.items():
        text = text.replace("{{outputs.%s}}" % key, val)
    return text


RESULT_OUTPUT = "__result__"  # implicit artifact carrying the return value


def _stage_collected(name: str, paths: list) -> str:
    """Materialize a fan-in input: a directory of numbered symlinks to the
    per-iteration artifacts, handed to the component as one path."""
    import tempfile

    stage = tempfile.mkdtemp(prefix=f"tpk-collect-{name}-")
    for i, p in enumerate(paths):
        if not os.path.exists(p):
            raise LauncherError(
                f"collected input {name!r}[{i}] missing at {p}")
        # Zero-padded so lexicographic listing preserves iteration order
        # past 10 items.
        os.symlink(os.path.abspath(p), os.path.join(stage, f"{i:05d}"))
    return stage


def run_task(spec: dict) -> None:
    comp = spec["component"]
    params = dict(comp.get("defaults") or {})
    params.update(spec.get("params") or {})
    inputs = spec.get("inputs") or {}
    outputs = spec.get("outputs") or {}

    for name, path in list(inputs.items()):
        if isinstance(path, list):  # Collected fan-in over loop iterations
            inputs[name] = _stage_collected(name, path)
        elif not os.path.exists(path):
            raise LauncherError(f"input artifact {name!r} missing at {path}")
    for path in outputs.values():
        os.makedirs(path, exist_ok=True)
    result_dir = outputs.pop(RESULT_OUTPUT, None)

    kind = comp.get("kind", "python")
    if kind == "python":
        # Re-hydrate the Component by exec'ing its captured source with the
        # DSL names in scope, then call the underlying function with params
        # + artifact paths (the KFP "lightweight python component" flow).
        from kubeflow_tpu.pipelines import dsl

        scope = {"component": dsl.component,
                 "container_component": dsl.container_component,
                 "InputArtifact": dsl.InputArtifact,
                 "OutputArtifact": dsl.OutputArtifact}
        # dont_inherit: exec must not leak this module's `from __future__
        # import annotations` into the component (it would stringify the
        # signature annotations the DSL dispatches on).
        code = compile(comp["source"], f"<component {comp['name']}>",
                       "exec", dont_inherit=True)
        exec(code, scope)  # noqa: S102 — the source IS the step
        obj = scope.get(comp["name"])
        if isinstance(obj, dsl.Component):
            fn = obj.fn
        elif callable(obj):
            fn = obj
        else:
            raise LauncherError(
                f"component source did not define {comp['name']!r}")
        ret = fn(**params, **inputs, **outputs)
        if (comp.get("returns") and result_dir
                and int(os.environ.get("TPK_PROC_ID", "0")) == 0):
            # The return value is the task's output parameter — recorded
            # as a tiny artifact the controller reads back for
            # dsl.Condition / Collected consumers. Process 0 only: in a
            # multi-replica gang every process runs this code against the
            # same shared path, and concurrent writes could interleave
            # into invalid JSON.
            with open(os.path.join(result_dir, "value.json"), "w") as fh:
                json.dump(ret, fh)
    elif kind == "command":
        argv = [_resolve_placeholders(a, params, inputs, outputs)
                for a in comp.get("argv") or []]
        if not argv:
            raise LauncherError("command component has empty argv")
        env = dict(os.environ)
        cpu = env.get("TPK_CPU_DEVICES")
        if cpu:
            # jax config can't cross the process boundary; give the child
            # the env form of CPU test mode instead.
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"{env.get('XLA_FLAGS', '')} "
                f"--xla_force_host_platform_device_count={cpu}").strip()
        rc = subprocess.call(argv, env=env)
        if rc != 0:
            raise LauncherError(f"command exited {rc}: {argv}")
    else:
        raise LauncherError(f"unknown component kind {kind!r}")

    missing = [n for n, p in outputs.items()
               if not os.path.exists(p) or not os.listdir(p)]
    if missing:
        raise LauncherError(
            f"component {comp.get('name')!r} did not populate declared "
            f"outputs: {missing}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpk-launcher")
    ap.add_argument("--spec", required=True, help="task spec JSON path")
    args = ap.parse_args(argv)
    with open(args.spec) as fh:
        spec = json.load(fh)
    # The gang launcher signals CPU test mode via env (the argv form
    # belongs to the trainer entrypoint). Configure before a python
    # component body touches a jax backend; command components get the
    # env form injected at exec instead (no jax import paid here).
    cpu = os.environ.get("TPK_CPU_DEVICES")
    if cpu and spec.get("component", {}).get("kind", "python") == "python":
        # Shared helper: covers jax >= 0.5 (jax_num_cpu_devices) AND
        # older jax (XLA_FLAGS) — a raw config update crashes the
        # component body on old-jax environments.
        from kubeflow_tpu.utils.devices import force_cpu_device_count

        force_cpu_device_count(int(cpu))
    try:
        run_task(spec)
    except Exception as e:
        print(f"launcher: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
