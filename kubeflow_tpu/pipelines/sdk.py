"""Pipelines SDK — kfp.Client parity (⟨pipelines: sdk/python/kfp — client⟩,
SURVEY.md §2.4): upload compiled pipelines, create runs, wait, inspect task
states and artifact paths, all against the control plane's API server."""

from __future__ import annotations

import time
from typing import Any

from kubeflow_tpu.controlplane.client import Client
from kubeflow_tpu.pipelines.dsl import Pipeline, compile_pipeline


class PipelineClient:
    def __init__(self, client: Client):
        self.client = client

    def create_pipeline(self, name: str, pipeline: Pipeline | dict,
                        **params: Any) -> dict:
        """Uploads a compiled pipeline (Pipeline object or IR dict)."""
        ir = (compile_pipeline(pipeline, **params)
              if isinstance(pipeline, Pipeline) else pipeline)
        return self.client.create("Pipeline", name, ir)

    def create_run(self, name: str, *, pipeline: str | Pipeline | dict,
                   params: dict | None = None) -> dict:
        """Starts a run of a named pipeline (str) or an inline one."""
        spec: dict[str, Any] = {"params": params or {}}
        if isinstance(pipeline, str):
            spec["pipeline"] = pipeline
        elif isinstance(pipeline, Pipeline):
            # Inline compile: defaults must exist; run-time overrides ride
            # in spec.params like the named-pipeline path.
            spec["pipeline_spec"] = compile_pipeline(pipeline)
        else:
            spec["pipeline_spec"] = pipeline
        return self.client.create("PipelineRun", name, spec)

    def get_run(self, name: str) -> dict:
        return self.client.get("PipelineRun", name)

    def wait(self, name: str, timeout: float = 600.0,
             poll: float = 0.5) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            phase = self.get_run(name).get("status", {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                return phase
            time.sleep(poll)
        raise TimeoutError(
            f"run {name} still "
            f"{self.get_run(name).get('status', {}).get('phase')!r} after "
            f"{timeout}s")

    def tasks(self, name: str) -> dict[str, dict]:
        """Task name → {phase, outputs, digests, fingerprint, ...}."""
        return self.get_run(name).get("status", {}).get("tasks", {})

    def artifacts(self, name: str, task: str) -> dict[str, str]:
        """Output name → artifact directory path for a completed task."""
        return self.tasks(name).get(task, {}).get("outputs", {})
