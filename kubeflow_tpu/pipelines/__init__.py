"""Pipelines — the KFP-equivalent subsystem (SURVEY.md §2.4).

Layout:
  dsl.py      — @component / @pipeline / container_component authoring API,
                plus compile_pipeline(): DAG trace → IR JSON (PipelineSpec
                analog)
  launcher.py — per-step executor run inside worker processes
  sdk.py      — PipelineClient: create/run/wait against the control plane

The PipelineRun DAG driver, content-hash step cache, and lineage store
(MLMD stand-in) live in the C++ control plane (cpp/pipelines.cc).
"""

from kubeflow_tpu.pipelines.dsl import (  # noqa: F401
    Collected,
    Component,
    Condition,
    ExitHandler,
    InputArtifact,
    OutputArtifact,
    ParallelFor,
    Pipeline,
    PipelineError,
    compile_pipeline,
    component,
    container_component,
    pipeline,
)
