"""Pipeline DSL — the KFP v2 authoring surface (⟨pipelines: sdk/python/kfp —
dsl⟩, SURVEY.md §2.4/§3.5).

`@component` wraps a self-contained Python function; `@pipeline` wraps a
function that calls components to build a DAG. `compile_pipeline()` emits
the IR (the PipelineSpec-proto analog, here plain JSON) that the C++
pipeline controller executes. Artifacts flow by path: a component declares
`InputArtifact` / `OutputArtifact` parameters, the launcher hands it real
filesystem paths at run time.

    @component
    def preprocess(out: OutputArtifact, n: int = 100):
        ...write files under `out`...

    @component
    def train(data: InputArtifact, model: OutputArtifact, lr: float = 0.1):
        ...

    @pipeline
    def demo(n: int = 100, lr: float = 0.1):
        p = preprocess(n=n)
        train(data=p.output("out"), lr=lr)

    ir = compile_pipeline(demo)
"""

from __future__ import annotations

import inspect
import textwrap
import threading
import typing
from typing import Any, Callable


class PipelineError(ValueError):
    pass


class InputArtifact:
    """Annotation marker: parameter receives the path of an upstream
    artifact."""


class OutputArtifact:
    """Annotation marker: parameter receives a fresh directory path the
    component must populate."""


_PARAM_TYPES = {int: "int", float: "double", str: "string", bool: "bool"}


class ParamRef:
    """Reference to a pipeline-level parameter."""

    def __init__(self, name: str):
        self.name = name


class OutputRef:
    """Reference to a task's output artifact."""

    def __init__(self, task: "Task", output: str):
        self.task = task
        self.output = output


class Task:
    def __init__(self, name: str, component: "Component",
                 arguments: dict[str, Any]):
        self.name = name
        self.component = component
        self.arguments = arguments
        self.after: list[Task] = []

    def output(self, name: str) -> OutputRef:
        if name not in self.component.outputs:
            raise PipelineError(
                f"component {self.component.name!r} has no output {name!r}; "
                f"declared outputs: {self.component.outputs}")
        return OutputRef(self, name)

    @property
    def outputs(self) -> dict[str, OutputRef]:
        return {o: OutputRef(self, o) for o in self.component.outputs}

    def after_task(self, *tasks: "Task") -> "Task":
        """Explicit ordering edge with no data dependency (dsl .after())."""
        self.after.extend(tasks)
        return self


class _PipelineContext(threading.local):
    def __init__(self):
        self.tasks: list[Task] | None = None


_ctx = _PipelineContext()


class Component:
    """A packaged python-function step (KFP lightweight component), or a
    raw-command step when built via `container_component` (KFP container
    component analog)."""

    def __init__(self, fn: Callable | None, replicas: int = 1,
                 cpu_devices_per_proc: int = 0, cache: bool = True):
        self.fn = fn
        self.replicas = replicas
        self.cpu_devices_per_proc = cpu_devices_per_proc
        self.cache = cache
        self.kind = "python"
        self.argv: list[str] = []
        self.params: dict[str, str] = {}      # name -> type
        self.defaults: dict[str, Any] = {}
        self.inputs: list[str] = []           # InputArtifact params
        self.outputs: list[str] = []          # OutputArtifact params
        if fn is None:       # container_component fills the fields itself
            self.name = ""
            self.source = ""
            return
        self.name = fn.__name__
        try:
            self.source = textwrap.dedent(inspect.getsource(fn))
        except OSError:
            # No retrievable source (REPL, or the launcher re-exec'ing a
            # packaged component). Such a Component can run but not be
            # re-compiled into IR — to_ir() enforces that.
            self.source = ""

        # get_type_hints resolves string annotations (files using
        # `from __future__ import annotations`) against fn's globals.
        try:
            hints = typing.get_type_hints(fn)
        except Exception:
            hints = {}
        sig = inspect.signature(fn)
        for pname, p in sig.parameters.items():
            ann = hints.get(pname, p.annotation)
            if ann is InputArtifact:
                self.inputs.append(pname)
            elif ann is OutputArtifact:
                self.outputs.append(pname)
            elif ann in _PARAM_TYPES:
                self.params[pname] = _PARAM_TYPES[ann]
                if p.default is not inspect.Parameter.empty:
                    self.defaults[pname] = p.default
            else:
                raise PipelineError(
                    f"component {self.name!r} parameter {pname!r} needs an "
                    f"annotation: int/float/str/bool, InputArtifact, or "
                    f"OutputArtifact")

    def __call__(self, **arguments: Any) -> Task:
        if _ctx.tasks is None:
            raise PipelineError(
                f"component {self.name!r} called outside a @pipeline "
                f"function")
        for k, v in arguments.items():
            if k in self.inputs:
                if not isinstance(v, OutputRef):
                    raise PipelineError(
                        f"{self.name}.{k} is an InputArtifact; pass "
                        f"task.output(...)")
            elif k in self.params:
                if isinstance(v, OutputRef):
                    raise PipelineError(
                        f"{self.name}.{k} is a parameter; got an artifact")
            elif k in self.outputs:
                raise PipelineError(
                    f"{self.name}.{k} is an OutputArtifact; it is produced, "
                    f"not passed")
            else:
                raise PipelineError(
                    f"component {self.name!r} has no parameter {k!r}")
        missing = [i for i in self.inputs if i not in arguments]
        if missing:
            raise PipelineError(
                f"component {self.name!r} missing input artifacts: {missing}")
        # Required params (no default) must be bound now — catching this at
        # compile time beats burning a gang on a TypeError in the launcher.
        unbound = [p for p in self.params
                   if p not in arguments and p not in self.defaults]
        if unbound:
            raise PipelineError(
                f"component {self.name!r} missing required params: {unbound}")
        # Unique task name within the pipeline: name, name-2, name-3, ...
        base = self.name
        existing = {t.name for t in _ctx.tasks}
        name, i = base, 1
        while name in existing:
            i += 1
            name = f"{base}-{i}"
        task = Task(name, self, arguments)
        _ctx.tasks.append(task)
        return task

    def to_ir(self) -> dict:
        if self.kind == "python" and not self.source:
            raise PipelineError(
                f"component {self.name!r} has no retrievable source (was it "
                f"defined in a REPL?); define it in a file")
        return {
            "name": self.name,
            "kind": self.kind,
            "source": self.source,
            "argv": list(self.argv),
            "params": dict(self.params),
            "defaults": dict(self.defaults),
            "inputs": list(self.inputs),
            "outputs": list(self.outputs),
            "replicas": self.replicas,
            "cpu_devices_per_proc": self.cpu_devices_per_proc,
            "cache": self.cache,
        }


def component(fn: Callable | None = None, *, replicas: int = 1,
              cpu_devices_per_proc: int = 0, cache: bool = True):
    """Decorator: python function → Component (KFP @dsl.component)."""
    def wrap(f: Callable) -> Component:
        return Component(f, replicas=replicas,
                         cpu_devices_per_proc=cpu_devices_per_proc,
                         cache=cache)
    return wrap(fn) if fn is not None else wrap


def container_component(name: str, argv: list[str], *,
                        params: dict[str, type] | None = None,
                        defaults: dict[str, Any] | None = None,
                        inputs: list[str] | None = None,
                        outputs: list[str] | None = None,
                        cache: bool = True) -> Component:
    """Raw-command step. `argv` may use `{{params.x}}`, `{{inputs.a}}`,
    `{{outputs.b}}` placeholders, resolved by the launcher at run time."""
    c = Component(None, cache=cache)
    c.kind = "command"
    c.name = name
    c.argv = list(argv)
    c.params = {k: _PARAM_TYPES[t] for k, t in (params or {}).items()}
    c.defaults = dict(defaults or {})
    c.inputs = list(inputs or [])
    c.outputs = list(outputs or [])
    return c


class Pipeline:
    def __init__(self, fn: Callable):
        self.fn = fn
        self.name = fn.__name__
        self.params: dict[str, Any] = {}
        try:  # resolve PEP-563 string annotations like Component does
            hints = typing.get_type_hints(fn)
        except Exception:
            hints = {}
        sig = inspect.signature(fn)
        for pname, p in sig.parameters.items():
            if hints.get(pname, p.annotation) not in _PARAM_TYPES:
                raise PipelineError(
                    f"pipeline {self.name!r} parameter {pname!r} needs an "
                    f"int/float/str/bool annotation")
            self.params[pname] = (None if p.default is
                                  inspect.Parameter.empty else p.default)


def pipeline(fn: Callable) -> Pipeline:
    """Decorator: DAG-building function → Pipeline (KFP @dsl.pipeline)."""
    return Pipeline(fn)


def _arg_ir(value: Any) -> dict:
    if isinstance(value, ParamRef):
        return {"param": value.name}
    if isinstance(value, OutputRef):
        return {"task": value.task.name, "output": value.output}
    if isinstance(value, (int, float, str, bool)):
        return {"value": value}
    raise PipelineError(f"unsupported argument value: {value!r}")


def compile_pipeline(p: Pipeline, **param_overrides: Any) -> dict:
    """Traces the pipeline function and emits the IR document.

    The KFP compiler analog (⟨pipelines: sdk/python/kfp/compiler⟩): tasks
    carry their full component spec (self-contained IR — no registry
    lookups at run time), arguments reference literals, pipeline params, or
    upstream outputs; `depends_on` holds explicit .after() edges (data
    edges are implied by arguments and recomputed by the controller).
    """
    params = dict(p.params)
    for k, v in param_overrides.items():
        if k not in params:
            raise PipelineError(f"pipeline {p.name!r} has no param {k!r}")
        params[k] = v
    missing = [k for k, v in params.items() if v is None]
    if missing:
        raise PipelineError(
            f"pipeline {p.name!r} params need values: {missing}")

    if _ctx.tasks is not None:
        raise PipelineError("nested pipeline compilation is not supported")
    _ctx.tasks = []
    try:
        p.fn(**{k: ParamRef(k) for k in params})
        tasks = _ctx.tasks
    finally:
        _ctx.tasks = None

    if not tasks:
        raise PipelineError(f"pipeline {p.name!r} has no tasks")

    ir_tasks: dict[str, dict] = {}
    for t in tasks:
        args = {k: _arg_ir(v) for k, v in t.arguments.items()}
        # Unpassed params fall back to component defaults at launch time.
        ir_tasks[t.name] = {
            "component": t.component.to_ir(),
            "arguments": args,
            "depends_on": sorted({a.name for a in t.after}),
        }
    return {
        "schema": "tpk-pipeline/v1",
        "name": p.name,
        "params": params,
        "tasks": ir_tasks,
    }
